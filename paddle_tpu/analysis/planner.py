"""paddle_tpu.analysis.planner — auto-sharding planner.

Closes the cost-model loop the HLO audit opened: PR 4 could *score* a
sharding (collective wire census through ``costmodel`` + liveness
peak-memory vs an HBM budget) but a human still picked dp/tp/pp by
hand and discovered mistakes at OOM or at the step-time cliff.  The
planner enumerates candidate mesh shapes (dp/tp/pp factorizations of
the chip count, including 2D/3D layouts) and PartitionSpec
assignments for the parameters, lowers every candidate through the
SPMD partitioner — ``jax.jit(...).lower().compile()`` only, abstract
shapes, no device execution, works on forced virtual CPU devices —
and ranks them by a per-global-batch step estimate:

    score_us = compute_us + collective_us

* ``collective_us`` — the torus-decomposed alpha+beta census of the
  compiled module (``hlo.collective_census`` over
  ``costmodel.torus_cost``), optionally re-anchored by a measured
  ``Calibration`` table;
* ``compute_us`` — a per-device roofline floor from the SAME
  compiled module: max(dot/conv FLOPs / peak_tflops, non-alias
  buffer bytes / hbm_gbps).  This is what keeps "replicate
  everything, communicate nothing" from winning: an unpartitioned
  batch costs full-batch compute on every device.

Candidates whose liveness peak exceeds the HBM budget rank behind
every fitting plan; when NOTHING fits, the planner re-lowers the
closest misses with remat (``jax.checkpoint`` around the forward) and
with the batch halved, and returns those as explicit fallback plans.

Pipeline (pp>1) candidates are scored semi-analytically: the dp×tp
stage group is lowered for real (chips/pp devices), then optimizer
state is divided by pp and the 1F1B microbatch boundary transfers are
added as collective-permute cost.  Such plans carry
``scored_via='pp-model'`` so consumers know the number came from the
model, not a lowering of the actual pipelined step.

Surfaces: ``tpu_lint --plan --chips N [--hbm-gb G]`` (ranked table +
``--json`` schema) and ``ParallelTrainer(auto_shard=True)`` (applies
the winner and emits a ``plan_selected`` telemetry event that
``tools/run_report.py`` joins against the observed collective
census).
"""
import math
import re

from . import costmodel
from . import hlo as _hlo
from . import targets as _targets

__all__ = ['ShardingPlan', 'PlanResult', 'enumerate_meshes',
           'assignments_for', 'plan_model', 'DEFAULT_PEAK_TFLOPS',
           'DEFAULT_HBM_GBPS']

# per-device roofline knobs for the compute floor (v5p-class order of
# magnitude; thresholds / calibration override them — the point is the
# MODEL SHAPE, not chip-generation precision)
DEFAULT_PEAK_TFLOPS = 200.0
DEFAULT_HBM_GBPS = 1200.0

# every candidate is a full trace+lower+XLA-compile: at 256 chips the
# dp/tp/pp enumeration alone is ~45 meshes x up to 3 assignments, so an
# uncapped plan would burn tens of minutes of CPU compile.  The cap is
# never silent — PlanResult.enumerated records what the cap dropped and
# render()/to_json() surface it.
DEFAULT_MAX_CANDIDATES = 32

class ShardingPlan:
    """One scored (mesh, PartitionSpec-assignment) candidate."""

    __slots__ = ('mesh_axes', 'assignment', 'param_specs', 'batch_axis',
                 'wire_bytes', 'est_us', 'compute_us', 'score_us',
                 'peak_bytes', 'phases', 'fits', 'scored_via',
                 'remat', 'batch_scale', 'census', 'notes', 'rank',
                 'quant')

    def __init__(self, mesh_axes, assignment, param_specs=None,
                 batch_axis='dp'):
        self.mesh_axes = dict(mesh_axes)
        self.assignment = assignment
        self.param_specs = dict(param_specs or {})
        self.batch_axis = batch_axis
        self.wire_bytes = 0
        self.est_us = 0.0
        self.compute_us = 0.0
        self.score_us = 0.0
        self.peak_bytes = 0
        self.phases = 0
        self.fits = True
        self.scored_via = 'hlo'
        self.remat = False
        self.batch_scale = 1.0
        self.census = {}
        self.notes = []
        self.rank = None
        # wire-dtype what-if: when the full-width grad all-reduce
        # dominates this plan's estimate, the predicted numbers of
        # re-wiring it at int8 (quantized_allreduce_cost) land here —
        # {'wire_dtype', 'wire_bytes', 'est_us', 'score_us',
        #  'saved_us'} — and the planner RECOMMENDS
        # quant_collectives='int8'
        self.quant = None

    @property
    def fallback(self):
        """'remat' / 'half-batch' when this is a budget-fallback plan,
        else None."""
        if self.remat:
            return 'remat'
        if self.batch_scale < 1.0:
            return 'half-batch'
        return None

    def mesh_str(self):
        return ','.join(f'{a}={s}' for a, s in self.mesh_axes.items()
                        if s > 1) or '1 device'

    def describe(self):
        tag = self.fallback
        return (f'{self.mesh_str()} [{self.assignment}]'
                + (f' +{tag}' if tag else ''))

    def to_json(self):
        return {
            'mesh': {a: s for a, s in self.mesh_axes.items()},
            'assignment': self.assignment,
            'param_specs': {n: list(s) if s else []
                            for n, s in self.param_specs.items()},
            'batch_axis': self.batch_axis,
            'wire_bytes': self.wire_bytes,
            'est_us': self.est_us,
            'compute_us': self.compute_us,
            'score_us': self.score_us,
            'peak_bytes': self.peak_bytes,
            'phases': self.phases,
            'fits': self.fits,
            'scored_via': self.scored_via,
            'remat': self.remat,
            'batch_scale': self.batch_scale,
            'fallback': self.fallback,
            'notes': list(self.notes),
            'rank': self.rank,
            'quant': dict(self.quant) if self.quant else None,
        }

    def __repr__(self):
        return (f'ShardingPlan({self.describe()}, '
                f'score={self.score_us:.1f}us, '
                f'peak={self.peak_bytes / (1 << 20):.1f}MiB, '
                f'fits={self.fits})')


class PlanResult:
    """Ranked candidates + the winner (best plan under budget)."""

    def __init__(self, name, chips, hbm_bytes):
        self.name = name
        self.chips = chips
        self.hbm_bytes = hbm_bytes
        self.candidates = []   # ranked, best first
        self.fallbacks = []    # remat / half-batch plans (no-fit case)
        self.errors = {}       # candidate desc -> repr(exception)
        self.enumerated = 0    # candidates before the max_candidates
                               # cap (scored < enumerated = truncated)

    @property
    def winner(self):
        """Best plan that fits the budget: top-ranked fitting
        candidate, else the best fitting fallback, else None."""
        for p in self.candidates:
            if p.fits:
                return p
        for p in self.fallbacks:
            if p.fits:
                return p
        return None

    def rank(self):
        """Order: fitting plans by score, then over-budget ones by how
        badly they miss (peak ascending)."""
        self.candidates.sort(
            key=lambda p: (not p.fits, p.score_us, p.peak_bytes,
                           p.describe()))
        for i, p in enumerate(self.candidates):
            p.rank = i + 1
        self.fallbacks.sort(
            key=lambda p: (not p.fits, p.score_us, p.peak_bytes,
                           p.describe()))

    def to_json(self):
        return {
            'name': self.name,
            'chips': self.chips,
            'hbm_budget_bytes': self.hbm_bytes,
            'enumerated': self.enumerated,
            'candidates': [p.to_json() for p in self.candidates],
            'fallbacks': [p.to_json() for p in self.fallbacks],
            'winner': self.winner.to_json() if self.winner else None,
            'errors': dict(self.errors),
        }

    def to_event(self):
        """The ``plan_selected`` telemetry payload: enough for
        run_report to show predicted-vs-actual for the chosen plan."""
        w = self.winner
        return {
            'name': self.name,
            'chips': self.chips,
            'hbm_budget_bytes': self.hbm_bytes,
            'candidates_scored': len(self.candidates),
            'winner': (None if w is None else {
                'mesh': dict(w.mesh_axes),
                'assignment': w.assignment,
                'fallback': w.fallback}),
            'wire_bytes': None if w is None else w.wire_bytes,
            'est_us': None if w is None else w.est_us,
            'compute_us': None if w is None else w.compute_us,
            'peak_bytes': None if w is None else w.peak_bytes,
            'quant': (dict(w.quant)
                      if w is not None and w.quant else None),
        }

    def render(self):
        """Human table, best plan first."""
        lines = [f'-- sharding plan [{self.name}]: {self.chips} chips, '
                 f'HBM budget '
                 f'{self.hbm_bytes / (1 << 30):.1f} GiB --']
        hdr = (f'  {"#":>3} {"mesh":<16} {"assignment":<11} '
               f'{"score us":>9} {"comm us":>8} {"peak MiB":>9} '
               f'{"wire MiB":>9} fits')
        lines.append(hdr)
        for p in self.candidates:
            lines.append(
                f'  {p.rank:>3} {p.mesh_str():<16} '
                f'{p.assignment:<11} {p.score_us:>9.1f} '
                f'{p.est_us:>8.1f} '
                f'{p.peak_bytes / (1 << 20):>9.1f} '
                f'{p.wire_bytes / (1 << 20):>9.2f} '
                f'{"yes" if p.fits else "NO"}'
                + (f'  ({p.scored_via})'
                   if p.scored_via != 'hlo' else ''))
        if self.fallbacks:
            lines.append('  -- nothing fit the budget; fallbacks --')
            for p in self.fallbacks:
                lines.append(
                    f'      {p.describe():<34} '
                    f'{p.score_us:>9.1f} {p.est_us:>8.1f} '
                    f'{p.peak_bytes / (1 << 20):>9.1f} '
                    f'{"fits" if p.fits else "STILL OVER"}')
        w = self.winner
        lines.append(f'  winner: {w.describe() if w else "none"}')
        if w is not None and w.quant and w.quant.get('recommended'):
            q = w.quant
            lines.append(
                "  recommend: quant_collectives="
                f"'{q['wire_dtype']}' — the grad all-reduce is "
                f"{q['ar_frac'] * 100:.0f}% of the step estimate; "
                f"int8 wire cuts it to ~{q['score_us']:.1f}us "
                f"(saves {q['saved_us']:.1f}us/step, wire "
                f"{w.wire_bytes / (1 << 20):.2f} -> "
                f"{q['wire_bytes'] / (1 << 20):.2f} MiB).  Gate "
                'quality first: tools/quant_accuracy.py')
        if self.enumerated > len(self.candidates) + len(self.errors):
            lines.append(
                f'  (scored {len(self.candidates)} of '
                f'{self.enumerated} enumerated candidates — raise '
                '--max-candidates to widen the search)')
        if self.errors:
            for d, e in self.errors.items():
                lines.append(f'  [skipped {d}: {e}]')
        return '\n'.join(lines)


def enumerate_meshes(chips, *, include_pp=True, max_axes=3):
    """Ordered dp/tp/pp factorizations of `chips`.

    Every ordered (dp, tp[, pp]) with dp·tp·pp == chips, each axis a
    divisor — including the 1-axis ring (dp=chips), the 2D layouts,
    and (when ``include_pp``) 3D layouts with a pipeline axis.
    Returns ordered {'dp': d, 'tp': t, 'pp': p} dicts (pp omitted
    when 1 and include_pp is False)."""
    chips = int(chips)
    if chips < 1:
        raise ValueError(f'chips must be >= 1, got {chips}')
    divs = [d for d in range(1, chips + 1) if chips % d == 0]
    out = []
    pps = divs if (include_pp and max_axes >= 3) else [1]
    for pp in pps:
        rest = chips // pp
        for dp in (d for d in divs if rest % d == 0):
            tp = rest // dp
            axes = {'dp': dp, 'tp': tp}
            if include_pp and max_axes >= 3:
                axes['pp'] = pp
            out.append(axes)
    # stable, human-sensible order: flat dp first, then growing tp/pp
    out.sort(key=lambda a: (a.get('pp', 1), a['tp'], -a['dp']))
    seen, uniq = set(), []
    for a in out:
        k = (a['dp'], a['tp'], a.get('pp', 1))
        if k not in seen:
            seen.add(k)
            uniq.append(a)
    return uniq


def _shard_factor(spec, mesh_axes):
    """How many ways a spec tuple splits a buffer on this mesh."""
    f = 1
    for part in (spec or ()):
        for ax in (part if isinstance(part, (tuple, list)) else (part,)):
            if ax and ax != '...':
                f *= max(1, int(mesh_axes.get(ax, 1)))
    return f


def assignments_for(model, mesh_axes, declared=None):
    """Candidate {assignment_name: {param: spec tuple}} for one mesh.

    * ``declared`` — the model's own per-param specs (tp layers), kept
      only when some spec actually bites on this mesh;
    * ``replicated`` — every param replicated (pure data parallel),
      kept only when it differs from declared;
    * ``fsdp`` — declared plus dim-0 'dp' sharding of every
      still-replicated param whose dim 0 divides (ZeRO-3 posture:
      weight-gather on use, cheapest HBM).
    """
    from ..parallel.api import collect_param_shardings
    if declared is None:
        declared = collect_param_shardings(model)
    params, _ = model.functional_state()
    out = {}
    declared_bites = any(_shard_factor(s, mesh_axes) > 1
                         for s in declared.values())
    if declared_bites:
        out['declared'] = dict(declared)
    out['replicated'] = {n: None for n in declared}
    dp = int(mesh_axes.get('dp', 1))
    if dp > 1:
        fsdp = {}
        bites = False
        for n, v in params.items():
            spec = declared.get(n)
            if _shard_factor(spec, mesh_axes) > 1:
                fsdp[n] = spec
            elif v.ndim and v.shape[0] % dp == 0:
                fsdp[n] = ('dp',) + (None,) * (v.ndim - 1)
                bites = True
            else:
                fsdp[n] = spec
        if bites:
            out['fsdp'] = fsdp
    return out


# -- per-device compute floor from the compiled module ------------------------

_DOT_OPS = ('dot', 'convolution')
_CUSTOM_DOT_RE = re.compile(r'dot|conv|gemm|matmul', re.IGNORECASE)


def _instr_flops(comp, ins):
    """~2·sqrt(|op0|·|op1|·|out|) — exact 2·m·k·n for a plain matmul,
    a usable proxy for batched dots and convs."""
    elems = []
    for name in ins.operands[:2]:
        src = comp.index.get(name)
        if src is None or not src.shape:
            return 0.0
        elems.append(max(1, math.prod(src.shape)))
    if len(elems) < 2 or not ins.shape:
        return 0.0
    out = max(1, math.prod(ins.shape))
    return 2.0 * math.sqrt(float(elems[0]) * elems[1] * out)


def compute_floor_us(module, *, peak_tflops=DEFAULT_PEAK_TFLOPS,
                     hbm_gbps=DEFAULT_HBM_GBPS):
    """Roofline floor for ONE device executing the compiled module:
    max(FLOPs/peak, HBM traffic/bw).  FLOPs from dot/convolution
    instructions (plus custom-call dots some backends emit); traffic
    as the bytes of every non-alias buffer written.  Deliberately a
    FLOOR — overlap, fusion and caching only push real time up from
    here, and the planner only needs a consistent per-candidate
    comparison, not wall-clock fidelity."""
    flops = 0.0
    traffic = 0
    for comp, ins in module.walk():
        if ins.opcode in _hlo._ALIAS_OPS:
            continue
        traffic += ins.bytes
        if ins.opcode in _DOT_OPS or (
                ins.opcode == 'custom-call'
                and _CUSTOM_DOT_RE.search(ins.call_target or '')):
            flops += _instr_flops(comp, ins)
        elif ins.opcode == 'fusion':
            # dots fused into a fusion body still run: walk the body
            sub = None
            for cname in ins.called:
                sub = module.computations.get(cname)
                if sub is not None:
                    break
            if sub is not None:
                for fins in sub.instrs:
                    if fins.opcode in _DOT_OPS:
                        flops += _instr_flops(sub, fins)
    flops_us = flops / (float(peak_tflops) * 1e6)
    traffic_us = traffic / (float(hbm_gbps) * 1e3)
    return max(flops_us, traffic_us)


# -- scoring ------------------------------------------------------------------

def _scale_batch(batch, scale):
    import jax
    if scale >= 1.0:
        return tuple(batch)
    out = []
    for b in batch:
        if b.shape and b.shape[0] >= 2:
            dim0 = max(1, int(b.shape[0] * scale))
            out.append(jax.ShapeDtypeStruct((dim0,) + tuple(b.shape[1:]),
                                            b.dtype))
        else:
            out.append(b)
    return tuple(out)


def _build_mesh(devices, mesh_axes):
    import numpy as np
    from jax.sharding import Mesh
    sizes = tuple(mesh_axes.values())
    n = math.prod(sizes)
    return Mesh(np.array(devices[:n]).reshape(sizes),
                tuple(mesh_axes.keys()))


def _score_lowered(plan, model, batch, mesh, *, thresholds,
                   lower_cache, name):
    """Lower the surrogate step under `plan`'s shardings and fill the
    plan's predicted numbers from the compiled module."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..distributed import env as _env
    thr = dict(_hlo.DEFAULT_HLO_THRESHOLDS)
    thr.update(thresholds or {})
    batch = _scale_batch(batch, plan.batch_scale)
    prev_mesh = _env.get_mesh()
    _env.set_mesh(mesh)   # model-internal maybe_shard constraints live
    try:
        params, buffers, p_sh, b_sh = _targets.target_state(
            model, mesh, param_specs=plan.param_specs)
        axis = plan.batch_axis if mesh.shape.get(plan.batch_axis, 1) > 1 \
            else None
        batch_sh = _targets.batch_shardings(mesh, batch, axis=axis)
        repl = NamedSharding(mesh, P())
        step = _targets.surrogate_step(model, remat=plan.remat)
        key = jax.random.PRNGKey(0)
        ck = _targets.cache_key(
            name, mesh.shape, p_sh, batch_sh,
            remat=plan.remat, batch=batch)
        text = _hlo.lower_text(
            step, params, buffers, key, *batch,
            jit_kwargs={'in_shardings': (p_sh, b_sh, repl) + batch_sh},
            lower_cache=lower_cache, cache_key=ck)
    finally:
        _env.set_mesh(prev_mesh)
    module = _hlo.parse_module(text)
    census = _hlo.collective_census(
        module, bw_gbps=thr['link_bw_gbps'],
        latency_us=thr['link_latency_us'],
        mesh_shape=dict(mesh.shape),
        calibration=thr.get('calibration'))
    plan.census = census
    plan.wire_bytes = sum(r['wire_bytes'] for r in census.values())
    plan.est_us = round(sum(r['est_us'] for r in census.values()), 3)
    plan.phases = sum(r['phases'] for r in census.values())
    # the liveness estimate, scaled by the fitted predicted-vs-compiled
    # bias when the Calibration table carries one (memory observatory:
    # per_op['peak_memory']['bias'], fitted from memory_compiled
    # events the same way collective alpha/beta are fitted from
    # collective_observed) — so the HBM gate below judges candidates
    # at the estimator's MEASURED accuracy, not its nominal one
    peak = _hlo.peak_memory(module)
    cal = thr.get('calibration')
    if cal is not None:
        try:
            bias = float(cal.per_op.get('peak_memory', {})
                         .get('bias', 1.0))
            if bias > 0:
                peak = int(peak * bias)
        except Exception:
            pass
    plan.peak_bytes = peak
    plan.compute_us = round(compute_floor_us(
        module, peak_tflops=thr.get('peak_tflops', DEFAULT_PEAK_TFLOPS),
        hbm_gbps=thr.get('hbm_gbps', DEFAULT_HBM_GBPS)), 3)
    plan.score_us = round(plan.compute_us + plan.est_us, 3)
    _maybe_recommend_quant(plan, thr)
    return plan


# -- wire-dtype what-if: recommend quantized collectives ----------------------

# a plan is "collective-dominated" when the grad all-reduce alone is
# at least this share of the whole step estimate — below it the
# quantized wire cannot move the step time enough to matter
QUANT_RECOMMEND_AR_FRAC = 0.25
# ...and the re-wired step must be at least this much faster overall
QUANT_RECOMMEND_MIN_SPEEDUP = 1.1


def _maybe_recommend_quant(plan, thr, *, wire_dtype='int8',
                           block=256):
    """Price the plan's full-width all-reduce traffic at the
    quantized wire (costmodel.quantized_allreduce_cost) and attach a
    recommendation when the collective dominates and the savings are
    real.  Pure what-if — never changes the plan's own score (the
    ranking stays full-width-honest; flipping the wire is the
    operator's call: quality gate first, see tools/quant_accuracy)."""
    ar = plan.census.get('all-reduce')
    if not ar or not ar.get('wire_bytes'):
        return
    elem = costmodel.WIRE_DTYPE_BYTES.get(
        ar.get('wire_dtype') or 'f32', 4.0)
    if elem <= costmodel.WIRE_DTYPE_BYTES[wire_dtype]:
        return      # already on a narrow wire
    q = costmodel.quantized_allreduce_cost(
        ar['bytes'], ar['axes'], elem_bytes=elem,
        wire_dtype=wire_dtype, block=block,
        bw_gbps=thr['link_bw_gbps'], latency_us=thr['link_latency_us'],
        calibration=thr.get('calibration'))
    q_est = round(plan.est_us - ar['est_us'] + q['est_us'], 3)
    q_score = round(plan.compute_us + q_est, 3)
    q_wire = plan.wire_bytes - ar['wire_bytes'] + q['wire_bytes']
    plan.quant = {
        'wire_dtype': wire_dtype,
        'block': block,
        'ar_frac': round(ar['est_us'] / plan.score_us, 4)
        if plan.score_us else 0.0,
        'wire_bytes': q_wire,
        'est_us': q_est,
        'score_us': q_score,
        'saved_us': round(plan.score_us - q_score, 3),
        'recommended': bool(
            plan.score_us
            and ar['est_us'] >= QUANT_RECOMMEND_AR_FRAC * plan.score_us
            and plan.score_us
            >= QUANT_RECOMMEND_MIN_SPEEDUP * q_score),
    }


def _params_dev_bytes(model, mesh_axes, param_specs):
    from . import walker as _w
    params, _ = model.functional_state()
    total = 0
    for n, v in params.items():
        b = _w.aval_bytes(v) if hasattr(v, 'aval') else (
            math.prod(v.shape) * v.dtype.itemsize if v.shape
            else v.dtype.itemsize)
        total += b // _shard_factor(param_specs.get(n), mesh_axes)
    return total


def _score_pp(plan, sub_plan, model, batch, *, thresholds):
    """Derive a pp>1 plan's numbers from its lowered dp×tp stage-group
    plan: optimizer-ish state (params+grads) divides across stages;
    activation stash under 1F1B stays ~flat; collectives shrink to the
    stage's share; microbatch boundary hand-offs are added as
    collective-permute traffic."""
    thr = dict(_hlo.DEFAULT_HLO_THRESHOLDS)
    thr.update(thresholds or {})
    pp = int(plan.mesh_axes.get('pp', 1))
    sub_axes = {a: s for a, s in plan.mesh_axes.items() if a != 'pp'}
    state_dev = 2 * _params_dev_bytes(model, sub_axes, plan.param_specs)
    act = max(0, sub_plan.peak_bytes - state_dev)
    plan.peak_bytes = act + state_dev // pp
    plan.wire_bytes = sub_plan.wire_bytes // pp
    plan.est_us = round(sub_plan.est_us / pp, 3)
    plan.phases = max(1, sub_plan.phases // pp)
    plan.compute_us = round(sub_plan.compute_us / pp, 3)
    # 1F1B boundary traffic: each of ~pp microbatches crosses pp-1
    # stage boundaries forward and backward
    mb_bytes = sum(
        (math.prod(b.shape) * getattr(b.dtype, 'itemsize', 4)) // pp
        for b in batch if b.shape)
    hops = 2 * (pp - 1) * pp
    perm = costmodel.torus_cost(
        'collective-permute', mb_bytes, (('pp', pp),),
        bw_gbps=thr['link_bw_gbps'], latency_us=thr['link_latency_us'],
        calibration=thr.get('calibration'))
    plan.wire_bytes += perm['wire_bytes'] * hops
    plan.est_us = round(plan.est_us + perm['est_us'] * hops, 3)
    plan.phases += perm['phases'] * hops
    # the 1F1B bubble: (pp-1)/pp of one stage-compute wasted per step
    plan.score_us = round(
        plan.compute_us * (1 + (pp - 1) / pp) + plan.est_us, 3)
    plan.scored_via = 'pp-model'
    plan.census = dict(sub_plan.census)
    plan.notes.append(
        f'pp={pp} scored analytically from the {sub_plan.mesh_str()} '
        'stage-group lowering (1F1B not lowered)')
    return plan


def plan_model(model, example_batch, *, chips=None, devices=None,
               hbm_budget_gb=None, calibration=None, include_pp=True,
               thresholds=None, lower_cache=None, max_candidates=None,
               name=None):
    """Enumerate, lower, score and rank sharding plans for `model`.

    model: a paddle_tpu Layer (functional_state + declared specs).
    example_batch: tuple of arrays / ShapeDtypeStructs the step
    consumes (shapes drive everything; no values are read).
    chips: devices to plan for (default: all visible).
    devices: explicit jax device list (default jax.devices()) — must
    hold at least `chips`.
    hbm_budget_gb: per-device budget the peak-memory estimate is
    gated on (default: the audit's 16 GiB).
    calibration: ``costmodel.Calibration`` (or path) with measured
    alpha/beta.
    lower_cache: optional dict shared with the --hlo audit so one
    (target, mesh) lowering is never paid twice.
    max_candidates: cap on the number of LOWERED candidates (default
    DEFAULT_MAX_CANDIDATES=32 — a 256-chip enumeration would
    otherwise compile 100+ modules).  The enumeration is pruned
    mesh-major, cheapest meshes first, keeping every assignment of
    the meshes that survive; ``PlanResult.enumerated`` records what
    the cap dropped.

    Returns a ranked ``PlanResult``.
    """
    import jax
    if devices is None:
        devices = jax.devices()
    chips = int(chips or len(devices))
    if chips > len(devices):
        raise ValueError(
            f'planner asked for {chips} chips but only {len(devices)} '
            'devices exist (force more with '
            '--xla_force_host_platform_device_count)')
    if isinstance(calibration, str):
        calibration = costmodel.load_calibration(calibration)
    thr = dict(thresholds or {})
    if calibration is not None:
        thr.setdefault('calibration', calibration)
    if hbm_budget_gb is not None:       # 0 is a legitimate budget
        thr['hbm_bytes'] = int(float(hbm_budget_gb) * (1 << 30))
    hbm_bytes = thr.get('hbm_bytes',
                        _hlo.DEFAULT_HLO_THRESHOLDS['hbm_bytes'])
    name = name or type(model).__name__
    result = PlanResult(name, chips, hbm_bytes)
    if lower_cache is None:
        lower_cache = {}
    batch = tuple(
        b if hasattr(b, 'dtype') and hasattr(b, 'shape')
        else jax.ShapeDtypeStruct(b.shape, b.dtype)
        for b in example_batch)

    from ..parallel.api import collect_param_shardings
    declared = collect_param_shardings(model)
    todo = []
    # mesh-major order (enumerate_meshes already runs cheapest — flat
    # dp, then growing tp/pp — first), assignments nested inside each
    # mesh: truncation under max_candidates keeps EVERY assignment of
    # the cheapest meshes instead of dropping whole families
    for mesh_axes in enumerate_meshes(chips, include_pp=include_pp):
        for aname, specs in assignments_for(
                model, mesh_axes, declared=declared).items():
            todo.append((mesh_axes, aname, specs))
    result.enumerated = len(todo)
    if max_candidates is None:
        max_candidates = DEFAULT_MAX_CANDIDATES
    if len(todo) > int(max_candidates):
        todo = todo[:int(max_candidates)]

    sub_cache = {}      # (dp, tp, assignment) -> scored stage plan
    for mesh_axes, aname, specs in todo:
        plan = ShardingPlan(mesh_axes, aname, param_specs=specs)
        pp = int(mesh_axes.get('pp', 1))
        try:
            if pp <= 1:
                mesh = _build_mesh(devices, mesh_axes)
                _score_lowered(plan, model, batch, mesh,
                               thresholds=thr, lower_cache=lower_cache,
                               name=name)
                sub_cache[(mesh_axes['dp'], mesh_axes['tp'], aname)] = \
                    plan
            else:
                sub_axes = {'dp': mesh_axes['dp'], 'tp': mesh_axes['tp']}
                skey = (sub_axes['dp'], sub_axes['tp'], aname)
                sub = sub_cache.get(skey)
                if sub is None:
                    sub = ShardingPlan(sub_axes, aname,
                                       param_specs=specs)
                    mesh = _build_mesh(devices, sub_axes)
                    _score_lowered(sub, model, batch, mesh,
                                   thresholds=thr,
                                   lower_cache=lower_cache, name=name)
                    sub_cache[skey] = sub
                _score_pp(plan, sub, model, batch, thresholds=thr)
        except Exception as e:      # one broken lower must not
            result.errors[plan.describe()] = repr(e)    # void the rest
            continue
        plan.fits = plan.peak_bytes <= hbm_bytes
        result.candidates.append(plan)
    result.rank()

    if result.candidates and not any(p.fits for p in result.candidates):
        # nothing fits: re-lower the closest misses with remat and
        # with the batch halved — the explicit escape hatches
        # (strategy.recompute / a smaller global batch) a human would
        # reach for at OOM time
        misses = [p for p in result.candidates
                  if p.scored_via == 'hlo'][:3]
        for base in misses:
            for kind in ('remat', 'half-batch'):
                fb = ShardingPlan(base.mesh_axes, base.assignment,
                                  param_specs=base.param_specs)
                if kind == 'remat':
                    fb.remat = True
                else:
                    if not (batch and batch[0].shape
                            and batch[0].shape[0] % 2 == 0):
                        continue
                    fb.batch_scale = 0.5
                try:
                    mesh = _build_mesh(devices, base.mesh_axes)
                    _score_lowered(fb, model, batch, mesh,
                                   thresholds=thr,
                                   lower_cache=lower_cache, name=name)
                except Exception as e:
                    result.errors[fb.describe()] = repr(e)
                    continue
                fb.fits = fb.peak_bytes <= hbm_bytes
                fb.notes.append(
                    f'budget fallback for {base.describe()}')
                result.fallbacks.append(fb)
        result.rank()
    return result


def plan_target(target, *, chips, mesh=None, devices=None, **kwargs):
    """Plan one built-in audit target (gpt / widedeep / lenet) —
    the ``tpu_lint --plan`` entry."""
    builder = _targets.TARGETS[target]
    model, batch = builder(mesh)
    return plan_model(model, batch, chips=chips, devices=devices,
                      name=target, **kwargs)
