"""AST concurrency lint: the host-thread side of the sync-free posture.

The jaxpr/HLO tiers check what XLA compiles; this tier checks what the
*host threads* do around it.  The runtime grew 15+ daemon threads and
15+ locks (watchdog, plan supervisor, metrics server, cluster
aggregator, chunk prefetcher, DataLoader workers) and every recent
review pass caught a real race by hand — this registry makes those
review checks mechanical.  Three rules, all ``origin='ast'``:

``guarded-by``
    Classes annotate shared attributes either with a trailing
    ``# guarded-by: _lock`` comment on the attribute's assignment
    line, or with a class-level ``_GUARDED_BY = {'attr': '_lock'}``
    map.  Reads/writes of an annotated attribute outside a lexical
    ``with self._lock:`` flag HIGH when the enclosing method is
    reachable off a thread entry point (a ``threading.Thread(target=
    self.m)`` target or a ``subscribe(self.m)`` callback — subscriber
    callbacks run on whatever thread emitted the event), WARN
    otherwise.  A ``# locked-by: _lock`` comment on a ``def`` line
    declares the whole method runs with the lock already held (the
    per-kind handler pattern: dispatched under the caller's ``with``)
    — that is the rule refinement for the common false positive, not
    a suppression.  ``__init__`` is exempt (construction
    happens-before publication).

``blocking-under-lock``
    ``block_until_ready`` / ``device_put`` / ``.post(`` / file IO /
    ``time.sleep`` lexically inside a ``with <lock>:`` body.  HIGH
    when the enclosing class is a Recorder/aggregator/publisher (the
    hot telemetry locks sit on every event emit — blocking there
    stalls the train loop), WARN elsewhere.

``daemon-thread-lifecycle``
    Every ``threading.Thread(daemon=True)`` start site must have a
    reachable stop/join path: a ``.join(`` on the thread in the
    enclosing scope, or — for ``self._thread``-style ownership — a
    class method from the known stop registry (``stop``, ``close``,
    ``stop_watchdog``, ``stop_supervisor``, ...).  Else WARN: a
    daemon thread with no shutdown path leaks past its owner's
    lifetime (parked on a bounded queue, holding batch memory).

Suppression uses the established grammar: ``# tpu-lint:
disable=guarded-by`` on the finding's line or its enclosing ``def``
line (see ast_lint).  Everything here is pure source analysis — no
imports, no execution — so the CLI sweep (``tpu_lint --threads``) and
the tier-1 self-lint gate run it over all of ``paddle_tpu/``.
"""
import ast
import linecache
import os
import re

from .findings import Finding, LintReport, HIGH, WARN, INFO
from .ast_lint import (_is_suppressed, _def_spans,
                       _enclosing_def_lines, _dotted_last)

__all__ = ['lint_threads_source', 'lint_threads_file',
           'lint_threads_sources', 'THREAD_RULES',
           'register_thread_rule', 'BLOCKING_UNDER_LOCK',
           'STOP_METHODS', 'HOT_CLASS_MARKERS']

_GUARD_RE = re.compile(r'#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)')
_LOCKED_RE = re.compile(r'#\s*locked-by:\s*([A-Za-z_][A-Za-z0-9_]*)')
_SELF_ASSIGN_RE = re.compile(
    r'self\.([A-Za-z_][A-Za-z0-9_]*)\s*(?:[-+*/|&^]|//|>>|<<)?=(?!=)')

# method names that block (or can block) the calling thread.  `.get`/
# `.put`/`.join` are deliberately absent: dict.get / str.join noise
# would drown the signal.
BLOCKING_UNDER_LOCK = {
    'block_until_ready',    # device sync
    'device_put',           # host->device transfer
    'sleep',                # time.sleep
    'post', 'post_stats',   # transport/KV publish (network RTT)
    'urlopen', 'request',   # HTTP
}

# classes whose locks sit on the per-event hot path: blocking under
# them stalls every emitter (the train loop included) -> HIGH
HOT_CLASS_MARKERS = ('Recorder', 'Aggregator', 'Publisher')

# known stop/teardown entry points: a daemon thread stored on `self`
# is considered owned when its class exposes one of these (the
# registry the lifecycle rule checks before demanding a literal join)
STOP_METHODS = {
    'stop', 'close', 'shutdown', 'terminate', 'uninstall',
    'stop_watchdog', 'stop_supervisor', 'stop_all', '__exit__',
}

THREAD_RULES = {}


def register_thread_rule(rule_id, severity):
    """Same decorator shape as rules.register_rule: registry maps
    rule id -> (default severity, fn(ctx) -> findings)."""
    def deco(fn):
        THREAD_RULES[rule_id] = (severity, fn)
        return fn
    return deco


# -- module context -----------------------------------------------------------

def _body_start(fn):
    """First body line of a def — comment scans for `# locked-by`
    cover the whole (possibly multi-line) signature."""
    return fn.body[0].lineno if fn.body else fn.lineno + 1


class _FuncScope:
    __slots__ = ('node', 'cls', 'start', 'end')

    def __init__(self, node, cls):
        self.node = node
        self.cls = cls          # enclosing ClassDef or None
        self.start = node.lineno
        self.end = getattr(node, 'end_lineno', node.lineno)


class _Ctx:
    """Parsed module + line-comment annotations, shared by all rules."""

    def __init__(self, tree, src, filename):
        self.tree = tree
        self.filename = filename
        self.lines = src.splitlines()
        # line -> annotation payload
        self.guard_at = {}
        self.locked_at = {}
        for i, text in enumerate(self.lines, start=1):
            m = _GUARD_RE.search(text)
            if m:
                self.guard_at[i] = m.group(1)
            m = _LOCKED_RE.search(text)
            if m:
                self.locked_at[i] = m.group(1)
        # scopes: every def, with its enclosing class (if any)
        self.funcs = []
        self.classes = []
        self._index(tree.body, None)

    def _index(self, body, cls):
        for node in body:
            if isinstance(node, ast.ClassDef):
                self.classes.append(node)
                self._index(node.body, node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.funcs.append(_FuncScope(node, cls))
                self._index(node.body, None)
            elif hasattr(node, 'body'):
                self._index(node.body, cls)
                for attr in ('orelse', 'finalbody'):
                    self._index(getattr(node, attr, []) or [], cls)
                for h in getattr(node, 'handlers', []) or []:
                    self._index(h.body, cls)

    def enclosing_func(self, line):
        """Innermost def scope containing `line` (None at module
        level)."""
        best = None
        for fs in self.funcs:
            if fs.start <= line <= fs.end:
                if best is None or fs.start > best.start:
                    best = fs
        return best

    def locked_by(self, fn):
        """Lock names declared via `# locked-by:` on the def's
        signature lines."""
        out = set()
        for ln in range(fn.lineno, _body_start(fn)):
            if ln in self.locked_at:
                out.add(self.locked_at[ln])
        return out


def _walk_skip_defs(node):
    """Walk `node`'s subtree but do not descend into nested function
    definitions (their bodies run later, not under the current
    with/lock)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _self_attr(node):
    """'x' for an `self.x` Attribute expression, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == 'self':
        return node.attr
    return None


def _with_lock_spans(fn):
    """[(lock_expr_name, start, end)] for every `with <lock>:` inside
    `fn`.  `self._lock` yields '_lock'; a bare name yields that name.
    Anything whose last segment doesn't look lock-ish is skipped."""
    spans = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                name = _self_attr(expr)
                if name is None and isinstance(expr, ast.Name):
                    name = expr.id
                if name is None and isinstance(expr, ast.Attribute):
                    name = expr.attr
                if name is None:
                    continue
                spans.append((name, node.lineno,
                              getattr(node, 'end_lineno', node.lineno)))
    return spans


# -- per-class model (guarded-by) ---------------------------------------------

class _ClassModel:
    def __init__(self, cls, ctx):
        self.node = cls
        self.name = cls.name
        self.methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.guarded = {}
        self._collect_guard_map()
        self._collect_guard_comments(ctx)
        self.entry_points = self._entry_points()
        self.reachable = self._closure(self.entry_points)

    def _collect_guard_map(self):
        for node in self.node.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == '_GUARDED_BY' \
                    and isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) and \
                            isinstance(v, ast.Constant) and \
                            isinstance(k.value, str) and \
                            isinstance(v.value, str):
                        self.guarded[k.value] = v.value

    def _collect_guard_comments(self, ctx):
        end = getattr(self.node, 'end_lineno', self.node.lineno)
        for ln in range(self.node.lineno, end + 1):
            lock = ctx.guard_at.get(ln)
            if lock is None:
                continue
            m = _SELF_ASSIGN_RE.search(ctx.lines[ln - 1])
            if m:
                self.guarded[m.group(1)] = lock

    def _entry_points(self):
        """Method names handed to Thread(target=...) or subscribe(...)
        anywhere in the class — code that runs on another thread."""
        out = set()
        for meth in self.methods.values():
            for node in ast.walk(meth):
                if not isinstance(node, ast.Call):
                    continue
                callee = _dotted_last(node.func)
                if callee == 'Thread':
                    for kw in node.keywords:
                        if kw.arg == 'target':
                            t = _self_attr(kw.value)
                            if t:
                                out.add(t)
                elif callee == 'subscribe':
                    for a in node.args:
                        t = _self_attr(a)
                        if t:
                            out.add(t)
        return out

    def _closure(self, seeds):
        """Transitive closure of `seeds` over the self.m() call
        graph."""
        calls = {}
        for name, meth in self.methods.items():
            callees = set()
            for node in ast.walk(meth):
                if isinstance(node, ast.Call):
                    t = _self_attr(node.func)
                    if t and t in self.methods:
                        callees.add(t)
            calls[name] = callees
        seen = set()
        frontier = [s for s in seeds if s in self.methods]
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            frontier.extend(calls.get(m, ()))
        return seen


@register_thread_rule('guarded-by', HIGH)
def check_guarded_by(ctx):
    findings = []
    for cls in ctx.classes:
        model = _ClassModel(cls, ctx)
        if not model.guarded:
            continue
        for mname, meth in model.methods.items():
            if mname == '__init__':
                continue
            held_whole = ctx.locked_by(meth)
            spans = _with_lock_spans(meth)
            seen = set()
            for node in ast.walk(meth):
                attr = _self_attr(node)
                if attr is None or attr not in model.guarded:
                    continue
                lock = model.guarded[attr]
                if lock in held_whole:
                    continue
                if any(n == lock and s <= node.lineno <= e
                       for n, s, e in spans):
                    continue
                key = (attr, node.lineno)
                if key in seen:
                    continue
                seen.add(key)
                hot = mname in model.reachable
                sev = HIGH if hot else WARN
                why = ('reachable from a thread entry point '
                       f'({", ".join(sorted(model.entry_points))})'
                       if hot else 'not provably single-threaded')
                findings.append(Finding(
                    'guarded-by', sev,
                    f'{model.name}.{mname}: self.{attr} is guarded by '
                    f"self.{lock} but accessed outside 'with "
                    f"self.{lock}' ({why}). Take the lock, or mark "
                    f"the method '# locked-by: {lock}' if every "
                    'caller already holds it.',
                    file=ctx.filename, line=node.lineno, origin='ast'))
    return findings


# -- blocking-call-under-lock -------------------------------------------------

def _is_blocking_call(node):
    """(label, True) when `node` is a call that can block the calling
    thread: the registry methods, builtin open(), or time.sleep."""
    if not isinstance(node, ast.Call):
        return None
    if isinstance(node.func, ast.Name):
        if node.func.id == 'open':
            return 'open() [file IO]'
        return None
    name = _dotted_last(node.func)
    if name in BLOCKING_UNDER_LOCK:
        return f'.{name}()'
    return None


@register_thread_rule('blocking-under-lock', HIGH)
def check_blocking_under_lock(ctx):
    findings = []
    for fs in ctx.funcs:
        cls_name = fs.cls.name if fs.cls is not None else None
        hot = bool(cls_name) and any(
            m in cls_name for m in HOT_CLASS_MARKERS)
        for node in ast.walk(fs.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_names = []
            for item in node.items:
                name = _self_attr(item.context_expr)
                if name is None and isinstance(item.context_expr,
                                               ast.Name):
                    name = item.context_expr.id
                if name and 'lock' in name.lower():
                    lock_names.append(name)
            if not lock_names:
                continue
            for sub in _walk_skip_defs(node):
                label = _is_blocking_call(sub)
                if label is None:
                    continue
                sev = HIGH if hot else WARN
                where = f'{cls_name}.{fs.node.name}' if cls_name \
                    else fs.node.name
                hint = ('every event emitter (the train loop '
                        'included) serializes behind this lock'
                        if hot else 'holders block waiters for the '
                        'full call')
                findings.append(Finding(
                    'blocking-under-lock', sev,
                    f'{where}: {label} inside '
                    f"'with {'/'.join(lock_names)}' — {hint}. Move "
                    'the blocking call outside the critical section '
                    '(snapshot under the lock, act after release).',
                    file=ctx.filename, line=sub.lineno, origin='ast'))
    return findings


# -- daemon lifecycle ---------------------------------------------------------

def _is_thread_join(node):
    """A Call that plausibly joins a thread: `x.join()`,
    `x.join(timeout)`, `x.join(timeout=..)` — excludes str.join
    (exactly one non-numeric positional) and os.path.join."""
    if not isinstance(node, ast.Call) or \
            not isinstance(node.func, ast.Attribute) or \
            node.func.attr != 'join':
        return False
    base = node.func.value
    if isinstance(base, ast.Constant):          # 'sep'.join(...)
        return False
    if isinstance(base, ast.Attribute) and base.attr == 'path':
        return False                            # os.path.join(...)
    if len(node.args) > 1:
        return False
    if node.args:
        a = node.args[0]
        if not (isinstance(a, ast.Constant)
                and isinstance(a.value, (int, float))) and \
                not isinstance(a, (ast.Name, ast.Attribute)):
            return False
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return False
    return True


def _contains_join(node):
    return any(_is_thread_join(n) for n in ast.walk(node))


@register_thread_rule('daemon-thread-lifecycle', WARN)
def check_daemon_lifecycle(ctx):
    findings = []
    # map Thread(...) call -> how it is bound (self attr / local / bare)
    assigned_self = {}          # id(call) -> attr name
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _dotted_last(node.value.func) == 'Thread':
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr:
                    assigned_self[id(node.value)] = attr
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _dotted_last(node.func) == 'Thread'):
            continue
        daemon = any(
            kw.arg == 'daemon' and isinstance(kw.value, ast.Constant)
            and kw.value.value is True for kw in node.keywords)
        if not daemon:
            continue
        fs = ctx.enclosing_func(node.lineno)
        ok = False
        if id(node) in assigned_self and fs is not None and \
                fs.cls is not None:
            cls = fs.cls
            ok = _contains_join(cls) or any(
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name in STOP_METHODS for n in cls.body)
        elif fs is not None:
            ok = _contains_join(fs.node)
        else:
            ok = _contains_join(ctx.tree)       # module-level start
        if ok:
            continue
        findings.append(Finding(
            'daemon-thread-lifecycle', WARN,
            'threading.Thread(daemon=True) started here has no '
            'reachable stop/join path (no .join() in the owning '
            'scope, no stop-registry method on the owning class). '
            'Daemon threads with no shutdown path leak past their '
            "owner's lifetime — add a sentinel/stop flag and a "
            'bounded join.',
            file=ctx.filename, line=node.lineno, origin='ast'))
    return findings


# -- entry points -------------------------------------------------------------

def lint_threads_source(src, filename='<source>', disable=(),
                        apply_suppress=True):
    """Run the concurrency rules on python source text; returns a
    list of Findings (origin='ast')."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding('parse-error', INFO,
                        f'could not parse: {e}', file=filename,
                        line=getattr(e, 'lineno', None), origin='ast')]
    ctx = _Ctx(tree, src, filename)
    findings = []
    for rule_id, (_sev, fn) in THREAD_RULES.items():
        if rule_id in disable:
            continue
        findings.extend(fn(ctx))
    if apply_suppress:
        spans = _def_spans(tree)
        findings = [
            f for f in findings
            if not _is_suppressed(f.rule, filename, f.line,
                                  _enclosing_def_lines(spans, f.line))]
    findings.sort(key=lambda f: (f.line or 0))
    return findings


def lint_threads_file(path, disable=()):
    with open(path, 'r', encoding='utf-8') as fh:
        src = fh.read()
    linecache.checkcache(path)
    return lint_threads_source(src, filename=path, disable=disable)


def _iter_py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith('.')
                                 and d != '__pycache__')
                for f in sorted(files):
                    if f.endswith('.py'):
                        yield os.path.join(root, f)
        elif p.endswith('.py'):
            yield p


def lint_threads_sources(paths, disable=()):
    """Sweep files/directories with the concurrency rules; returns a
    LintReport (what ``tpu_lint --threads`` and the tier-1 self-lint
    gate run)."""
    rep = LintReport(name='threads')
    n_files = 0
    for path in _iter_py_files(paths):
        n_files += 1
        rep.findings.extend(lint_threads_file(path, disable=disable))
    rep.extras['threads'] = {'files': n_files,
                             'rules': sorted(THREAD_RULES)}
    return rep
