"""Shared jaxpr traversal — the ONE walk used by every analysis rule
and by fluid.contrib.op_frequence.

A jaxpr is the unit XLA actually compiles, so walking it (instead of
Python source) sees exactly what will run on the chip: casts the
tracer inserted, constants it baked in, callbacks that punch through
to the host, and the sub-jaxprs of scan/cond/while/pjit/custom-vjp
bodies.  ``walk`` yields ``(parent_jaxpr, eqn)`` depth-first so
callers can both count ops globally and reason per-nesting-level
(op_frequence's adjacent-pair statistic pairs only within one level).

Nothing here executes device code: ``trace_jaxpr`` is jax.make_jaxpr
(abstract evaluation), usable with concrete arrays *or*
jax.ShapeDtypeStruct placeholders.
"""
import math

import numpy as np

import jax

try:                      # jax.core is the public alias; keep a fallback
    from jax import core as _core
    _core.Jaxpr, _core.ClosedJaxpr, _core.Literal, _core.Var
except (ImportError, AttributeError):       # pragma: no cover
    from jax._src import core as _core

__all__ = ['trace_jaxpr', 'walk', 'subjaxprs', 'eqn_location',
           'aval_bytes', 'is_literal', 'const_derived_vars']

Literal = _core.Literal


def trace_jaxpr(fn, *example_args, **example_kwargs):
    """Abstractly trace `fn` into a ClosedJaxpr (no device execution).

    `example_args` may be concrete arrays, pytrees of arrays, or
    jax.ShapeDtypeStruct placeholders."""
    return jax.make_jaxpr(fn)(*example_args, **example_kwargs)


def _as_jaxprs(v):
    if isinstance(v, _core.ClosedJaxpr):
        return [v.jaxpr]
    if isinstance(v, _core.Jaxpr):
        return [v]
    if isinstance(v, (list, tuple)):
        return [j for item in v for j in _as_jaxprs(item)]
    return []


def subjaxprs(eqn):
    """Sub-jaxprs carried in an equation's params (scan/cond/while/pjit
    bodies, custom_vjp calls, ...) — including ones nested in tuples
    (cond branches)."""
    for v in eqn.params.values():
        for j in _as_jaxprs(v):
            yield j


def walk(jaxpr):
    """Depth-first (parent_jaxpr, eqn) over `jaxpr` and every
    sub-jaxpr.  The parent identifies the nesting level an equation
    lives in (adjacency is only meaningful within one level)."""
    for eqn in jaxpr.eqns:
        yield jaxpr, eqn
        for sub in subjaxprs(eqn):
            yield from walk(sub)


def is_literal(v):
    return isinstance(v, Literal)


def eqn_location(eqn):
    """(file, line) of the user frame that emitted this equation, or
    (None, None) when source info is unavailable.  Uses jax's own
    user-frame filter so jax-internal frames are skipped."""
    try:
        from jax._src import source_info_util
        fr = source_info_util.user_frame(eqn.source_info)
        if fr is None:
            return None, None
        return fr.file_name, fr.start_line
    except Exception:
        return None, None


def aval_bytes(aval):
    """Byte size of an abstract value (0 when it has no shape/dtype)."""
    shape = getattr(aval, 'shape', None)
    dtype = getattr(aval, 'dtype', None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:      # symbolic dim (jax.export) — unknown size
            return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtype (PRNG key avals: 'key<fry>') — numpy cannot
        # size it; its base uint32 payload is what HBM actually holds
        base = getattr(getattr(dtype, '_impl', None), 'key_shape', None)
        itemsize = 4 * math.prod(base) if base else 4
    return n * itemsize


def const_derived_vars(jaxpr):
    """Dataflow: the set of Vars in `jaxpr` (this level only) whose
    value depends ONLY on constants/literals — i.e. on nothing fed
    through the jaxpr's invars.  These are materialized identically on
    every device (XLA replicates constants), which is what the
    replicated-giant rule keys on."""
    derived = set(jaxpr.constvars)
    for eqn in jaxpr.eqns:
        ins = [v for v in eqn.invars if not is_literal(v)]
        if all(v in derived for v in ins):
            derived.update(eqn.outvars)
    return derived
