"""fluid.io — legacy persistence entry points (reference fluid/io.py).

The 1.x API took (executor, dirname) pairs; these adapt onto
paddle_tpu.static's save/load and inference export (StableHLO).
"""
import os

from ..static import io as _sio
from ..static.program import default_main_program

__all__ = ['save_params', 'load_params', 'save_persistables',
           'load_persistables', 'save_inference_model',
           'load_inference_model']


def save_params(executor, dirname, main_program=None, filename=None):
    prog = main_program or default_main_program()
    _sio.save(prog, os.path.join(dirname, filename or 'params'))


def load_params(executor, dirname, main_program=None, filename=None):
    prog = main_program or default_main_program()
    _sio.load(prog, os.path.join(dirname, filename or 'params'))


save_persistables = save_params
load_persistables = load_params


def save_inference_model(dirname, feeded_var_names, target_vars,
                         executor, main_program=None,
                         model_filename=None, params_filename=None,
                         export_for_deployment=True,
                         program_only=False):
    """1.x signature: feed vars are passed by NAME."""
    prog = main_program or default_main_program()
    feed_vars = [prog.feed_vars[n] for n in feeded_var_names]
    _sio.save_inference_model(
        os.path.join(dirname, model_filename or 'model'),
        feed_vars, target_vars, executor, program=prog)


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    return _sio.load_inference_model(
        os.path.join(dirname, model_filename or 'model'), executor)


def save(program, model_path, protocol=4, **configs):
    """fluid.io.save: persist a Program's parameters (reference
    io.py::save — pickled params + opt state)."""
    _sio.save(program, model_path, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    _sio.load(program, model_path, executor, var_list)


def load_program_state(model_path, var_list=None):
    return _sio.load_program_state(model_path, var_list)


def set_program_state(program, state_dict):
    return _sio.set_program_state(program, state_dict)


def get_program_parameter(program):
    """All parameters of the program (reference returns the var
    list)."""
    return program.all_parameters()


def get_program_persistable_vars(program):
    """Parameters + persistable buffers; in the TPU-native Program the
    persistable set IS the parameter set (no scope-resident temps)."""
    return program.all_parameters()


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """Reference io.py::save_vars: persist a subset.  The subset
    (vars/predicate) filters the program's parameters by name."""
    import os
    prog = main_program or default_main_program()
    params = prog.all_parameters()
    if vars is not None:
        names = {getattr(v, 'name', v) for v in vars}
        params = [p for p in params if p.name in names]
    elif predicate is not None:
        params = [p for p in params if predicate(p)]
    import pickle
    import numpy as np
    state = {p.name or str(i): np.asarray(p.value)
             for i, p in enumerate(params)}
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, filename or '__all_vars__'),
              'wb') as f:
        pickle.dump(state, f)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import os
    import pickle
    prog = main_program or default_main_program()
    with open(os.path.join(dirname, filename or '__all_vars__'),
              'rb') as f:
        state = pickle.load(f)
    params = prog.all_parameters()
    if vars is not None:
        names = {getattr(v, 'name', v) for v in vars}
        params = [p for p in params if p.name in names]
    elif predicate is not None:
        params = [p for p in params if predicate(p)]
    import jax.numpy as jnp
    for i, p in enumerate(params):
        key = p.name or str(i)
        if key in state:
            p.set_value(jnp.asarray(state[key]))


def batch(reader, batch_size, drop_last=False):
    """fluid.io.batch — the reader-decorator alias."""
    from ..batch import batch as _batch
    return _batch(reader, batch_size, drop_last=drop_last)
