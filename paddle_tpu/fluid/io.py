"""fluid.io — legacy persistence entry points (reference fluid/io.py).

The 1.x API took (executor, dirname) pairs; these adapt onto
paddle_tpu.static's save/load and inference export (StableHLO).
"""
import os

from ..static import io as _sio
from ..static.program import default_main_program

__all__ = ['save_params', 'load_params', 'save_persistables',
           'load_persistables', 'save_inference_model',
           'load_inference_model']


def save_params(executor, dirname, main_program=None, filename=None):
    prog = main_program or default_main_program()
    _sio.save(prog, os.path.join(dirname, filename or 'params'))


def load_params(executor, dirname, main_program=None, filename=None):
    prog = main_program or default_main_program()
    _sio.load(prog, os.path.join(dirname, filename or 'params'))


save_persistables = save_params
load_persistables = load_params


def save_inference_model(dirname, feeded_var_names, target_vars,
                         executor, main_program=None,
                         model_filename=None, params_filename=None,
                         export_for_deployment=True,
                         program_only=False):
    """1.x signature: feed vars are passed by NAME."""
    prog = main_program or default_main_program()
    feed_vars = [prog.feed_vars[n] for n in feeded_var_names]
    _sio.save_inference_model(
        os.path.join(dirname, model_filename or 'model'),
        feed_vars, target_vars, executor, program=prog)


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    return _sio.load_inference_model(
        os.path.join(dirname, model_filename or 'model'), executor)
