"""fluid.entry_attr (reference: python/paddle/fluid/entry_attr.py) —
admission gates for sparse tables; implementation in
distributed/entry_attr.py (enforced by HostOffloadEmbedding)."""
from ..distributed.entry_attr import (  # noqa: F401
    ProbabilityEntry, CountFilterEntry)

__all__ = ['ProbabilityEntry', 'CountFilterEntry']
