"""Legacy 1.x learning-rate decay schedules.

Reference: python/paddle/fluid/dygraph/learning_rate_scheduler.py (the
class forms) and fluid/layers/learning_rate_scheduler.py (the
functional forms used inside static programs).  The 1.x schedules are
parameterized by (decay_steps, decay_rate, staircase) — different
formulas from the 2.0 `optimizer.lr` classes — so these are real
implementations, subclassing LRScheduler for optimizer compatibility.

1.x `begin`/`step` args: the global counter starts at `begin` and
advances by `step` per step() call; `dtype` is accepted for signature
parity (schedules compute in python floats / f32 either way).
"""
import math

from ..optimizer.lr import LRScheduler, ReduceOnPlateau

__all__ = [
    'NoamDecay', 'PiecewiseDecay', 'NaturalExpDecay', 'ExponentialDecay',
    'InverseTimeDecay', 'PolynomialDecay', 'CosineDecay', 'LinearLrWarmup',
    'StepDecay', 'MultiStepDecay', 'LambdaDecay', 'ReduceLROnPlateau',
]


class _LegacyDecay(LRScheduler):
    """Base: 1.x counter semantics (begin + n·step)."""

    def __init__(self, learning_rate, begin=0, step=1, dtype='float32'):
        self._begin = int(begin)
        self._incr = int(step)
        super().__init__(learning_rate, last_epoch=-1)

    @property
    def global_step(self):
        return self._begin + max(self.last_epoch, 0) * self._incr

    def get_lr(self):
        return self._decay(self.global_step)

    def value_at(self, step):
        return self._decay(self._begin + step * self._incr)

    def _decay(self, t):
        raise NotImplementedError


class NoamDecay(_LegacyDecay):
    """lr · d_model^-0.5 · min(t^-0.5, t·warmup^-1.5)
    (reference dygraph/learning_rate_scheduler.py NoamDecay — note the
    1.x argument order d_model, warmup_steps, begin, step, dtype)."""

    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype='float32', learning_rate=1.0):
        self.d_model = float(d_model)
        self.warmup_steps = float(warmup_steps)
        super().__init__(learning_rate, begin, step, dtype)

    def _decay(self, t):
        t = max(float(t), 1.0)
        return self.base_lr * self.d_model ** -0.5 * min(
            t ** -0.5, t * self.warmup_steps ** -1.5)


class PiecewiseDecay(_LegacyDecay):
    def __init__(self, boundaries, values, begin, step=1, dtype='float32'):
        if len(values) != len(boundaries) + 1:
            raise ValueError('values must have one more entry than '
                             'boundaries')
        self.boundaries = [float(b) for b in boundaries]
        self.values = [float(v) for v in values]
        super().__init__(values[0], begin, step, dtype)

    def _decay(self, t):
        for b, v in zip(self.boundaries, self.values):
            if t < b:
                return v
        return self.values[-1]


class NaturalExpDecay(_LegacyDecay):
    """lr · e^(−rate · t/decay_steps) (staircase floors the ratio)."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype='float32'):
        self.decay_steps = float(decay_steps)
        self.decay_rate = float(decay_rate)
        self.staircase = staircase
        super().__init__(learning_rate, begin, step, dtype)

    def _ratio(self, t):
        r = t / self.decay_steps
        return math.floor(r) if self.staircase else r

    def _decay(self, t):
        return self.base_lr * math.exp(-self.decay_rate * self._ratio(t))


class ExponentialDecay(NaturalExpDecay):
    """lr · rate^(t/decay_steps)."""

    def _decay(self, t):
        return self.base_lr * self.decay_rate ** self._ratio(t)


class InverseTimeDecay(NaturalExpDecay):
    """lr / (1 + rate · t/decay_steps)."""

    def _decay(self, t):
        return self.base_lr / (1.0 + self.decay_rate * self._ratio(t))


class PolynomialDecay(_LegacyDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1, dtype='float32'):
        self.decay_steps = float(decay_steps)
        self.end_lr = float(end_learning_rate)
        self.power = float(power)
        self.cycle = cycle
        super().__init__(learning_rate, begin, step, dtype)

    def _decay(self, t):
        t = float(t)
        steps = self.decay_steps
        if self.cycle:
            mult = math.ceil(t / steps) if t > 0 else 1.0
            steps = steps * max(mult, 1.0)
        else:
            t = min(t, steps)
        frac = (1.0 - t / steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class CosineDecay(_LegacyDecay):
    """lr · ½(cos(epoch·π/epochs)+1), epoch = ⌊t/step_each_epoch⌋."""

    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype='float32'):
        self.step_each_epoch = float(step_each_epoch)
        self.epochs = float(epochs)
        super().__init__(learning_rate, begin, step, dtype)

    def _decay(self, t):
        epoch = math.floor(t / self.step_each_epoch)
        return self.base_lr * 0.5 * (
            math.cos(epoch * math.pi / self.epochs) + 1.0)


class LinearLrWarmup(_LegacyDecay):
    """Linear start_lr→end_lr over warmup_steps, then the wrapped
    schedule (a float or another scheduler)."""

    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 begin=1, step=1, dtype='float32'):
        self.wrapped = learning_rate
        self.warmup_steps = float(warmup_steps)
        self.start_lr = float(start_lr)
        self.end_lr = float(end_lr)
        base = learning_rate if isinstance(learning_rate, (int, float)) \
            else learning_rate.base_lr
        super().__init__(base, begin, step, dtype)

    def _decay(self, t):
        if t < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) \
                * t / self.warmup_steps
        if isinstance(self.wrapped, (int, float)):
            return float(self.wrapped)
        return self.wrapped._decay(t) if hasattr(self.wrapped, '_decay') \
            else self.wrapped.value_at(t)


class StepDecay(_LegacyDecay):
    def __init__(self, learning_rate, step_size, decay_rate=0.1):
        self.step_size = int(step_size)
        self.decay_rate = float(decay_rate)
        super().__init__(learning_rate)

    def _decay(self, t):
        return self.base_lr * self.decay_rate ** (int(t) // self.step_size)


class MultiStepDecay(_LegacyDecay):
    def __init__(self, learning_rate, milestones, decay_rate=0.1):
        self.milestones = [int(m) for m in milestones]
        self.decay_rate = float(decay_rate)
        super().__init__(learning_rate)

    def _decay(self, t):
        n = sum(1 for m in self.milestones if t >= m)
        return self.base_lr * self.decay_rate ** n


class LambdaDecay(_LegacyDecay):
    def __init__(self, learning_rate, lr_lambda):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate)

    def _decay(self, t):
        return self.base_lr * self.lr_lambda(int(t))


class ReduceLROnPlateau(ReduceOnPlateau):
    """1.x name/args (decay_rate ↦ factor) over the 2.0 implementation."""

    def __init__(self, learning_rate, mode='min', decay_rate=0.1,
                 patience=10, verbose=False, threshold=1e-4,
                 threshold_mode='rel', cooldown=0, min_lr=0, eps=1e-8,
                 dtype='float32'):
        super().__init__(learning_rate, mode=mode, factor=decay_rate,
                         patience=patience, threshold=threshold,
                         threshold_mode=threshold_mode, cooldown=cooldown,
                         min_lr=min_lr, epsilon=eps, verbose=verbose)
