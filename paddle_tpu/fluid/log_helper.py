"""fluid.log_helper (reference: python/paddle/fluid/log_helper.py)."""
import logging

__all__ = ['get_logger']


def get_logger(name, level, fmt=None):
    lg = logging.getLogger(name)
    lg.setLevel(level)
    if not lg.handlers:
        h = logging.StreamHandler()
        if fmt:
            h.setFormatter(logging.Formatter(fmt))
        lg.addHandler(h)
    lg.propagate = False
    return lg
