"""fluid.transpiler (reference: python/paddle/fluid/transpiler/).

The reference DistributeTranspiler rewrites a Program into trainer +
pserver halves connected by send/recv ops.  The TPU-native stack has
no parameter-server graph split — dense synchronization is XLA
collectives over the mesh (fleet dp) and sparse tables are the
host-offloaded embedding (incubate/host_embedding.py) — so:

- sync_mode transpile returns the trainer program UNCHANGED (the
  collective insertion happens at jit/sharding time, not as a graph
  rewrite), with the endpoint bookkeeping kept for introspection;
- pserver-program extraction raises with a pointer to the PS
  substitute (the brpc fabric is a documented non-goal, SURVEY §2#34).

memory_optimize/release_memory are no-ops in the reference 2.0 as
well (XLA owns buffer liveness here).
"""
import hashlib
import warnings

__all__ = ['DistributeTranspiler', 'memory_optimize', 'release_memory',
           'HashName', 'RoundRobin', 'DistributeTranspilerConfig']


class PSDispatcher:
    """Distribute variable names over pserver endpoints."""

    def __init__(self, pserver_endpoints):
        self._eplist = list(pserver_endpoints)

    @property
    def eplist(self):
        return self._eplist

    def reset(self):
        pass

    def dispatch(self, varlist):
        raise NotImplementedError


class HashName(PSDispatcher):
    """Endpoint = hash(var name) % n (reference transpiler/
    ps_dispatcher.py)."""

    def _hash_block(self, block_str):
        return int(hashlib.md5(str(block_str).encode()).hexdigest(), 16)

    def dispatch(self, varlist):
        return [self._eplist[self._hash_block(getattr(v, 'name', v))
                             % len(self._eplist)]
                for v in varlist]


class RoundRobin(PSDispatcher):
    def __init__(self, pserver_endpoints):
        super().__init__(pserver_endpoints)
        self._step = 0

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eplist[self._step % len(self._eplist)])
            self._step += 1
        return out


class DistributeTranspilerConfig:
    slice_var_up = True
    split_method = RoundRobin
    min_block_size = 8192
    enable_dc_asgd = False
    mode = 'pserver'
    print_log = False
    wait_port = True
    runtime_split_send_recv = False
    sync_mode = True

    def __init__(self):
        pass


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_program = None
        self._endpoints = []

    def transpile(self, trainer_id, program=None, pservers='127.0.0.1:6174',
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint='127.0.0.1:6174'):
        from ..framework import default_main_program
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self._endpoints = pservers.split(',') if isinstance(pservers, str) \
            else list(pservers)
        self._trainer_program = program or default_main_program()

    def get_trainer_program(self, wait_port=True):
        """Collectives are inserted by sharding at jit time, so the
        trainer program is the original program."""
        if self._trainer_program is None:
            raise RuntimeError('call transpile() first')
        return self._trainer_program

    def get_pserver_program(self, endpoint):
        raise NotImplementedError(
            'the brpc parameter-server graph split is a documented '
            'non-goal on TPU; dense sync rides XLA collectives '
            '(distributed.fleet) and sparse tables live in '
            'paddle_tpu.incubate.HostOffloadEmbedding')

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint, pserver_program=None,
                            startup_program=None):
        raise NotImplementedError(
            'pserver startup programs do not exist on TPU; see '
            'get_pserver_program')


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    warnings.warn('memory_optimize is a no-op: XLA owns buffer liveness '
                  '(this matches the reference 2.0 deprecation)')


def release_memory(input_program, skip_opt_set=None):
    warnings.warn('release_memory is a no-op: XLA owns buffer liveness')
