"""fluid.transpiler.collective (reference: python/paddle/fluid/
transpiler/collective.py).

The reference classes rewrite a Program inserting c_allreduce /
c_broadcast ops.  TPU-native, the same effect is a sharding decision:
GradAllReduce marks the program for dp-mesh gradient synchronization
(XLA inserts the reduce-scatter/all-gather), LocalSGD for periodic
parameter averaging (parallel/localsgd.py).  transpile() records the
topology; the ParallelTrainer/fleet path consumes it.
"""

__all__ = ['GradAllReduce', 'LocalSGD']


class Collective:
    def __init__(self, nrings=1):
        self.nrings = nrings
        self.nranks = None
        self.rank = None
        self.endpoints = None

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        self.nranks = len(endpoints) if isinstance(endpoints, (list, tuple)) \
            else len(endpoints.split(','))
        self.rank = rank
        self.endpoints = endpoints
        self.startup_program = startup_program
        self.main_program = main_program
        self._mark(main_program)

    def _mark(self, program):
        raise NotImplementedError


class GradAllReduce(Collective):
    def _mark(self, program):
        if program is not None:
            program._dist_mode = 'grad_allreduce'
            program._dist_nranks = self.nranks


class LocalSGD(Collective):
    def __init__(self, nrings=1, k_steps=4):
        super().__init__(nrings)
        self.k_steps = k_steps

    def _mark(self, program):
        if program is not None:
            program._dist_mode = 'local_sgd'
            program._dist_nranks = self.nranks
            program._local_sgd_k = self.k_steps
