"""fluid.layers 1.x long-tail compat — the remaining reference names.

Reference analogue: /root/reference/python/paddle/fluid/layers/
(nn.py, tensor.py, control_flow.py __all__ names not yet covered by
fluid/layers.py).  Almost everything here adapts a legacy 1.x
signature onto the existing TPU-native implementation; the genuinely
new math (cos_sim, dice_loss, mean_iou, smooth_l1, log_loss,
add_position_encoding, space_to_depth, shuffle_channel,
temporal_shift, affine_channel, affine_grid, fsp_matrix, maxout,
ctc_greedy_decoder, linear_chain_crf/crf_decoding, psroi_pool, …) is
implemented as vectorized jnp here.

LoD-era machinery (DynamicRNN/StaticRNN/IfElse/While/Switch builders,
lod_reset/lod_append/reorder_lod_tensor_by_rank, im2sequence) and
SelectedRows/instag plumbing raise with pointers to the padded-dense
TPU-native equivalents — the same policy as SURVEY.md's LoD note.
"""
import builtins

import numpy as np
import jax
import jax.numpy as jnp

from .. import tensor as _T
from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..nn import functional as _F
from ..tensor._helpers import wrap

__all__ = []


def _register(fn):
    __all__.append(fn.__name__)
    return fn


# -- activations / simple math (legacy signatures) -----------------------

@_register
def one_hot(input, depth, allow_out_of_range=False):
    return _F.one_hot(input, depth)


@_register
def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return _F.normalize(x, p=2, axis=axis, epsilon=epsilon)


@_register
def elu(x, alpha=1.0, name=None):
    return _F.elu(x, alpha)


@_register
def relu6(x, threshold=6.0, name=None):
    return _F.relu6(x)


@_register
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772,
         name=None):
    def fn(v):
        return scale * jnp.where(v > 0, v,
                                 alpha * (jnp.exp(v) - 1.0))
    return apply(fn, wrap(x), op_name='selu')


@_register
def swish(x, beta=1.0, name=None):
    def fn(v):
        return v * jax.nn.sigmoid(beta * v)
    return apply(fn, wrap(x), op_name='swish')


@_register
def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    def fn(v):
        return v * jnp.clip(v + offset, 0.0, threshold) / scale
    return apply(fn, wrap(x), op_name='hard_swish')


@_register
def mish(x, threshold=20, name=None):
    return _F.mish(x)


@_register
def leaky_relu(x, alpha=0.02, name=None):
    return _F.leaky_relu(x, negative_slope=alpha)


@_register
def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _T.clip(x, t_min, t_max)


@_register
def soft_relu(x, threshold=40.0, name=None):
    def fn(v):
        return jnp.log1p(jnp.exp(jnp.clip(v, -threshold, threshold)))
    return apply(fn, wrap(x), op_name='soft_relu')


@_register
def pow(x, factor=1.0, name=None):
    return _T.pow(x, factor)


@_register
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    s = scale

    def fn(v):
        sv = getattr(s, 'value', s)
        out = v * sv + bias if bias_after_scale else (v + bias) * sv
        return out
    out = apply(fn, wrap(x), op_name='scale')
    if act is not None:
        out = getattr(_F, act)(out)
    return out


@_register
def sign(x, name=None):
    return _T.sign(x)


@_register
def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """Legacy mul op: flatten x to 2-D at x_num_col_dims, y at
    y_num_col_dims, matmul."""
    def fn(a, b):
        am = a.reshape(int(np.prod(a.shape[:x_num_col_dims])), -1)
        bm = b.reshape(int(np.prod(b.shape[:y_num_col_dims])), -1)
        out = am @ bm
        # reference output keeps the leading/trailing dims:
        # x.shape[:xd] + y.shape[yd:]
        return out.reshape(a.shape[:x_num_col_dims]
                           + b.shape[y_num_col_dims:])
    return apply(fn, wrap(x), wrap(y), op_name='mul')


@_register
def sum(x, name=None):
    return _T.add_n(x) if isinstance(x, (list, tuple)) else x


@_register
def elementwise_mod(x, y, axis=-1, act=None, name=None):
    from .layers import _ew
    return _ew(_T.mod, x, y, axis, act)


@_register
def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    from .layers import _ew
    return _ew(_T.floor_divide, x, y, axis, act)


@_register
def logical_and(x, y, out=None, name=None):
    return _T.logical_and(x, y)


@_register
def logical_or(x, y, out=None, name=None):
    return _T.logical_or(x, y)


@_register
def logical_xor(x, y, out=None, name=None):
    return _T.logical_xor(x, y)


@_register
def logical_not(x, out=None, name=None):
    return _T.logical_not(x)


@_register
def clip_by_norm(x, max_norm, name=None):
    def fn(v):
        norm = jnp.sqrt(jnp.maximum(jnp.sum(v * v), 1e-12))
        return jnp.where(norm > max_norm, v * (max_norm / norm), v)
    return apply(fn, wrap(x), op_name='clip_by_norm')


@_register
def maxout(x, groups, name=None, axis=1):
    return _F.maxout(x, groups, axis=axis)


@_register
def unbind(input, axis=0):
    return _T.unbind(input, axis)


@_register
def unstack(x, axis=0, num=None):
    return _T.unstack(x, axis, num)


@_register
def unique(x, dtype='int32'):
    """Eager-only (dynamic output shape): (unique values, index map
    such that x = out[index]) like the reference op."""
    v = np.asarray(getattr(x, 'value', x))
    vals, first, inv = np.unique(v, return_index=True,
                                 return_inverse=True)
    # reference preserves FIRST-OCCURRENCE order, not sorted order
    order = np.argsort(first)
    out = vals[order]
    remap = np.empty_like(order)
    remap[order] = np.arange(len(order))
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(remap[inv].astype(dtype))))


@_register
def unique_with_counts(x, dtype='int32'):
    v = np.asarray(getattr(x, 'value', x))
    vals, first, inv, count = np.unique(
        v, return_index=True, return_inverse=True,
        return_counts=True)
    order = np.argsort(first)
    remap = np.empty_like(order)
    remap[order] = np.arange(len(order))
    return (Tensor(jnp.asarray(vals[order])),
            Tensor(jnp.asarray(remap[inv].astype(dtype))),
            Tensor(jnp.asarray(count[order].astype(dtype))))


@_register
def expand_as(x, target_tensor, name=None):
    return _T.expand_as(x, target_tensor)


@_register
def strided_slice(input, axes, starts, ends, strides):
    return _T.strided_slice(input, axes, starts, ends, strides)


@_register
def size(input):
    return _T.numel(input)


@_register
def gather_tree(ids, parents):
    from ..nn.decode import gather_tree as _gt
    return _gt(ids, parents)


# -- padding / resize / crop ---------------------------------------------

@_register
def pad(x, paddings, pad_value=0.0, name=None):
    """Legacy pad: flat [before0, after0, before1, after1, ...]."""
    def fn(v):
        cfg = [(int(paddings[2 * i]), int(paddings[2 * i + 1]))
               for i in builtins.range(v.ndim)]
        return jnp.pad(v, cfg, constant_values=pad_value)
    return apply(fn, wrap(x), op_name='pad')


@_register
def pad_constant_like(x, y, pad_value=0.0, name=None):
    def fn(xv, yv):
        cfg = [(0, xs - ys) for xs, ys in zip(xv.shape, yv.shape)]
        return jnp.pad(yv, cfg, constant_values=pad_value)
    return apply(fn, wrap(x), wrap(y), op_name='pad_constant_like')


@_register
def pad2d(input, paddings=(0, 0, 0, 0), mode='constant',
          pad_value=0.0, data_format='NCHW', name=None):
    t, b, l, r = [int(p) for p in paddings]
    if data_format == 'NCHW':
        pad_cfg = [0, 0, 0, 0, t, b, l, r]
    else:
        pad_cfg = [0, 0, t, b, l, r, 0, 0]
    jmode = {'constant': 'constant', 'reflect': 'reflect',
             'edge': 'edge'}[mode]

    def fn(v):
        cfg = [(pad_cfg[2 * i], pad_cfg[2 * i + 1])
               for i in builtins.range(4)]
        if jmode == 'constant':
            return jnp.pad(v, cfg, constant_values=pad_value)
        return jnp.pad(v, cfg, mode=jmode)
    return apply(fn, wrap(input), op_name='pad2d')


@_register
def crop_tensor(x, shape=None, offsets=None, name=None):
    """Reference fluid.layers.crop_tensor — delegates to
    tensor.manipulation.crop, which carries the full semantics
    (-1 keeps offset..end of the dim; shape=None keeps the input
    shape)."""
    from ..tensor.manipulation import crop
    return crop(x, shape=shape, offsets=offsets, name=name)


@_register
def image_resize(input, out_shape=None, scale=None, name=None,
                 resample='BILINEAR', actual_shape=None,
                 align_corners=True, align_mode=1,
                 data_format='NCHW'):
    mode = {'BILINEAR': 'bilinear', 'NEAREST': 'nearest',
            'TRILINEAR': 'trilinear', 'LINEAR': 'linear',
            'BICUBIC': 'bicubic'}[resample.upper()]
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode=mode, align_corners=align_corners,
                          align_mode=align_mode,
                          data_format=data_format)


@_register
def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True,
                    align_mode=1, data_format='NCHW'):
    return image_resize(input, out_shape, scale, name, 'BILINEAR',
                        actual_shape, align_corners, align_mode,
                        data_format)


@_register
def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True,
                   data_format='NCHW'):
    return image_resize(input, out_shape, scale, name, 'NEAREST',
                        actual_shape, align_corners, 1, data_format)


@_register
def resize_linear(input, out_shape=None, scale=None, name=None,
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format='NCW'):
    return image_resize(input, out_shape, scale, name, 'LINEAR',
                        actual_shape, align_corners, align_mode,
                        data_format)


@_register
def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True,
                     align_mode=1, data_format='NCDHW'):
    return image_resize(input, out_shape, scale, name, 'TRILINEAR',
                        actual_shape, align_corners, align_mode,
                        data_format)


@_register
def image_resize_short(input, out_short_len, resample='BILINEAR'):
    H, W = input.shape[2], input.shape[3]
    if H <= W:
        new = (out_short_len, int(round(W * out_short_len / H)))
    else:
        new = (int(round(H * out_short_len / W)), out_short_len)
    return image_resize(input, out_shape=new, resample=resample)


@_register
def random_crop(x, shape, seed=None):
    """Eager random crop of the trailing len(shape) dims."""
    if seed is None:
        from ..core import rng as rng_mod
        # next_key advances the global stream: a fresh crop per call
        seed = int(np.asarray(rng_mod.next_key())[-1])
    rs = np.random.RandomState(int(seed) & 0x7fffffff)
    v = getattr(x, 'value', x)
    nd = len(shape)
    lead = v.ndim - nd
    offs = [rs.randint(0, v.shape[lead + i] - shape[i] + 1)
            for i in builtins.range(nd)]
    sl = (slice(None),) * lead + tuple(
        slice(o, o + s) for o, s in zip(offs, shape))

    def fn(vv):
        return vv[sl]
    return apply(fn, wrap(x), op_name='random_crop')


# -- pooling / layout ops ------------------------------------------------

@_register
def pool3d(input, pool_size=-1, pool_type='max', pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format='NCDHW'):
    if global_pooling:
        ps = input.shape[2:]
        return (_F.avg_pool3d(input, ps) if pool_type == 'avg'
                else _F.max_pool3d(input, ps))
    if pool_type == 'avg':
        return _F.avg_pool3d(input, pool_size, stride=pool_stride,
                             padding=pool_padding,
                             ceil_mode=ceil_mode)
    return _F.max_pool3d(input, pool_size, stride=pool_stride,
                         padding=pool_padding, ceil_mode=ceil_mode)


@_register
def adaptive_pool2d(input, pool_size, pool_type='max',
                    require_index=False, name=None):
    if require_index:
        raise NotImplementedError('require_index is not supported')
    return (_F.adaptive_avg_pool2d(input, pool_size)
            if pool_type == 'avg'
            else _F.adaptive_max_pool2d(input, pool_size))


@_register
def adaptive_pool3d(input, pool_size, pool_type='max',
                    require_index=False, name=None):
    if require_index:
        raise NotImplementedError('require_index is not supported')
    return (_F.adaptive_avg_pool3d(input, pool_size)
            if pool_type == 'avg'
            else _F.adaptive_max_pool3d(input, pool_size))


@_register
def space_to_depth(x, blocksize, name=None):
    def fn(v):
        N, C, H, W = v.shape
        b = int(blocksize)
        v = v.reshape(N, C, H // b, b, W // b, b)
        v = v.transpose(0, 3, 5, 1, 2, 4)
        return v.reshape(N, C * b * b, H // b, W // b)
    return apply(fn, wrap(x), op_name='space_to_depth')


@_register
def shuffle_channel(x, group, name=None):
    def fn(v):
        N, C, H, W = v.shape
        g = int(group)
        return v.reshape(N, g, C // g, H, W).transpose(
            0, 2, 1, 3, 4).reshape(N, C, H, W)
    return apply(fn, wrap(x), op_name='shuffle_channel')


@_register
def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """TSM shift (reference temporal_shift_op): shift the first
    C*ratio channels backward in time, the next C*ratio forward."""
    def fn(v):
        NT, C, H, W = v.shape
        N = NT // seg_num
        v = v.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        pad = jnp.pad(v, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        back = pad[:, :seg_num, :c1]          # t-1 -> t
        fwd = pad[:, 2:, c1:c2]               # t+1 -> t
        keep = v[:, :, c2:]
        out = jnp.concatenate([back, fwd, keep], axis=2)
        return out.reshape(NT, C, H, W)
    return apply(fn, wrap(x), op_name='temporal_shift')


@_register
def pixel_shuffle(x, upscale_factor):
    return _F.pixel_shuffle(x, upscale_factor)


@_register
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1,
           name=None):
    return _F.unfold(x, kernel_sizes, strides=strides,
                     paddings=paddings, dilations=dilations)


@_register
def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    from ..static.nn import deform_conv2d as _dc
    return _dc(input, offset, mask if modulated else None,
               num_filters, filter_size, stride=stride,
               padding=padding, dilation=dilation,
               param_attr=param_attr, bias_attr=bias_attr)


# -- losses / metrics ----------------------------------------------------

@_register
def cos_sim(X, Y):
    def fn(a, b):
        a2 = a.reshape(a.shape[0], -1)
        b2 = b.reshape(b.shape[0], -1) if b.shape[0] == a.shape[0] \
            else jnp.broadcast_to(b.reshape(1, -1),
                                  (a.shape[0], b.size))
        num = jnp.sum(a2 * b2, axis=1, keepdims=True)
        den = (jnp.linalg.norm(a2, axis=1, keepdims=True)
               * jnp.linalg.norm(b2, axis=1, keepdims=True))
        return num / jnp.maximum(den, 1e-12)
    return apply(fn, wrap(X), wrap(Y), op_name='cos_sim')


@_register
def smooth_l1(x, y, inside_weight=None, outside_weight=None,
              sigma=None):
    """Legacy smooth_l1 op: per-sample SUM of the huber terms with
    the sigma^2 transition point, [N, 1]."""
    s2 = 1.0 if sigma is None else float(sigma) ** 2

    def fn(a, b, *ws):
        iw = ws[0] if ws else jnp.ones_like(a)
        ow = ws[1] if len(ws) > 1 else jnp.ones_like(a)
        d = (a - b) * iw
        ad = jnp.abs(d)
        loss = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2,
                         ad - 0.5 / s2)
        loss = loss * ow
        return jnp.sum(loss.reshape(a.shape[0], -1), axis=1,
                       keepdims=True)
    args = [wrap(x), wrap(y)]
    if inside_weight is not None or outside_weight is not None:
        args.append(wrap(inside_weight)
                    if inside_weight is not None
                    else wrap(_T.ones_like(x)))
        args.append(wrap(outside_weight)
                    if outside_weight is not None
                    else wrap(_T.ones_like(x)))
    return apply(fn, *args, op_name='smooth_l1')


@_register
def dice_loss(input, label, epsilon=1e-5, name=None):
    def fn(p, y):
        if y.ndim == p.ndim and y.shape[-1] == 1:
            y = y[..., 0]
        y1 = jax.nn.one_hot(y, p.shape[-1], dtype=p.dtype)
        red = tuple(builtins.range(1, p.ndim))
        inse = jnp.sum(p * y1, axis=red)
        denom = jnp.sum(p, axis=red) + jnp.sum(y1, axis=red)
        # per-sample dice, then the batch mean (reference nn.py:7104)
        return jnp.mean(1.0 - 2.0 * inse / (denom + epsilon))
    return apply(fn, wrap(input), wrap(label), op_name='dice_loss')


@_register
def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, y):
        return (-y * jnp.log(p + epsilon)
                - (1.0 - y) * jnp.log(1.0 - p + epsilon))
    return apply(fn, wrap(input), wrap(label), op_name='log_loss')


@_register
def mean_iou(input, label, num_classes):
    """(mean_iou, out_wrong, out_correct) over a class-id prediction
    map (reference mean_iou_op)."""
    def fn(p, y):
        p = p.reshape(-1)
        y = y.reshape(-1)
        n = int(num_classes)
        hit = (p == y).astype(jnp.int32)
        correct = jnp.zeros(n, jnp.int32).at[y].add(hit)
        # the reference increments wrong at BOTH the label and the
        # prediction class of each mismatch (mean_iou_op.h)
        wrong = (jnp.zeros(n, jnp.int32).at[y].add(1 - hit)
                 .at[p].add(1 - hit))
        union = wrong + correct
        present = union > 0
        iou = jnp.where(present,
                        correct / jnp.maximum(union, 1), 0.0)
        miou = jnp.sum(iou) / jnp.maximum(
            jnp.sum(present.astype(jnp.int32)), 1)
        return miou.astype(jnp.float32), wrong, correct
    return apply(fn, wrap(input), wrap(label), op_name='mean_iou')


@_register
def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix (reference fsp_op): [N, Cx,
    Cy] = x·y^T over the spatial dims, normalized by H*W."""
    def fn(a, b):
        N, Cx, H, W = a.shape
        Cy = b.shape[1]
        am = a.reshape(N, Cx, H * W)
        bm = b.reshape(N, Cy, H * W)
        return jnp.einsum('nch,ndh->ncd', am, bm) / (H * W)
    return apply(fn, wrap(x), wrap(y), op_name='fsp_matrix')


# -- misc ----------------------------------------------------------------

@_register
def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format='NCHW'):
    return _F.local_response_norm(input, n, alpha=alpha, beta=beta,
                                  k=k, data_format=data_format)


@_register
def grid_sampler(x, grid, name=None):
    return _F.grid_sample(x, grid)


@_register
def affine_channel(x, scale=None, bias=None, data_layout='NCHW',
                   act=None, name=None):
    def fn(v, s, b):
        if data_layout == 'NCHW':
            s = s.reshape(1, -1, 1, 1)
            b = b.reshape(1, -1, 1, 1)
        return v * s + b
    out = apply(fn, wrap(x), wrap(scale), wrap(bias),
                op_name='affine_channel')
    if act is not None:
        out = getattr(_F, act)(out)
    return out


@_register
def affine_grid(theta, out_shape, name=None):
    """2-D affine sampling grid (reference affine_grid_op): theta
    [N, 2, 3] x normalized target coords -> grid [N, H, W, 2]."""
    def fn(t):
        N = t.shape[0]
        shp = [int(s) for s in out_shape]
        H, W = shp[2], shp[3]
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        xg, yg = jnp.meshgrid(xs, ys)           # [H, W]
        ones = jnp.ones_like(xg)
        coords = jnp.stack([xg, yg, ones], -1)  # [H, W, 3]
        return jnp.einsum('nij,hwj->nhwi', t.astype(jnp.float32),
                          coords)
    return apply(fn, wrap(theta), op_name='affine_grid')


@_register
def add_position_encoding(input, alpha, beta, name=None):
    """Sinusoidal position encoding mixed in (reference
    add_position_encoding_op): out = alpha*x + beta*pe."""
    def fn(v):
        N, T, C = v.shape
        half = C // 2
        pos = jnp.arange(T, dtype=jnp.float32)[:, None]
        div = jnp.power(10000.0,
                        jnp.arange(half, dtype=jnp.float32) / half)
        ang = pos / div                          # [T, half]
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=1)
        if pe.shape[1] < C:   # odd C: pad the last channel with 0
            pe = jnp.pad(pe, ((0, 0), (0, C - pe.shape[1])))
        return alpha * v + beta * pe[None, :, :C].astype(v.dtype)
    return apply(fn, wrap(input), op_name='add_position_encoding')


@_register
def sampling_id(x, min=0.0, max=1.0, seed=0, dtype='float32'):
    """Sample one category id per row of a probability matrix."""
    if seed == 0:
        from ..core import rng as rng_mod
        seed = int(np.asarray(rng_mod.next_key())[-1])

    def fn(p):
        key = jax.random.PRNGKey(int(seed))
        return jax.random.categorical(
            key, jnp.log(jnp.maximum(p, 1e-20)), axis=-1)
    return apply(fn, wrap(x), op_name='sampling_id')


@_register
def uniform_random_batch_size_like(input, shape, dtype='float32',
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    shp = list(shape)
    shp[output_dim_idx] = input.shape[input_dim_idx]
    return _T.uniform(shp, dtype=dtype, min=min, max=max, seed=seed)


@_register
def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0,
                                    std=1.0, seed=0, dtype='float32'):
    shp = list(shape)
    shp[output_dim_idx] = input.shape[input_dim_idx]
    return _T.cast(_T.normal(mean=mean, std=std, shape=shp), dtype)


@_register
def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    shp = list(shape)
    shp[output_dim_idx] = input.shape[input_dim_idx]
    return _T.full(shp, value, dtype=dtype)


@_register
def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """A persistent step counter (reference uses a global var; eager
    equivalent: a module-level counter per name)."""
    name = counter_name or '@STEP_COUNTER@'
    cur = _STEP_COUNTERS.get(name)
    if cur is None:
        cur = begin
    else:
        cur = cur + step
    _STEP_COUNTERS[name] = cur
    return Tensor(jnp.asarray([cur], jnp.int64))


_STEP_COUNTERS = {}


@_register
def ctc_greedy_decoder(input, blank, input_length=None,
                       padding_value=0, name=None):
    """Greedy CTC decode (reference ctc_greedy_decoder): argmax per
    step, merge repeats, drop blanks.  Padded-dense redesign: input
    [N, T, C] (batch-major), returns (decoded [N, T] padded with
    padding_value, seq_len [N])."""
    def fn(p, *ls):
        N, T, C = p.shape
        ids = jnp.argmax(p, axis=-1)             # [N, T]
        prev = jnp.concatenate(
            [jnp.full((N, 1), -1, ids.dtype), ids[:, :-1]], axis=1)
        keep = (ids != blank) & (ids != prev)
        if ls:
            tmask = jnp.arange(T)[None, :] < ls[0].reshape(-1, 1)
            keep = keep & tmask
        pos = jnp.where(keep, jnp.cumsum(keep, axis=1) - 1, T)
        rows = jnp.broadcast_to(jnp.arange(N)[:, None], (N, T))
        # drop-mode scatter: dropped steps target column T (OOB)
        out = jnp.full((N, T), padding_value, ids.dtype).at[
            rows.reshape(-1),
            jnp.where(pos < T, pos, T).reshape(-1)].set(
                ids.reshape(-1), mode='drop')
        lens = jnp.sum(keep, axis=1)
        return out, lens
    args = [wrap(input)]
    if input_length is not None:
        args.append(wrap(input_length))
    return apply(fn, *args, op_name='ctc_greedy_decoder')


@_register
def linear_chain_crf(input, label, param_attr=None, length=None,
                     transition=None):
    """Linear-chain CRF negative log-likelihood (reference
    linear_chain_crf_op): transition params [C+2, C] (start/stop rows
    first), emissions [N, T, C], labels [N, T].  Returns per-sequence
    NLL [N, 1]; the forward algorithm is one lax.scan (log-space).
    Dense redesign of the reference's LoD sequences (use `length` for
    ragged batches)."""
    C = input.shape[-1]
    if transition is None:
        from ..tensor.creation import create_parameter
        transition = create_parameter(
            [C + 2, C], str(input.dtype).replace('paddle.', ''),
            attr=param_attr)

    def fn(emit, lab, trans, *ls):
        N, T, Cc = emit.shape
        emit = emit.astype(jnp.float32)
        start = trans[0]
        stop = trans[1]
        A = trans[2:].astype(jnp.float32)       # [C, C]
        lens = ls[0].reshape(-1) if ls else jnp.full((N,), T)

        def step(carry, xs):
            alpha, t = carry
            e_t = xs                              # [N, C]
            nxt = jax.scipy.special.logsumexp(
                alpha[:, :, None] + A[None], axis=1) + e_t
            alive = (t < lens)[:, None]
            alpha = jnp.where(alive, nxt, alpha)
            return (alpha, t + 1), None

        alpha0 = start[None] + emit[:, 0]
        (alphaT, _), _ = lax.scan(
            step, (alpha0, jnp.ones((), jnp.int32)),
            jnp.swapaxes(emit[:, 1:], 0, 1))
        logZ = jax.scipy.special.logsumexp(alphaT + stop[None],
                                           axis=1)
        # score of the gold path
        tmask = jnp.arange(T)[None, :] < lens[:, None]
        lab_c = jnp.clip(lab, 0, Cc - 1)
        e_score = jnp.sum(
            jnp.take_along_axis(emit, lab_c[..., None],
                                axis=2)[..., 0] * tmask, axis=1)
        pair_mask = (jnp.arange(1, T)[None, :]
                     < lens[:, None])            # [N, T-1]
        t_score = jnp.sum(
            A[lab_c[:, :-1], lab_c[:, 1:]] * pair_mask, axis=1)
        last = jnp.clip(lens - 1, 0, T - 1)
        lab_last = jnp.take_along_axis(lab_c, last[:, None],
                                       axis=1)[:, 0]
        gold = (start[lab_c[:, 0]] + e_score + t_score
                + stop[lab_last])
        return (logZ - gold)[:, None]

    args = [wrap(input), wrap(label), transition]
    if length is not None:
        args.append(wrap(length))
    return apply(fn, *args, op_name='linear_chain_crf')


@_register
def psroi_pool(input, rois, output_channels, spatial_scale,
               pooled_height, pooled_width, rois_num=None, name=None):
    """Position-sensitive RoI average pooling (reference
    psroi_pool_op): input channels C = output_channels*ph*pw, each
    output bin averages ITS OWN channel slice over the bin region."""
    # roi_pool's mask machinery with a position-sensitive mean
    from ..vision.detection import _roi_batch_ids

    def fn2(x, bx, bn):
        N, C, H, W = x.shape
        R = bx.shape[0]
        ph, pw = int(pooled_height), int(pooled_width)
        oc = int(output_channels)
        bids = _roi_batch_ids(bn, R)

        def one_roi(roi, bid):
            x1 = jnp.round(roi[0] * spatial_scale)
            y1 = jnp.round(roi[1] * spatial_scale)
            x2 = jnp.round(roi[2] * spatial_scale)
            y2 = jnp.round(roi[3] * spatial_scale)
            rh = jnp.maximum(y2 - y1, 0.1)
            rw = jnp.maximum(x2 - x1, 0.1)
            bin_h = rh / ph
            bin_w = rw / pw
            pidx = jnp.arange(ph)[:, None]
            hh = jnp.arange(H)[None, :]
            hstart = jnp.clip(jnp.floor(pidx * bin_h + y1), 0, H)
            hend = jnp.clip(jnp.ceil((pidx + 1) * bin_h + y1), 0, H)
            mask_h = ((hh >= hstart) & (hh < hend)).astype(x.dtype)
            qidx = jnp.arange(pw)[:, None]
            ww = jnp.arange(W)[None, :]
            wstart = jnp.clip(jnp.floor(qidx * bin_w + x1), 0, W)
            wend = jnp.clip(jnp.ceil((qidx + 1) * bin_w + x1), 0, W)
            mask_w = ((ww >= wstart) & (ww < wend)).astype(x.dtype)
            img = x[bid].reshape(oc, ph, pw, H, W)
            # bin (i, j) of output channel k reads input channel
            # k*ph*pw + i*pw + j — the position-sensitive layout
            sums = jnp.einsum('opqhw,ph,qw->opq', img, mask_h,
                              mask_w)
            area = (jnp.einsum('ph,qw->pq', mask_h, mask_w))
            return sums / jnp.maximum(area, 1.0)

        return jax.vmap(one_roi)(bx, bids)

    if rois_num is None:
        rois_num = _T.full([input.shape[0]],
                           rois.shape[0] // input.shape[0], 'int32')
    return apply(fn2, wrap(input), wrap(rois), wrap(rois_num),
                 op_name='psroi_pool')


# -- tensor.py names -----------------------------------------------------

@_register
def tensor_array_to_tensor(input, axis=1, name=None,
                           use_stack=False):
    arrs = list(input) if isinstance(input, (list, tuple)) \
        else input.to_list()
    out = _T.stack(arrs, axis=axis) if use_stack else \
        _T.concat(arrs, axis=axis)
    per = [1 if use_stack else a.shape[axis] for a in arrs]
    sizes = Tensor(jnp.asarray(per, jnp.int32))
    return out, sizes


@_register
def sums(input, out=None):
    res = _T.add_n(list(input))
    if out is not None:
        out.set_value(res.value)
        return out
    return res


@_register
def has_inf(x):
    return _T.any(_T.isinf(x))


@_register
def has_nan(x):
    return _T.any(_T.isnan(x))


@_register
def isfinite(x):
    return _T.all(_T.isfinite(x))


@_register
def range(start, end, step, dtype, name=None):
    return _T.arange(start, end, step, dtype)


@_register
def linspace(start, stop, num, dtype=None, name=None):
    return _T.linspace(start, stop, num, dtype)


@_register
def diag(diagonal):
    return _T.diag(diagonal)


@_register
def eye(num_rows, num_columns=None, batch_shape=None,
        dtype='float32', name=None):
    out = _T.eye(num_rows, num_columns, dtype=dtype)
    if batch_shape:
        for _ in batch_shape:
            out = _T.unsqueeze(out, axis=0)
        out = _T.expand(out, list(batch_shape) + list(out.shape[-2:]))
    return out


@_register
def triu(input, diagonal=0, name=None):
    return _T.triu(input, diagonal)


# -- control_flow.py names -----------------------------------------------

@_register
def create_array(dtype):
    from ..tensor.array import create_array as _ca
    return _ca(dtype)


@_register
def array_length(array):
    from ..tensor.array import array_length as _al
    return _al(array)


@_register
def less_than(x, y, force_cpu=None, cond=None, name=None):
    return _T.less_than(x, y)


@_register
def less_equal(x, y, cond=None, name=None):
    return _T.less_equal(x, y)


@_register
def greater_than(x, y, cond=None, name=None):
    return _T.greater_than(x, y)


@_register
def greater_equal(x, y, cond=None, name=None):
    return _T.greater_equal(x, y)


@_register
def equal(x, y, cond=None, name=None):
    return _T.equal(x, y)


@_register
def not_equal(x, y, cond=None, name=None):
    return _T.not_equal(x, y)


@_register
def is_empty(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) == 0))


@_register
def Assert(cond, data=None, summarize=20, name=None):
    """Eager assert (the reference op halts the Executor)."""
    v = np.asarray(getattr(cond, 'value', cond))
    if not bool(v.all()):
        payload = [np.asarray(getattr(d, 'value', d))[:summarize]
                   for d in (data or [])]
        raise AssertionError(f'fluid.layers.Assert failed: {payload}')
    return cond


# -- LoD-era / SelectedRows non-goals ------------------------------------

_LEGACY_NON_GOALS = {
    'DynamicRNN': 'use nn.RNN / lax.scan (LoD loop builder)',
    'StaticRNN': 'use nn.RNN / lax.scan (graph loop builder)',
    'IfElse': 'use fluid.layers.cond',
    'While': 'use fluid.layers.while_loop',
    'Switch': 'use fluid.layers.case/switch_case',
    'lod_reset': 'LoD is redesigned away (padded-dense + lengths)',
    'lod_append': 'LoD is redesigned away (padded-dense + lengths)',
    'reorder_lod_tensor_by_rank': 'LoD is redesigned away',
    'im2sequence': 'use fluid.layers.unfold (padded-dense)',
    'merge_selected_rows': 'SelectedRows does not exist here',
    'get_tensor_from_selected_rows': 'SelectedRows does not exist '
                                     'here',
    'continuous_value_model': 'BoxPS/CVM parameter-server machinery',
    'filter_by_instag': 'instag PS-era filtering',
    'similarity_focus': 'niche op with no 2.x surface',
    'hash': 'pyramid-hash machinery (documented non-goal)',
    'prroi_pool': 'precise-RoI integral pooling; use roi_align',
    'deformable_roi_pooling': 'offset-deformed RoI pooling; use '
                              'roi_align (+ deform_conv2d for the '
                              'deformable pathway)',
    'inplace_abn': 'use batch_norm + activation (no in-place '
                   'semantics on TPU)',
    'chunk_eval': 'host-side chunking metric; compute F1 from '
                  'crf_decoding output with sklearn-style tooling',
}


def __getattr__(name):
    if name in _LEGACY_NON_GOALS:
        raise NotImplementedError(
            f'fluid.layers.{name} is a documented non-goal: '
            f'{_LEGACY_NON_GOALS[name]}.')
    raise AttributeError(name)


from jax import lax  # noqa: E402  (used by crf/ctc above)
