"""fluid.clip (reference: python/paddle/fluid/clip.py).  The clip
implementations live in nn/clip.py; the 1.x GradientClipBy* names are
the same classes."""
from ..nn.clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
    ErrorClipByValue, set_gradient_clip, get_gradient_clip)

# 1.x class names
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm

__all__ = ['set_gradient_clip', 'get_gradient_clip', 'ErrorClipByValue',
           'ClipGradByValue',
           'ClipGradByNorm', 'ClipGradByGlobalNorm',
           'GradientClipByValue', 'GradientClipByNorm',
           'GradientClipByGlobalNorm']
