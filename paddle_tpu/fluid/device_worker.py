"""fluid.device_worker (reference: python/paddle/fluid/device_worker.py).

The reference DeviceWorkers emit protobuf trainer descriptors that pick
a C++ execution strategy (hogwild threads, downpour PS pull/push,
pipeline sections).  TPU-native execution is one compiled XLA program,
so these classes carry the same configuration knobs as plain dicts and
`_gen_worker_desc` records the chosen strategy on the TrainerDesc —
the executor's dataset-training loop consults it for sparse-PS and
pipeline behavior.
"""

__all__ = ['DeviceWorker', 'Hogwild', 'DownpourSGD', 'Section',
           'DownpourSGDOPT']


class DeviceWorker:
    def __init__(self):
        self._program = None
        self._infer = None

    def _set_infer(self, infer=False):
        self._infer = infer

    def _set_fleet_desc(self, fleet_desc):
        self._fleet_desc = fleet_desc

    def _set_program(self, program):
        self._program = program

    def _gen_worker_desc(self, trainer_desc):
        raise NotImplementedError(
            'DeviceWorker is abstract; use Hogwild/DownpourSGD/Section')


class Hogwild(DeviceWorker):
    """Lock-free multi-thread host loop feeding the compiled step."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.proto['device_worker_name'] = 'HogwildWorker'
        if self._infer:
            trainer_desc.proto['hogwild_param'] = {
                'skip_ops': ['feed', 'fetch']}


class DownpourSGD(DeviceWorker):
    """Sparse-PS worker: pulls/pushes through the host-offloaded
    embedding tables (incubate/host_embedding.py)."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.proto['device_worker_name'] = 'DownpourWorker'
        trainer_desc.proto['downpour_param'] = {
            'push_sparse': not self._infer,
            'push_dense': not self._infer,
        }


class DownpourSGDOPT(DownpourSGD):
    def _gen_worker_desc(self, trainer_desc):
        super()._gen_worker_desc(trainer_desc)
        trainer_desc.proto['device_worker_name'] = 'DownpourWorkerOpt'


class Section(DeviceWorker):
    """Pipeline-parallel section worker; the TPU-native pipeline is the
    1F1B shard_map engine (parallel/pipeline_1f1b.py)."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.proto['device_worker_name'] = 'SectionWorker'
        pipeline = getattr(self._program, '_pipeline_opt', None) or {}
        trainer_desc.proto['section_param'] = {
            'num_microbatches': pipeline.get('num_microbatches', 1)}
