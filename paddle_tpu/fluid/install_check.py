"""fluid.install_check (reference: python/paddle/fluid/install_check.py).
run_check lives in paddle_tpu.utils (trains a tiny model end-to-end)."""
from ..utils import run_check  # noqa: F401

__all__ = ['run_check']
