"""fluid.data_feed_desc (reference: python/paddle/fluid/
data_feed_desc.py).

The reference parses a protobuf-text DataFeedDesc.  TPU-native: a
protobuf-free mini-parser for the same `multi_slot_desc { slots {...} }`
text format (the one fleet data generators emit), holding slots as
plain dicts; `desc()` renders the config back as proto text so files
round-trip.
"""
import re

__all__ = ['DataFeedDesc']

_KV = re.compile(r'(\w+)\s*:\s*("[^"]*"|\S+)')


class DataFeedDesc:
    def __init__(self, proto_file):
        self.name = 'MultiSlotDataFeed'
        self.batch_size = 32
        self.pipe_command = 'cat'
        self.slots = []
        with open(proto_file) as f:
            self._parse(f.read())
        self._by_name = {s['name']: i for i, s in enumerate(self.slots)}

    def _parse(self, text):
        # block structure: top-level key:value pairs + slots { ... }
        depth = 0
        cur = None
        for line in text.splitlines():
            stripped = line.strip()
            if stripped.endswith('{'):
                depth += 1
                if stripped.startswith('slots'):
                    cur = {'name': '', 'type': 'float', 'is_dense': False,
                           'is_used': False, 'shape': []}
                    self.slots.append(cur)
                continue
            if stripped == '}':
                depth -= 1
                if depth <= 1:
                    cur = None
                continue
            for key, raw in _KV.findall(stripped):
                val = raw.strip('"')
                if val in ('true', 'false'):
                    val = val == 'true'
                elif re.fullmatch(r'-?\d+', val):
                    val = int(val)
                if cur is not None:
                    if key == 'shape':
                        cur['shape'].append(val)
                    else:
                        cur[key] = val
                elif key == 'batch_size':
                    self.batch_size = val
                elif key == 'name':
                    self.name = val
                elif key == 'pipe_command':
                    self.pipe_command = val

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_pipe_command(self, pipe_command):
        self.pipe_command = pipe_command

    def set_dense_slots(self, dense_slots_name):
        for n in dense_slots_name:
            if n not in self._by_name:
                raise ValueError(f'slot {n!r} is not in the data feed '
                                 'description')
            self.slots[self._by_name[n]]['is_dense'] = True

    def set_use_slots(self, use_slots_name):
        for n in use_slots_name:
            if n not in self._by_name:
                raise ValueError(f'slot {n!r} is not in the data feed '
                                 'description')
            self.slots[self._by_name[n]]['is_used'] = True

    def desc(self):
        """Render back as protobuf text (reference data_feed_desc.py:225)."""
        out = [f'name: "{self.name}"',
               f'batch_size: {self.batch_size}',
               'multi_slot_desc {']
        for s in self.slots:
            out.append('  slots {')
            out.append(f'    name: "{s["name"]}"')
            out.append(f'    type: "{s["type"]}"')
            out.append(f'    is_dense: {str(s["is_dense"]).lower()}')
            out.append(f'    is_used: {str(s["is_used"]).lower()}')
            for d in s['shape']:
                out.append(f'    shape: {d}')
            out.append('  }')
        out.append('}')
        out.append(f'pipe_command: "{self.pipe_command}"')
        return '\n'.join(out) + '\n'
