"""fluid.wrapped_decorator (reference: python/paddle/fluid/
wrapped_decorator.py) — functools-based, no `decorator` dependency."""
import contextlib
import functools

__all__ = ['wrap_decorator', 'signature_safe_contextmanager']


def wrap_decorator(decorator_func):
    """Turn a (fn → wrapped-call) factory into a decorator that
    preserves the wrapped function's metadata."""
    @functools.wraps(decorator_func)
    def _decorator(func):
        dec = decorator_func(func)
        return functools.wraps(func)(dec)
    return _decorator


def signature_safe_contextmanager(func):
    return contextlib.contextmanager(func)
