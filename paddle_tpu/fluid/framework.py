"""fluid.framework (reference: python/paddle/fluid/framework.py).

Program/Variable/scope machinery lives in static/program.py; this
module adds the 1.x framework helpers (mode queries, flags, device
guards, place lists).
"""
import contextlib

from ..static.program import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    Variable, global_scope, name_scope, in_static_mode)
from ..static.compat import cpu_places, cuda_places  # noqa: F401
from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, TPUPlace, XPUPlace, CUDAPinnedPlace,
    is_compiled_with_cuda, is_compiled_with_xpu)
from ..utils import require_version  # noqa: F401

__all__ = ['Program', 'default_startup_program', 'default_main_program',
           'program_guard', 'name_scope', 'cpu_places', 'cuda_places',
           'xpu_places', 'cuda_pinned_places', 'in_dygraph_mode',
           'is_compiled_with_cuda', 'is_compiled_with_xpu',
           'Variable', 'require_version', 'device_guard', 'set_flags',
           'get_flags']


def in_dygraph_mode():
    return not in_static_mode()


def xpu_places(device_ids=None):
    """XPU is not a TPU-native target; the device list is empty unless
    ids are forced explicitly (matching paddle semantics of returning
    XPUPlace objects for requested ids)."""
    return [XPUPlace(i) for i in (device_ids or [])]


def cuda_pinned_places(device_count=None):
    """Pinned host staging places; on TPU the host side of the
    double-buffered transfer path plays this role."""
    return [CUDAPinnedPlace()] * (device_count or 1)


# global framework flags (reference: C++ gflags surfaced via
# set_flags/get_flags).  TPU-native: a plain dict consulted by the
# python runtime; XLA knobs go through XLA_FLAGS instead.
_FLAGS = {}


def set_flags(flags):
    if not isinstance(flags, dict):
        raise TypeError('set_flags expects a dict of {flag: value}')
    _FLAGS.update(flags)


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    if not isinstance(flags, (list, tuple)):
        raise TypeError('get_flags expects a flag name or list of names')
    return {f: _FLAGS.get(f) for f in flags}


@contextlib.contextmanager
def device_guard(device=None):
    """Reference framework.py device_guard: pins ops to a device in the
    program.  Under XLA, placement inside one program is the
    compiler's; the guard validates the name and is otherwise
    advisory."""
    if device is not None and device.split(':')[0] not in (
            'cpu', 'gpu', 'xpu', 'npu', 'tpu', 'all'):
        raise ValueError(f'unsupported device type {device!r}')
    yield
