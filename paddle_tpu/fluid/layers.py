"""fluid.layers — legacy op namespace.

Reference analogue: /root/reference/python/paddle/fluid/layers/ (nn.py,
tensor.py, control_flow.py, sequence_lod.py — ~8k LoC of op wrappers).
Everything here aliases the paddle_tpu implementation; the handful of
signature differences the 1.x API had (`dim=` instead of `axis=`,
`input=` instead of `x=`, fill_constant, elementwise_*) get explicit
adapters so reference-era model code runs verbatim.
"""
import numpy as np

from .. import tensor as _T
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor
from ..nn import functional as _F
from ..static.nn import *          # noqa: F401,F403  (fc, conv2d, ...)
from ..static.nn import cond, while_loop, case, switch_case  # noqa: F401
from ..static import sequence as _seq
from ..static.sequence import *    # noqa: F401,F403  (sequence_* ops)
from ..static.program import (     # noqa: F401
    data, Print, py_func, create_global_var)
from ..metric import accuracy      # noqa: F401
from ..tensor import (             # noqa: F401
    concat, reshape, transpose, squeeze, unsqueeze, stack, split, cast,
    gather, gather_nd, scatter, scatter_nd, scatter_nd_add, expand,
    slice, shape, rank, zeros, ones, full, arange, argmax, argmin,
    argsort, where, clip, abs, exp, log, sqrt, square, sin, cos, tanh,
    matmul, topk, multiplex, shard_index, crop, stanh, reverse)
from ..nn.functional import sigmoid  # noqa: F401
from ..tensor.creation import assign  # noqa: F401
# 1.x fluid.layers exported the distribution classes directly
# (reference fluid/layers/distributions.py __all__)
from ..distribution import (  # noqa: F401
    Normal, Uniform, Categorical, MultivariateNormalDiag)


def fill_constant(shape, dtype, value, force_cpu=False, out=None,
                  name=None):
    """fluid/layers/tensor.py::fill_constant."""
    return _T.full(shape, value, dtype=dtype)


def zeros_like(x, out=None, name=None):
    return _T.zeros_like(x)


def ones_like(x, out=None, name=None):
    return _T.ones_like(x)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _T.mean(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _T.sum(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _T.max(input, axis=dim, keepdim=keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _T.min(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _T.prod(input, axis=dim, keepdim=keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _T.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _T.any(input, axis=dim, keepdim=keep_dim)


def _ew(op, x, y, axis=-1, act=None, name=None):
    """elementwise_* had an `axis` arg aligning y's dims to x's; with
    numpy broadcasting the only non-trivial case is right-aligning a
    smaller y at `axis`, handled by reshaping y with trailing 1s."""
    from ..tensor._helpers import wrap
    x, y = wrap(x), wrap(y)
    if axis != -1 and y.ndim < x.ndim:
        pad = x.ndim - axis - y.ndim
        if pad > 0:
            y = _T.reshape(y, list(y.shape) + [1] * pad)
    out = op(x, y)
    if act is not None:
        out = getattr(_F, act)(out)
    return out


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _ew(_T.add, x, y, axis, act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _ew(_T.subtract, x, y, axis, act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _ew(_T.multiply, x, y, axis, act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _ew(_T.divide, x, y, axis, act)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _ew(_T.maximum, x, y, axis, act)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _ew(_T.minimum, x, y, axis, act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _ew(_T.pow, x, y, axis, act)


def mean(x, name=None):
    return _T.mean(x)


def relu(x, name=None):
    return _F.relu(x)


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return _F.softmax(input, axis=axis)


def log_softmax(input, axis=-1, name=None):
    return _F.log_softmax(input, axis=axis)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    """fluid cross_entropy takes PROBABILITIES (softmax applied by the
    caller), unlike paddle 2.x's logits-based loss.  Returns [N, 1]
    per-sample losses like the reference op."""
    eps = 1e-12
    logp = _T.log(_T.clip(input, eps, 1.0))
    if soft_label:
        return -_T.sum(_T.multiply(_T.cast(label, str(input.dtype)),
                                   logp), axis=-1, keepdim=True)
    lab = label
    if lab.ndim == logp.ndim:          # [N, 1] index form
        lab = _T.squeeze(lab, axis=-1)
    out = _F.nll_loss(logp, lab, reduction='none',
                      ignore_index=ignore_index)
    return _T.unsqueeze(out, axis=-1)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    out = _F.softmax_with_cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        axis=axis)
    if return_softmax:
        return out, _F.softmax(logits, axis=axis)
    return out


def mse_loss(input, label):
    return _F.mse_loss(input, label)


def pool2d(input, pool_size=-1, pool_type='max', pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format='NCHW'):
    if global_pooling:
        return _F.adaptive_avg_pool2d(input, 1) if pool_type == 'avg' \
            else _F.adaptive_max_pool2d(input, 1)
    if pool_type == 'avg':
        return _F.avg_pool2d(input, pool_size, stride=pool_stride,
                             padding=pool_padding, ceil_mode=ceil_mode)
    return _F.max_pool2d(input, pool_size, stride=pool_stride,
                         padding=pool_padding, ceil_mode=ceil_mode)


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from ..tensor.creation import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def create_tensor(dtype, name=None, persistable=False):
    return Tensor(np.zeros((), convert_dtype(dtype) or
                           get_default_dtype()))


def increment(x, value=1.0, in_place=True):
    out = _T.add(x, value)
    if in_place and hasattr(x, 'set_value'):
        x.set_value(out)
        return x
    return out


def array_write(x, i, array=None):
    from ..tensor.array import array_write as _aw
    return _aw(x, i, array)


def array_read(array, i):
    from ..tensor.array import array_read as _ar
    return _ar(array, i)


def unsqueeze_(x, axes):
    return _T.unsqueeze(x, axes)


def flatten(x, axis=1, name=None):
    """fluid flatten: collapse to 2-D at `axis` (unlike 2.x's
    start/stop_axis form)."""
    shp = x.shape
    lead = 1
    for d in shp[:axis]:
        lead *= d
    return _T.reshape(x, [lead, -1])


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation='downgrade_in_infer'):
    mode = 'downscale_in_infer' \
        if dropout_implementation == 'downgrade_in_infer' \
        else 'upscale_in_train'
    return _F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def uniform_random(shape, dtype='float32', min=-1.0, max=1.0, seed=0):
    return _T.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype='float32'):
    return _T.cast(_T.normal(mean=mean, std=std, shape=shape), dtype)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _T.clip(_T.add(_T.multiply(x, slope), offset), 0.0, 1.0)


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype='float32'):
    k = label.shape[-1]
    smoothed = _T.add(_T.multiply(label, 1.0 - epsilon), epsilon / k)
    return _T.cast(smoothed, dtype)


# -- detection ops (reference fluid/layers/detection.py; implemented in
# vision/detection.py, TPU-native fixed-shape redesign) ------------------
from ..vision.detection import (    # noqa: F401,E402
    iou_similarity, prior_box, anchor_generator, box_coder, box_clip,
    multiclass_nms, generate_proposals)
from ..vision.detection import roi_align as _roi_align          # noqa: E402
from ..vision.detection import roi_pool as _roi_pool            # noqa: E402


def _uniform_rois_num(input, rois):
    """The legacy LoD-free fallback assumes rois split EVENLY over the
    batch; anything else needs an explicit rois_num (silently guessing
    would pool rois against the wrong image)."""
    n, r = input.shape[0], rois.shape[0]
    if r % n != 0:
        raise ValueError(
            f'{r} rois cannot be split evenly over batch {n}; pass '
            'rois_num=[...] with the per-image counts (the LoD the '
            'reference op carried)')
    return _T.full([n], r // n, 'int32')


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              name=None):
    """Legacy 1.x signature over vision.detection.roi_align (the
    reference's LoD rois become rois + rois_num)."""
    if rois_num is None:
        rois_num = _uniform_rois_num(input, rois)
    return _roi_align(input, rois, rois_num,
                      (pooled_height, pooled_width),
                      spatial_scale=spatial_scale,
                      sampling_ratio=sampling_ratio, aligned=False)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    """Legacy 1.x signature over vision.detection.roi_pool."""
    if rois_num is None:
        rois_num = _uniform_rois_num(input, rois)
    return _roi_pool(input, rois, rois_num,
                     (pooled_height, pooled_width),
                     spatial_scale=spatial_scale)

from ..vision.detection import (    # noqa: F401,E402
    density_prior_box, bipartite_match, target_assign,
    detection_output, ssd_loss, distribute_fpn_proposals,
    collect_fpn_proposals)

from ..vision.detection import (    # noqa: F401,E402
    sigmoid_focal_loss, matrix_nms, polygon_box_transform,
    box_decoder_and_assign, rpn_target_assign,
    generate_proposal_labels, retinanet_target_assign,
    retinanet_detection_output)
from ..vision.ops import yolo_box, yolo_loss  # noqa: F401,E402
yolov3_loss = yolo_loss


from .layers_compat import *       # noqa: F401,F403,E402
from . import layers_compat as _compat  # noqa: E402


def __getattr__(name):
    # the polygon-machinery long tail raises with pointers (see
    # vision/detection.py batch-3 non-goals); ditto the LoD-era /
    # SelectedRows names (layers_compat non-goals)
    from ..vision import detection as _det
    if name in _det._POLY_NON_GOALS:
        return getattr(_det, name)   # raises NotImplementedError
    if name in _compat._LEGACY_NON_GOALS:
        return getattr(_compat, name)
    raise AttributeError(name)
