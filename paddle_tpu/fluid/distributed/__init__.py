"""fluid.distributed (reference: python/paddle/fluid/distributed/) —
legacy downpour/PS helpers; the live API is the fleet module."""
from . import fleet  # noqa: F401
