"""fluid.distributed.fleet (reference: python/paddle/fluid/distributed/
fleet.py) — the minimal legacy Fleet facade over the modern fleet."""
from ...distributed import fleet as _fleet

__all__ = ['Fleet']


class Fleet:
    """Legacy downpour Fleet shim: init/stop + worker/server queries
    mapped onto the modern fleet singleton."""

    def __init__(self):
        self._fleet = _fleet

    def init(self, role_maker=None):
        self._fleet.init(role_maker)

    def stop(self):
        pass

    def is_worker(self):
        return self._fleet.is_worker()

    def is_server(self):
        return self._fleet.is_server() \
            if hasattr(self._fleet, 'is_server') else False

    def worker_num(self):
        return self._fleet.worker_num()

    def worker_index(self):
        return self._fleet.worker_index()
