"""paddle_tpu.fluid — the legacy `import paddle.fluid as fluid` namespace.

Reference analogue: /root/reference/python/paddle/fluid/__init__.py.
Paddle-1.x-era user code (and much of the reference's own model zoo)
drives the framework through this namespace; every name here is a REAL
alias onto the paddle_tpu implementation — fluid.Program is
static.Program, fluid.layers.fc is static.nn.fc, fluid.dygraph.guard
flips eager mode — so that code runs unchanged on the TPU-native stack.
"""
from ..static.program import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    Executor, Variable, global_scope, scope_guard, name_scope,
    in_static_mode)
from ..static.program import data  # noqa: F401
from ..static.program import gradients  # noqa: F401
from ..static.compat import (  # noqa: F401
    BuildStrategy, ExecutionStrategy, CompiledProgram, ParallelExecutor,
    cpu_places, cuda_places, WeightNormParamAttr)
from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, TPUPlace, XPUPlace, NPUPlace, CUDAPinnedPlace,
    is_compiled_with_cuda, is_compiled_with_xpu)
from ..nn.layer.layers import ParamAttr  # noqa: F401
from ..core.rng import seed  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from .input import one_hot, embedding  # noqa: F401

from . import layers  # noqa: F401
from . import dygraph  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import nets  # noqa: F401
from . import core  # noqa: F401
from . import contrib  # noqa: F401
from . import framework  # noqa: F401
from . import average  # noqa: F401
from . import data_feeder  # noqa: F401
from . import data_feed_desc  # noqa: F401
from . import dataloader  # noqa: F401
from . import dataset  # noqa: F401
from . import unique_name  # noqa: F401
from . import lod_tensor  # noqa: F401
from . import log_helper  # noqa: F401
from . import entry_attr  # noqa: F401
from . import evaluator  # noqa: F401
from . import profiler  # noqa: F401
from . import generator  # noqa: F401
from . import install_check  # noqa: F401
from . import wrapped_decorator  # noqa: F401
from . import layer_helper_base  # noqa: F401
from . import default_scope_funcs  # noqa: F401
from . import communicator  # noqa: F401
from . import device_worker  # noqa: F401
from . import trainer_desc  # noqa: F401
from . import trainer_factory  # noqa: F401
from . import transpiler  # noqa: F401
from . import distributed  # noqa: F401
from . import input  # noqa: F401
from .average import WeightedAverage  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .data_feed_desc import DataFeedDesc  # noqa: F401
from .dataset import DatasetFactory  # noqa: F401
from .framework import (  # noqa: F401
    in_dygraph_mode, device_guard, set_flags, get_flags, xpu_places,
    cuda_pinned_places, require_version)
from .lod_tensor import (  # noqa: F401
    create_lod_tensor, create_random_int_lodtensor)
from .transpiler import (  # noqa: F401
    DistributeTranspiler, DistributeTranspilerConfig, memory_optimize,
    release_memory)
from .generator import Generator  # noqa: F401
from .clip import (  # noqa: F401
    GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm)


def enable_dygraph(place=None):
    from ..static.program import disable_static
    disable_static()


def disable_dygraph():
    from ..static.program import enable_static
    enable_static()

# reader surface at the fluid top level (reference fluid/reader.py)
from ..io import DataLoader, default_collate_fn  # noqa: F401,E402


class PyReader:
    """Legacy PyReader (reference fluid/reader.py): feed a Program
    from a python generator.  The TPU-native DataLoader covers the
    same contract; this adapter keeps decorate_* API parity."""

    def __init__(self, feed_list=None, capacity=64,
                 use_double_buffer=True, iterable=True,
                 return_list=False):
        self._feed_list = feed_list
        self._reader = None
        self._iterable = iterable

    def decorate_sample_list_generator(self, reader, places=None):
        self._reader = reader

    decorate_batch_generator = decorate_sample_list_generator

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        """Per-SAMPLE generator: batch it here (the reference's
        contract), stacking each field across batch_size samples."""
        import numpy as np

        def batched():
            buf = []
            for sample in sample_generator():
                buf.append(sample)
                if len(buf) == batch_size:
                    yield [np.stack([s[i] for s in buf])
                           for i in range(len(buf[0]))]
                    buf = []
            if buf and not drop_last:
                yield [np.stack([s[i] for s in buf])
                       for i in range(len(buf[0]))]
        self._reader = batched

    def __iter__(self):
        if self._reader is None:
            raise RuntimeError('call decorate_*_generator first')
        return iter(self._reader())

    def start(self):
        pass

    def reset(self):
        pass


# fluid.backward / fluid.append_backward: the static machinery
# already implements the full contract (no_grad_set included)
from ..static.program import append_backward  # noqa: F401,E402
from . import backward  # noqa: F401,E402
from . import metrics  # noqa: F401,E402
