"""paddle_tpu.fluid — the legacy `import paddle.fluid as fluid` namespace.

Reference analogue: /root/reference/python/paddle/fluid/__init__.py.
Paddle-1.x-era user code (and much of the reference's own model zoo)
drives the framework through this namespace; every name here is a REAL
alias onto the paddle_tpu implementation — fluid.Program is
static.Program, fluid.layers.fc is static.nn.fc, fluid.dygraph.guard
flips eager mode — so that code runs unchanged on the TPU-native stack.
"""
from ..static.program import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    Executor, Variable, global_scope, scope_guard, name_scope,
    in_static_mode)
from ..static.program import data  # noqa: F401
from ..static.program import gradients  # noqa: F401
from ..static.compat import (  # noqa: F401
    BuildStrategy, ExecutionStrategy, CompiledProgram, ParallelExecutor,
    cpu_places, cuda_places, WeightNormParamAttr)
from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, TPUPlace, XPUPlace, NPUPlace, CUDAPinnedPlace,
    is_compiled_with_cuda, is_compiled_with_xpu)
from ..nn.layer.layers import ParamAttr  # noqa: F401
from ..core.rng import seed  # noqa: F401
from .. import regularizer  # noqa: F401
from ..nn import clip  # noqa: F401
from ..static.nn import embedding  # noqa: F401
from ..nn.functional import one_hot as _one_hot


def one_hot(input, depth, allow_out_of_range=False):
    """fluid/input.py::one_hot — num_classes is called depth there."""
    return _one_hot(input, depth)

from . import layers  # noqa: F401
from . import dygraph  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import nets  # noqa: F401
from . import core  # noqa: F401
from . import contrib  # noqa: F401


def enable_dygraph(place=None):
    from ..static.program import disable_static
    disable_static()


def disable_dygraph():
    from ..static.program import enable_static
    enable_static()
