"""fluid.layer_helper_base (reference: python/paddle/fluid/
layer_helper_base.py).  The 1.x LayerHelper mediated between layer
front-ends and the ProgramDesc; here parameters are created directly
through the Layer machinery, so the helper delegates to an anonymous
Layer and keeps the name/activation conveniences."""
from ..nn.layer.layers import Layer
from ..utils import unique_name

__all__ = ['LayerHelperBase']


class LayerHelperBase:
    def __init__(self, name=None, layer_type=''):
        self._layer_type = layer_type
        self._name = name or unique_name.generate(layer_type or 'layer')
        self._owner = Layer()

    @property
    def name(self):
        return self._name

    @property
    def layer_type(self):
        return self._layer_type

    def create_parameter(self, attr, shape, dtype='float32',
                         is_bias=False, default_initializer=None):
        return self._owner.create_parameter(
            shape, attr=attr, dtype=dtype, is_bias=is_bias,
            default_initializer=default_initializer)

    def to_variable(self, value, name=None):
        from ..core.tensor import Tensor
        return Tensor(value)

    def append_activation(self, x, act=None):
        if act is None:
            return x
        from ..nn import functional as F
        return getattr(F, act)(x)
