"""fluid.unique_name (reference: python/paddle/fluid/unique_name.py) —
same implementation as paddle.utils.unique_name."""
from ..utils.unique_name import (  # noqa: F401
    generate, switch, guard, UniqueNameGenerator)

__all__ = ['generate', 'switch', 'guard']
