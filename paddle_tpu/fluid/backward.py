"""fluid.backward — the canonical 1.x spelling
(reference fluid/backward.py: append_backward:1363, gradients)."""
from ..static.program import append_backward, gradients  # noqa: F401

__all__ = ['append_backward', 'gradients']
