"""fluid.initializer — legacy initializer aliases (reference
fluid/initializer.py: MSRA is Kaiming, Xavier covers both modes)."""
from ..nn.initializer import (  # noqa: F401
    Constant, Normal, TruncatedNormal, Uniform, Bilinear,
    set_global_initializer)
from ..nn.initializer import XavierNormal, XavierUniform  # noqa: F401
from ..nn.initializer import KaimingNormal, KaimingUniform  # noqa: F401

__all__ = ['Constant', 'ConstantInitializer', 'Normal',
           'NormalInitializer', 'TruncatedNormal', 'Uniform',
           'UniformInitializer', 'Xavier', 'XavierInitializer', 'MSRA',
           'MSRAInitializer', 'Bilinear', 'BilinearInitializer',
           'set_global_initializer']


def Xavier(uniform=True, fan_in=None, fan_out=None, seed=0):
    return XavierUniform() if uniform else XavierNormal()


def MSRA(uniform=True, fan_in=None, seed=0):
    return KaimingUniform() if uniform else KaimingNormal()


ConstantInitializer = Constant
NormalInitializer = Normal
UniformInitializer = Uniform
XavierInitializer = Xavier
MSRAInitializer = MSRA
BilinearInitializer = Bilinear


class NumpyArrayInitializer:
    """Initialize from a literal array (reference
    initializer.py::NumpyArrayInitializer) — the Assign initializer."""

    def __init__(self, value):
        from ..nn.initializer import Assign
        self._inner = Assign(value)

    def __call__(self, shape, dtype, key=None):
        return self._inner(shape, dtype, key)


TruncatedNormalInitializer = TruncatedNormal
__all__ += ['TruncatedNormalInitializer', 'NumpyArrayInitializer']
