"""fluid.communicator (reference: python/paddle/fluid/communicator.py).

The reference Communicator is the async parameter-server push/pull
thread (brpc).  The TPU-native sparse path is the host-offloaded
embedding (incubate/host_embedding.py) whose updates are applied by
the native C++ sparse kernel; this Communicator controls that
machinery's lifecycle so legacy `fleet`-era training scripts keep
their start/stop calls.
"""
import threading

__all__ = ['Communicator', 'LargeScaleKV']


class Communicator:
    def __init__(self, program=None, mode=None, kwargs=None, envs=None):
        self._running = False
        self._lock = threading.Lock()

    def start(self):
        with self._lock:
            self._running = True

    def stop(self):
        with self._lock:
            self._running = False

    def is_running(self):
        return self._running

    def recv(self):
        """Synchronous pull barrier.  Host-PS tables apply updates
        synchronously in-step, so a pull is already consistent."""
        return None

    init_with_ctx = staticmethod(lambda *a, **k: None)


class LargeScaleKV:
    """Host-memory KV store (reference: large-scale sparse table ops).
    Backs save/load of raw rows for tools that expect the KV API."""

    def __init__(self):
        self._kv = {}

    def save(self, name, path):
        import pickle
        with open(path, 'wb') as f:
            pickle.dump(self._kv.get(name, {}), f)

    def load(self, name, path):
        import pickle
        with open(path, 'rb') as f:
            self._kv[name] = pickle.load(f)

    def size(self, name):
        return len(self._kv.get(name, {}))
