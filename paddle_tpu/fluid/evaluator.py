"""fluid.evaluator (reference: python/paddle/fluid/evaluator.py) — the
surviving evaluators are the fluid.metrics implementations."""
from .metrics import ChunkEvaluator, EditDistance, DetectionMAP  # noqa: F401

__all__ = ['ChunkEvaluator', 'EditDistance', 'DetectionMAP']
