"""fluid.dataloader.batch_sampler (reference: fluid/dataloader/
batch_sampler.py)."""
from ...io import BatchSampler, DistributedBatchSampler  # noqa: F401

__all__ = ['BatchSampler', 'DistributedBatchSampler']
