"""fluid.dataloader.dataset (reference: fluid/dataloader/dataset.py)."""
from ...io import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    random_split, Subset)

__all__ = ['Dataset', 'IterableDataset', 'TensorDataset', 'ComposeDataset',
           'ChainDataset', 'random_split', 'Subset']
