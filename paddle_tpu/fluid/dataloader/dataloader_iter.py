"""fluid.dataloader.dataloader_iter (reference: fluid/dataloader/
dataloader_iter.py)."""
from ...io import get_worker_info  # noqa: F401

__all__ = ['get_worker_info']
