"""fluid.dataloader.worker (reference: fluid/dataloader/worker.py)."""
from ...io import get_worker_info  # noqa: F401

__all__ = ['get_worker_info']
