"""fluid.dataloader (reference: python/paddle/fluid/dataloader/) — the
dataset/sampler/loader implementations live in paddle_tpu/io."""
from ...io import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    random_split, Subset, BatchSampler, DistributedBatchSampler, Sampler,
    SequenceSampler, RandomSampler, WeightedRandomSampler, get_worker_info)

from . import dataset  # noqa: F401
from . import batch_sampler  # noqa: F401
from . import sampler  # noqa: F401
from . import worker  # noqa: F401
from . import dataloader_iter  # noqa: F401

__all__ = ['Dataset', 'IterableDataset', 'TensorDataset', 'ComposeDataset',
           'ChainDataset', 'random_split', 'Subset', 'BatchSampler',
           'DistributedBatchSampler', 'Sampler', 'SequenceSampler',
           'RandomSampler', 'WeightedRandomSampler', 'get_worker_info']
