"""fluid.dataloader.sampler (reference: fluid/dataloader/sampler.py)."""
from ...io import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler)

__all__ = ['Sampler', 'SequenceSampler', 'RandomSampler',
           'WeightedRandomSampler']
