"""fluid.input (reference: python/paddle/fluid/input.py)."""
from ..static.nn import embedding  # noqa: F401
from ..nn.functional import one_hot as _one_hot

__all__ = ['one_hot', 'embedding']


def one_hot(input, depth, allow_out_of_range=False):
    """1.x signature: num_classes is called depth."""
    return _one_hot(input, depth)
