"""fluid.profiler (reference: python/paddle/fluid/profiler.py) — the
nvprof-era API over the XLA trace backend (paddle_tpu.profiler)."""
from ..profiler import (  # noqa: F401
    cuda_profiler, reset_profiler, profiler, start_profiler,
    stop_profiler)

__all__ = ['cuda_profiler', 'reset_profiler', 'profiler',
           'start_profiler', 'stop_profiler']
