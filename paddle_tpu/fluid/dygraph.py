"""fluid.dygraph — legacy eager-mode namespace.

Reference analogue: /root/reference/python/paddle/fluid/dygraph/
(base.py guard/to_variable, layers.py Layer, nn.py Linear/Conv2D/...).
Eager IS the default here, so guard() only ensures static mode is off.
The 1.x layer classes had different constructor signatures (Linear
took input_dim/output_dim; Conv2D took num_channels/num_filters) —
adapters below translate them onto the paddle_tpu layers.
"""
import contextlib

import numpy as np

from ..core.tensor import Tensor
from ..core.autograd import no_grad  # noqa: F401
from ..nn.layer.layers import Layer, ParamAttr  # noqa: F401
from .. import nn as _nn

__all__ = ['guard', 'to_variable', 'no_grad', 'Layer', 'Linear',
           'Conv2D', 'Pool2D', 'BatchNorm', 'Embedding', 'Dropout',
           'LayerNorm', 'save_dygraph', 'load_dygraph',
           'ProgramTranslator', 'TracedLayer']

from ..jit import ProgramTranslator, TracedLayer  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """with fluid.dygraph.guard(): — eager mode (the default)."""
    from ..static.program import in_static_mode, disable_static, \
        enable_static
    was_static = in_static_mode()
    if was_static:
        disable_static()
    try:
        yield
    finally:
        if was_static:
            enable_static()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    """numpy -> Tensor (reference dygraph/base.py:612)."""
    if isinstance(value, Tensor):
        return value
    return Tensor(np.asarray(value), dtype=dtype)


class Linear(_nn.Linear):
    """1.x signature: Linear(input_dim, output_dim, param_attr=...,
    bias_attr=..., act=...)."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype='float32'):
        super().__init__(input_dim, output_dim, weight_attr=param_attr,
                         bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Conv2D(_nn.Conv2D):
    """1.x signature: Conv2D(num_channels, num_filters, filter_size)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None,
                 dtype='float32'):
        super().__init__(num_channels, num_filters, filter_size,
                         stride=stride, padding=padding,
                         dilation=dilation, groups=groups,
                         weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Pool2D(Layer):
    """1.x Pool2D(pool_size, pool_type, pool_stride, pool_padding,
    global_pooling)."""

    def __init__(self, pool_size=-1, pool_type='max', pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True):
        super().__init__()
        self._args = (pool_size, pool_type, pool_stride, pool_padding,
                      global_pooling, ceil_mode)

    def forward(self, x):
        size, ptype, stride, pad, global_p, ceil = self._args
        from ..nn import functional as F
        if global_p:
            return F.adaptive_avg_pool2d(x, 1) if ptype == 'avg' \
                else F.adaptive_max_pool2d(x, 1)
        fn = F.avg_pool2d if ptype == 'avg' else F.max_pool2d
        return fn(x, size, stride=stride, padding=pad, ceil_mode=ceil)


class BatchNorm(_nn.BatchNorm2D):
    """1.x BatchNorm(num_channels, act=...)."""

    def __init__(self, num_channels, act=None, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype='float32', data_layout='NCHW', in_place=False,
                 is_test=False, use_global_stats=False,
                 trainable_statistics=False):
        super().__init__(num_channels, momentum=momentum,
                         epsilon=epsilon, weight_attr=param_attr,
                         bias_attr=bias_attr, data_format=data_layout)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Embedding(_nn.Embedding):
    """1.x Embedding(size=[vocab, dim], is_sparse=..., param_attr=...)."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype='float32'):
        super().__init__(size[0], size[1], padding_idx=padding_idx,
                         sparse=is_sparse, weight_attr=param_attr)


Dropout = _nn.Dropout
LayerNorm = _nn.LayerNorm


def save_dygraph(state_dict, model_path):
    """fluid.dygraph.save_dygraph -> <path>.pdparams (reference
    checkpoint.py)."""
    from ..framework.io import save
    save(state_dict, model_path + '.pdparams')


def load_dygraph(model_path):
    """-> (param_dict, optimizer_dict|None)."""
    import os
    from ..framework.io import load
    params = load(model_path + '.pdparams') \
        if os.path.exists(model_path + '.pdparams') else None
    opt = load(model_path + '.pdopt') \
        if os.path.exists(model_path + '.pdopt') else None
    return params, opt


class Conv3D(_nn.Conv3D):
    """1.x signature: Conv3D(num_channels, num_filters, filter_size)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None,
                 dtype='float32'):
        super().__init__(num_channels, num_filters, filter_size,
                         stride=stride, padding=padding,
                         dilation=dilation, groups=groups,
                         weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Conv2DTranspose(_nn.Conv2DTranspose):
    """1.x signature: Conv2DTranspose(num_channels, num_filters,
    filter_size)."""

    def __init__(self, num_channels, num_filters, filter_size,
                 output_size=None, padding=0, stride=1, dilation=1,
                 groups=1, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype='float32'):
        super().__init__(num_channels, num_filters, filter_size,
                         stride=stride, padding=padding,
                         dilation=dilation, groups=groups,
                         weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act
        self._output_size = output_size

    def forward(self, x):
        out = super().forward(x, output_size=self._output_size)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Conv3DTranspose(_nn.Conv3DTranspose):
    def __init__(self, num_channels, num_filters, filter_size,
                 padding=0, stride=1, dilation=1, groups=1,
                 param_attr=None, bias_attr=None, use_cudnn=True,
                 act=None, dtype='float32'):
        super().__init__(num_channels, num_filters, filter_size,
                         stride=stride, padding=padding,
                         dilation=dilation, groups=groups,
                         weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class InstanceNorm(_nn.InstanceNorm2D):
    """1.x InstanceNorm(num_channels, epsilon=1e-5, param_attr=...,
    bias_attr=...)."""

    def __init__(self, num_channels, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype='float32'):
        super().__init__(num_channels, epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr)


class GroupNorm(_nn.GroupNorm):
    """1.x GroupNorm(channels, groups, epsilon, param_attr,
    bias_attr)."""

    def __init__(self, channels, groups, epsilon=1e-05,
                 param_attr=None, bias_attr=None, act=None,
                 data_layout='NCHW', dtype='float32'):
        super().__init__(num_groups=groups, num_channels=channels,
                         epsilon=epsilon, weight_attr=param_attr,
                         bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class SpectralNorm(_nn.SpectralNorm):
    """1.x SpectralNorm(weight_shape, dim, power_iters, eps)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype='float32'):
        super().__init__(weight_shape, dim=dim,
                         power_iters=power_iters, eps=eps)


class PRelu(Layer):
    """1.x PRelu(mode, channel=None, input_shape=None, param_attr=...):
    mode 'all' (one alpha), 'channel', or 'element'."""

    def __init__(self, mode, channel=None, input_shape=None,
                 param_attr=None, dtype='float32'):
        super().__init__()
        if mode == 'all':
            n = 1
        elif mode == 'channel':
            n = int(channel)
        elif mode == 'element':
            n = int(np.prod(input_shape[1:]))
        else:
            raise ValueError(f'unknown PRelu mode {mode!r}')
        self._mode = mode
        self._input_shape = input_shape
        from ..nn import initializer as I
        self.weight = self.create_parameter(
            [n], attr=param_attr, dtype=dtype,
            default_initializer=I.Constant(0.25))

    def forward(self, x):
        from ..core.dispatch import apply as _apply
        import jax.numpy as jnp

        mode, shp = self._mode, self._input_shape

        def fn(v, a):
            if mode == 'channel':
                a = a.reshape((1, -1) + (1,) * (v.ndim - 2))
            elif mode == 'element':
                a = a.reshape((1,) + tuple(shp[1:]))
            return jnp.where(v > 0, v, a * v)
        return _apply(fn, x, self.weight, op_name='prelu')


class BilinearTensorProduct(_nn.Bilinear):
    """1.x BilinearTensorProduct(input1_dim, input2_dim, output_dim)."""

    def __init__(self, input1_dim, input2_dim, output_dim,
                 name=None, act=None, param_attr=None, bias_attr=None,
                 dtype='float32'):
        super().__init__(input1_dim, input2_dim, output_dim,
                         weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x, y):
        out = super().forward(x, y)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Flatten(_nn.Flatten):
    """Reference dygraph Flatten uses the 2.x (start_axis,
    stop_axis) signature."""

    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__(start_axis=start_axis, stop_axis=stop_axis)


class GRUUnit(Layer):
    """One GRU step (reference dygraph/nn.py:1841 / gru_unit op):
    input is the PRE-PROJECTED [N, 3D] (x @ W_x done by the caller),
    hidden [N, D].  Returns (hidden', reset_hidden_pre, gate) like the
    reference op.  h' = u*h + (1-u)*c (the fluid update rule)."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation='tanh', gate_activation='sigmoid',
                 origin_mode=False, dtype='float32'):
        super().__init__()
        D = size // 3
        self._D = D
        self._origin = origin_mode
        self._act = activation
        self._gate_act = gate_activation
        from ..nn import initializer as I
        self.weight = self.create_parameter(
            [D, 3 * D], attr=param_attr, dtype=dtype,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [1, 3 * D], attr=bias_attr, dtype=dtype, is_bias=True,
            default_initializer=I.Constant(0.0))

    def forward(self, input, hidden):
        from ..core.dispatch import apply as _apply
        import jax
        import jax.numpy as jnp
        D = self._D
        act = getattr(jax.nn, self._act) if self._act != 'tanh' \
            else jnp.tanh
        gate_act = getattr(jax.nn, self._gate_act)
        origin = self._origin

        def fn(x, h, w, b):
            xu, xr, xc = x[:, :D], x[:, D:2 * D], x[:, 2 * D:]
            wu, wr, wc = w[:, :D], w[:, D:2 * D], w[:, 2 * D:]
            bu, br, bc = b[:, :D], b[:, D:2 * D], b[:, 2 * D:]
            u = gate_act(xu + h @ wu + bu)
            r = gate_act(xr + h @ wr + br)
            rhp = r * h
            c = act(xc + rhp @ wc + bc)
            if origin:
                h2 = u * h + (1.0 - u) * c
            else:
                h2 = (1.0 - u) * h + u * c
            gate = jnp.concatenate([u, r, c], axis=1)
            return h2, rhp, gate
        return _apply(fn, input, hidden, self.weight, self.bias,
                      op_name='gru_unit')


class NCE(Layer):
    """Noise-contrastive estimation loss (reference dygraph/nn.py:2019,
    Gutmann & Hyvärinen): logistic discrimination of the true class
    against num_neg_samples uniformly sampled noise classes.  The
    'uniform' and 'log_uniform' samplers are supported; custom_dist
    raises (SelectedRows-era machinery)."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler='uniform', custom_dist=None, seed=0,
                 is_sparse=False, dtype='float32'):
        super().__init__()
        if sampler not in ('uniform', 'log_uniform'):
            raise NotImplementedError(
                f'NCE sampler {sampler!r}: only uniform/log_uniform '
                '(custom_dist is SelectedRows-era machinery)')
        self._C = int(num_total_classes)
        self._k = int(num_neg_samples)
        self._sampler = sampler
        self._seed = seed
        from ..nn import initializer as I
        self.weight = self.create_parameter(
            [self._C, dim], attr=param_attr, dtype=dtype,
            default_initializer=I.XavierUniform())
        self.bias = None if bias_attr is False else \
            self.create_parameter(
                [self._C, 1], attr=bias_attr, dtype=dtype,
                is_bias=True, default_initializer=I.Constant(0.0))

    def forward(self, input, label, sample_weight=None):
        from ..core.dispatch import apply as _apply
        from ..core import rng as rng_mod
        import jax
        import jax.numpy as jnp
        C, k = self._C, self._k
        seed = self._seed or int(np.asarray(rng_mod.next_key())[-1])
        sampler = self._sampler
        has_bias = self.bias is not None
        has_sw = sample_weight is not None

        def fn(x, y, w, *rest):
            N = x.shape[0]
            key = jax.random.PRNGKey(seed)
            if sampler == 'uniform':
                noise = jax.random.randint(key, (N, k), 0, C)
            else:   # log_uniform (Zipf-ish)
                u = jax.random.uniform(key, (N, k))
                noise = (jnp.exp(u * jnp.log(C + 1.0)) - 1.0) \
                    .astype(jnp.int32)
                noise = jnp.clip(noise, 0, C - 1)
            y = y.reshape(-1)
            ids = jnp.concatenate([y[:, None], noise], axis=1)
            ws = w[ids]                           # [N, 1+k, D]
            logits = jnp.einsum('nd,nkd->nk', x, ws)
            ri = 0
            if has_bias:
                logits = logits + rest[ri][ids][..., 0]
                ri += 1
            # NCE noise correction (reference nce_op.h:204): the
            # discriminator is o/(o+b) with b = q(class) * k, i.e.
            # sigmoid(logit - log b) — without it the estimator
            # loses its consistency guarantee
            if sampler == 'uniform':
                q = jnp.full(ids.shape, 1.0 / C)
            else:
                cid = ids.astype(jnp.float32)
                q = (jnp.log((cid + 2.0) / (cid + 1.0))
                     / jnp.log(C + 1.0))
            logits = logits - jnp.log(q * k)
            labels = jnp.concatenate(
                [jnp.ones((N, 1)), jnp.zeros((N, k))], axis=1)
            # logistic loss, summed over the 1+k discriminations
            ll = jnp.maximum(logits, 0) - logits * labels \
                + jnp.log1p(jnp.exp(-jnp.abs(logits)))
            out = jnp.sum(ll, axis=1, keepdims=True)
            if has_sw:
                out = out * rest[ri].reshape(-1, 1)
            return out

        args = [input, label, self.weight]
        if has_bias:
            args.append(self.bias)
        if has_sw:
            args.append(sample_weight)
        return _apply(fn, *args, op_name='nce')


class TreeConv(Layer):
    def __init__(self, *a, **k):
        raise NotImplementedError(
            'TreeConv is a documented non-goal (tree-index machinery; '
            'see fluid.contrib.layers non-goals)')


# -- dygraph/base.py names (reference fluid/dygraph/base.py) -------------

def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """fluid.dygraph.grad — the partial-grad API."""
    from ..autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs=grad_outputs,
                 retain_graph=retain_graph, create_graph=create_graph,
                 only_inputs=only_inputs, allow_unused=allow_unused,
                 no_grad_vars=no_grad_vars)


no_grad_ = no_grad   # decorator-style alias the reference exports


def enable_dygraph(place=None):
    """Dygraph is the default mode here; kept for API parity."""
    from ..static.program import disable_static
    disable_static()


def disable_dygraph():
    from ..static.program import enable_static
    enable_static()


def enabled():
    """True iff imperative (dygraph) mode is active."""
    from ..static.program import in_static_mode
    return not in_static_mode()


# --- containers / cells (reference fluid/dygraph/{container,rnn}.py) ---
Sequential = _nn.Sequential
ParameterList = _nn.ParameterList
LayerList = _nn.LayerList
LSTMCell = _nn.LSTMCell
GRUCell = _nn.GRUCell

# --- legacy decay schedules (reference fluid/dygraph/
# learning_rate_scheduler.py; real 1.x formulas in lr_compat) ---
from .lr_compat import (  # noqa: F401,E402
    NoamDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
    InverseTimeDecay, PolynomialDecay, CosineDecay, LinearLrWarmup,
    StepDecay, MultiStepDecay, LambdaDecay, ReduceLROnPlateau)

# --- parallel (reference fluid/dygraph/parallel.py) ---
from ..distributed import ParallelEnv, DataParallel  # noqa: F401,E402


def prepare_context(strategy=None):
    """1.x parallel bootstrap: returns the parallel env after
    initializing collectives (reference dygraph/parallel.py)."""
    from ..distributed import init_parallel_env
    init_parallel_env()
    return ParallelEnv()


# --- dy2static entry points (reference fluid/dygraph/jit.py) ---
from ..jit import (  # noqa: F401,E402
    save, load, not_to_static, TranslatedLayer, set_verbosity,
    set_code_level, to_static as declarative)


def dygraph_to_static_func(function):
    """Legacy decorator name for to_static (reference dygraph/jit.py)."""
    return declarative(function)


# --- amp (reference fluid/dygraph/amp/{auto_cast,loss_scaler}.py) ---
from ..amp import amp_guard  # noqa: F401,E402
from ..amp import GradScaler as AmpScaler  # noqa: E402

# --- profiler hooks (reference fluid/dygraph/profiler.py) ---
from ..profiler import start_profiler as _start_prof  # noqa: E402
from ..profiler import stop_profiler as _stop_prof  # noqa: E402


def start_gperf_profiler():
    """gperftools has no TPU meaning; records an XLA trace instead."""
    return _start_prof()


def stop_gperf_profiler():
    return _stop_prof()


__all__ += [
    'Sequential', 'ParameterList', 'LayerList', 'LSTMCell', 'GRUCell',
    'NoamDecay', 'PiecewiseDecay', 'NaturalExpDecay', 'ExponentialDecay',
    'InverseTimeDecay', 'PolynomialDecay', 'CosineDecay', 'LinearLrWarmup',
    'StepDecay', 'MultiStepDecay', 'LambdaDecay', 'ReduceLROnPlateau',
    'prepare_context', 'ParallelEnv', 'DataParallel',
    'declarative', 'dygraph_to_static_func', 'save', 'load',
    'not_to_static', 'TranslatedLayer', 'set_verbosity', 'set_code_level',
    'amp_guard', 'AmpScaler', 'start_gperf_profiler',
    'stop_gperf_profiler']
