"""fluid.dygraph — legacy eager-mode namespace.

Reference analogue: /root/reference/python/paddle/fluid/dygraph/
(base.py guard/to_variable, layers.py Layer, nn.py Linear/Conv2D/...).
Eager IS the default here, so guard() only ensures static mode is off.
The 1.x layer classes had different constructor signatures (Linear
took input_dim/output_dim; Conv2D took num_channels/num_filters) —
adapters below translate them onto the paddle_tpu layers.
"""
import contextlib

import numpy as np

from ..core.tensor import Tensor
from ..core.autograd import no_grad  # noqa: F401
from ..nn.layer.layers import Layer, ParamAttr  # noqa: F401
from .. import nn as _nn

__all__ = ['guard', 'to_variable', 'no_grad', 'Layer', 'Linear',
           'Conv2D', 'Pool2D', 'BatchNorm', 'Embedding', 'Dropout',
           'LayerNorm', 'save_dygraph', 'load_dygraph',
           'ProgramTranslator', 'TracedLayer']

from ..jit import ProgramTranslator, TracedLayer  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """with fluid.dygraph.guard(): — eager mode (the default)."""
    from ..static.program import in_static_mode, disable_static, \
        enable_static
    was_static = in_static_mode()
    if was_static:
        disable_static()
    try:
        yield
    finally:
        if was_static:
            enable_static()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    """numpy -> Tensor (reference dygraph/base.py:612)."""
    if isinstance(value, Tensor):
        return value
    return Tensor(np.asarray(value), dtype=dtype)


class Linear(_nn.Linear):
    """1.x signature: Linear(input_dim, output_dim, param_attr=...,
    bias_attr=..., act=...)."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype='float32'):
        super().__init__(input_dim, output_dim, weight_attr=param_attr,
                         bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Conv2D(_nn.Conv2D):
    """1.x signature: Conv2D(num_channels, num_filters, filter_size)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None,
                 dtype='float32'):
        super().__init__(num_channels, num_filters, filter_size,
                         stride=stride, padding=padding,
                         dilation=dilation, groups=groups,
                         weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Pool2D(Layer):
    """1.x Pool2D(pool_size, pool_type, pool_stride, pool_padding,
    global_pooling)."""

    def __init__(self, pool_size=-1, pool_type='max', pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True):
        super().__init__()
        self._args = (pool_size, pool_type, pool_stride, pool_padding,
                      global_pooling, ceil_mode)

    def forward(self, x):
        size, ptype, stride, pad, global_p, ceil = self._args
        from ..nn import functional as F
        if global_p:
            return F.adaptive_avg_pool2d(x, 1) if ptype == 'avg' \
                else F.adaptive_max_pool2d(x, 1)
        fn = F.avg_pool2d if ptype == 'avg' else F.max_pool2d
        return fn(x, size, stride=stride, padding=pad, ceil_mode=ceil)


class BatchNorm(_nn.BatchNorm2D):
    """1.x BatchNorm(num_channels, act=...)."""

    def __init__(self, num_channels, act=None, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype='float32', data_layout='NCHW', in_place=False,
                 is_test=False, use_global_stats=False,
                 trainable_statistics=False):
        super().__init__(num_channels, momentum=momentum,
                         epsilon=epsilon, weight_attr=param_attr,
                         bias_attr=bias_attr, data_format=data_layout)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ..nn import functional as F
            out = getattr(F, self._act)(out)
        return out


class Embedding(_nn.Embedding):
    """1.x Embedding(size=[vocab, dim], is_sparse=..., param_attr=...)."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype='float32'):
        super().__init__(size[0], size[1], padding_idx=padding_idx,
                         sparse=is_sparse, weight_attr=param_attr)


Dropout = _nn.Dropout
LayerNorm = _nn.LayerNorm


def save_dygraph(state_dict, model_path):
    """fluid.dygraph.save_dygraph -> <path>.pdparams (reference
    checkpoint.py)."""
    from ..framework.io import save
    save(state_dict, model_path + '.pdparams')


def load_dygraph(model_path):
    """-> (param_dict, optimizer_dict|None)."""
    import os
    from ..framework.io import load
    params = load(model_path + '.pdparams') \
        if os.path.exists(model_path + '.pdparams') else None
    opt = load(model_path + '.pdopt') \
        if os.path.exists(model_path + '.pdopt') else None
    return params, opt
