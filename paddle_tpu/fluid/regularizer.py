"""fluid.regularizer (reference: python/paddle/fluid/regularizer.py).
The 1.x *Regularizer names are the 2.0 decay classes."""
from ..regularizer import L1Decay, L2Decay  # noqa: F401

L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay

__all__ = ['L1Decay', 'L2Decay', 'L1DecayRegularizer',
           'L2DecayRegularizer']
