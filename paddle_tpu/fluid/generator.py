"""fluid.generator (reference: python/paddle/fluid/generator.py) — RNG
stream handle over the global PRNGKey threading (core/rng.py)."""
from ..core import rng as _rng

__all__ = ['Generator']


class Generator:
    """Per-place random generator.  TPU-native randomness is a threaded
    jax PRNGKey; manual_seed re-seeds the global stream and the
    returned state is the (seed, counter) pair."""

    def __init__(self, place=None):
        self.place = place

    def get_state(self):
        return _rng.get_cuda_rng_state()

    def set_state(self, state):
        _rng.set_cuda_rng_state(state)

    def manual_seed(self, seed):
        _rng.seed(seed)
        return self

    def seed(self):
        import random as _random
        s = _random.getrandbits(32)
        _rng.seed(s)
        return s

    def initial_seed(self):
        return _rng.get_seed()

    def random(self):
        raise NotImplementedError(
            'Generator.random() (raw C++ engine draw) has no TPU '
            'counterpart; draw through paddle_tpu.tensor.rand* ops')
