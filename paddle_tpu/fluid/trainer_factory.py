"""fluid.trainer_factory (reference: python/paddle/fluid/
trainer_factory.py)."""
import threading
import time

import numpy as np

from . import trainer_desc as _td
from . import device_worker as _dw

__all__ = ['TrainerFactory', 'FetchHandler', 'FetchHandlerMonitor']


class TrainerFactory:
    def _create_trainer(self, opt_info=None):
        opt_info = opt_info or {}
        trainer_name = opt_info.get('trainer', 'MultiTrainer')
        worker_name = opt_info.get('device_worker', 'Hogwild')
        trainer = getattr(_td, trainer_name, None)
        worker = getattr(_dw, worker_name, None)
        if trainer is None or worker is None:
            raise ValueError(
                f'unknown trainer/device_worker pair '
                f'({trainer_name!r}, {worker_name!r})')
        t = trainer()
        w = worker()
        if 'fleet_desc' in opt_info:
            t._set_fleet_desc(opt_info['fleet_desc'])
            w._set_fleet_desc(opt_info['fleet_desc'])
        t._set_device_worker(w)
        return t


class FetchHandler:
    """User hook receiving {var_name: ndarray} every `period` seconds
    while a dataset-training run is live."""

    def __init__(self, var_dict=None, period_secs=60):
        if var_dict is None:
            raise ValueError('var_dict must map names to variables')
        self.var_dict = var_dict
        self.period_secs = period_secs

    def handler(self, res_dict):
        for k, v in res_dict.items():
            if isinstance(v, np.ndarray):
                print(f'{k}[0]: {v.ravel()[:1]}')

    @staticmethod
    def help():
        print('''\
class FetchHandlerExample(FetchHandler):
    def handler(self, res_dict):
        print(res_dict["var_name"])
''')


class FetchHandlerMonitor:
    """Polls a scope for the handler's variables on a daemon thread
    (reference trainer_factory.py:114)."""

    def __init__(self, scope, handler):
        self.scope = scope
        self.handler = handler
        self._stop = threading.Event()
        self._thread = None
        self._running = False

    def _lookup(self, name):
        try:
            v = self.scope.find_var(name)
        except Exception:
            v = getattr(self.scope, 'vars', {}).get(name)
        if v is None:
            return None
        val = getattr(v, 'value', v)
        try:
            return np.asarray(val)
        except Exception:
            return None

    def _loop(self):
        while not self._stop.is_set():
            if self._stop.wait(self.handler.period_secs):
                break
            res = {user_name: self._lookup(getattr(var, 'name', var))
                   for user_name, var in self.handler.var_dict.items()}
            self.handler.handler(res)

    def start(self):
        if not self._running:
            self._running = True
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()

    def stop(self):
        self._stop.set()
        self._running = False
