"""fluid.optimizer — legacy optimizer names.

Reference analogue: /root/reference/python/paddle/fluid/optimizer.py:
classes were named SGDOptimizer/AdamOptimizer/..., took
`parameter_list=` instead of `parameters=`, and `regularization=`
instead of `weight_decay=`.  Adapters translate both spellings.
"""
from .. import optimizer as _opt

__all__ = ['SGDOptimizer', 'MomentumOptimizer', 'AdagradOptimizer',
           'AdamOptimizer', 'AdamaxOptimizer', 'RMSPropOptimizer',
           'AdadeltaOptimizer', 'LambOptimizer', 'SGD', 'Momentum',
           'Adam', 'AdamW']


def _legacy(cls):
    def make(learning_rate=0.001, parameter_list=None, parameters=None,
             regularization=None, weight_decay=None, grad_clip=None,
             **kwargs):
        kwargs.pop('name', None)
        wd = weight_decay if weight_decay is not None else regularization
        extra = {}
        if wd is not None:
            extra['weight_decay'] = wd
        return cls(learning_rate=learning_rate,
                   parameters=parameters or parameter_list,
                   grad_clip=grad_clip, **extra, **kwargs)
    make.__name__ = cls.__name__ + 'Legacy'
    return make


SGDOptimizer = _legacy(_opt.SGD)
MomentumOptimizer = _legacy(_opt.Momentum)
AdagradOptimizer = _legacy(_opt.Adagrad)
AdamOptimizer = _legacy(_opt.Adam)
AdamaxOptimizer = _legacy(_opt.Adamax)
RMSPropOptimizer = _legacy(_opt.RMSProp)
AdadeltaOptimizer = _legacy(_opt.Adadelta)
LambOptimizer = _legacy(_opt.Lamb)

# 2.x names pass through
SGD = _opt.SGD
Momentum = _opt.Momentum
Adam = _opt.Adam
AdamW = _opt.AdamW
