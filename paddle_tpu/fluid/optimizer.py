"""fluid.optimizer — legacy optimizer names.

Reference analogue: /root/reference/python/paddle/fluid/optimizer.py:
classes were named SGDOptimizer/AdamOptimizer/..., took
`parameter_list=` instead of `parameters=`, and `regularization=`
instead of `weight_decay=`.  Adapters translate both spellings.
"""
from .. import optimizer as _opt

__all__ = ['SGDOptimizer', 'MomentumOptimizer', 'AdagradOptimizer',
           'AdamOptimizer', 'AdamaxOptimizer', 'RMSPropOptimizer',
           'AdadeltaOptimizer', 'LambOptimizer', 'SGD', 'Momentum',
           'Adam', 'AdamW']


def _legacy(cls):
    def make(learning_rate=0.001, parameter_list=None, parameters=None,
             regularization=None, weight_decay=None, grad_clip=None,
             **kwargs):
        kwargs.pop('name', None)
        wd = weight_decay if weight_decay is not None else regularization
        extra = {}
        if wd is not None:
            extra['weight_decay'] = wd
        return cls(learning_rate=learning_rate,
                   parameters=parameters or parameter_list,
                   grad_clip=grad_clip, **extra, **kwargs)
    make.__name__ = cls.__name__ + 'Legacy'
    return make


SGDOptimizer = _legacy(_opt.SGD)
MomentumOptimizer = _legacy(_opt.Momentum)
AdagradOptimizer = _legacy(_opt.Adagrad)
AdamOptimizer = _legacy(_opt.Adam)
AdamaxOptimizer = _legacy(_opt.Adamax)
RMSPropOptimizer = _legacy(_opt.RMSProp)
AdadeltaOptimizer = _legacy(_opt.Adadelta)
LambOptimizer = _legacy(_opt.Lamb)

# 2.x names pass through
SGD = _opt.SGD
Momentum = _opt.Momentum
Adam = _opt.Adam
AdamW = _opt.AdamW


# bare legacy names (the reference exports both spellings)
Adagrad = _legacy(_opt.Adagrad)
Adamax = _legacy(_opt.Adamax)
Adadelta = _legacy(_opt.Adadelta)
def LarsMomentum(learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameter_list=None, parameters=None,
                 regularization=None, grad_clip=None, name=None):
    wd = lars_weight_decay
    if regularization is not None:
        # the reference folds the L2 regularizer into the lars decay
        wd = getattr(regularization, '_coeff', regularization)
    return _opt.Lars(learning_rate=learning_rate, momentum=momentum,
                     lars_coeff=lars_coeff, lars_weight_decay=wd,
                     parameters=parameters or parameter_list,
                     grad_clip=grad_clip)


LarsMomentumOptimizer = LarsMomentum


def _incubate_alias(name):
    def make(*args, **kwargs):
        from ..incubate import optimizer as _iopt
        return getattr(_iopt, name)(*args, **kwargs)
    make.__name__ = name
    return make


ModelAverage = _incubate_alias('ModelAverage')
LookaheadOptimizer = _incubate_alias('LookAhead')


class DecayedAdagrad(_opt.Adagrad):
    """Adagrad whose accumulator decays (reference
    DecayedAdagradOptimizer): acc = decay*acc + (1-decay)*g^2."""

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6,
                 parameter_list=None, parameters=None,
                 regularization=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(
            learning_rate=learning_rate, epsilon=epsilon,
            parameters=parameters or parameter_list,
            weight_decay=weight_decay if weight_decay is not None
            else regularization, grad_clip=grad_clip)
        self._decay = float(decay)

    def _rule(self, p, g, state, lr, t):
        import jax.numpy as jnp
        acc = state['moment']
        acc = self._decay * acc + (1.0 - self._decay) * g * g
        new_p = p - (lr * g / (jnp.sqrt(acc)
                               + self._epsilon)).astype(p.dtype)
        return new_p, {'moment': acc}


DecayedAdagradOptimizer = DecayedAdagrad


class Ftrl(_opt.Optimizer):
    """FTRL-proximal (reference FtrlOptimizer / ftrl_op): the
    squared-gradient accumulator plus the linear term with L1/L2
    shrinkage."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0,
                 lr_power=-0.5, parameter_list=None, parameters=None,
                 regularization=None, grad_clip=None, name=None):
        super().__init__(learning_rate=learning_rate,
                         parameters=parameters or parameter_list,
                         weight_decay=regularization,
                         grad_clip=grad_clip)
        self._l1 = float(l1)
        self._l2 = float(l2)
        self._lr_power = float(lr_power)

    def _create_state(self, p_value):
        import jax.numpy as jnp
        return {'squared': jnp.zeros_like(p_value),
                'linear': jnp.zeros_like(p_value)}

    def _rule(self, p, g, state, lr, t):
        import jax.numpy as jnp
        n, z = state['squared'], state['linear']
        new_n = n + g * g
        sigma = (jnp.power(new_n, -self._lr_power)
                 - jnp.power(jnp.maximum(n, 1e-38),
                             -self._lr_power)) / lr
        # first step: n was 0 -> sigma reduces to n_new^{-power}/lr
        sigma = jnp.where(n > 0, sigma,
                          jnp.power(new_n, -self._lr_power) / lr)
        new_z = z + g - sigma * p
        pre = jnp.clip(new_z, -self._l1, self._l1) - new_z
        denom = (jnp.power(new_n, -self._lr_power) / lr) + 2 * self._l2
        new_p = jnp.where(jnp.abs(new_z) > self._l1,
                          pre / denom,
                          jnp.zeros_like(p)).astype(p.dtype)
        return new_p, {'squared': new_n, 'linear': new_z}


FtrlOptimizer = Ftrl


class Dpsgd(_opt.SGD):
    """Differentially-private SGD (reference DpsgdOptimizer /
    dpsgd_op): per-update clip to `clip` then Gaussian noise scaled
    by sigma = sqrt(2 log(1.25/delta)) / batch_size."""

    def __init__(self, learning_rate=0.001, clip=0.9,
                 batch_size=0.999, sigma=1.0, parameter_list=None,
                 parameters=None, seed=0, name=None):
        super().__init__(learning_rate=learning_rate,
                         parameters=parameters or parameter_list)
        self._dp_clip = float(clip)
        self._dp_batch = float(batch_size)
        self._dp_sigma = float(sigma)
        self._dp_seed = seed

    def _rule(self, p, g, state, lr, t):
        import jax
        import jax.numpy as jnp
        import zlib
        norm = jnp.sqrt(jnp.maximum(jnp.sum(g * g), 1e-20))
        g = g * jnp.minimum(1.0, self._dp_clip / norm)
        # fold in a per-parameter identity: same-shaped params must
        # NOT share a noise draw (correlated noise breaks the DP
        # accounting)
        pid = zlib.crc32(str(self._ctx_param_name).encode())
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(self._dp_seed),
                               jnp.asarray(t, jnp.int32)),
            pid & 0x7fffffff)
        noise = jax.random.normal(key, g.shape, g.dtype) \
            * (self._dp_sigma * self._dp_clip / self._dp_batch)
        return (p - lr * (g + noise)).astype(p.dtype), state


DpsgdOptimizer = Dpsgd


class ExponentialMovingAverage:
    """EMA of parameters (reference fluid/optimizer.py
    ExponentialMovingAverage): update() refreshes the shadow values,
    apply() swaps them in (a context manager restores on exit)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._shadow = {}
        self._backup = {}
        self._step = 0
        self._params = None

    def _ensure(self, params):
        import numpy as np
        params = list(params)
        if not self._params and params:
            self._params = params
            for i, p in enumerate(self._params):
                self._shadow[i] = np.asarray(p.value).copy()

    def update(self, parameters=None):
        """Refresh the shadow from the live parameters.  Call after
        each optimizer step (the reference hooks the train program)."""
        import numpy as np
        if parameters is not None:
            self._ensure(parameters)
        if not self._params:
            raise ValueError(
                'ExponentialMovingAverage has no parameters: pass '
                'parameters= to update() (or _ensure) first')
        self._step += 1
        if self._thres_steps is not None:
            # the reference ramps the decay only when thres_steps is
            # given; otherwise the configured decay applies as-is
            d = min(self._decay,
                    (1.0 + self._step) / (10.0 + self._step))
        else:
            d = self._decay
        for i, p in enumerate(self._params):
            self._shadow[i] = (d * self._shadow[i]
                               + (1.0 - d) * np.asarray(p.value))

    def apply(self, executor=None, need_restore=True):
        """Context manager: params take their EMA values inside."""
        import contextlib
        if not self._params:
            raise ValueError(
                'ExponentialMovingAverage has no parameters '
                'registered; call update(parameters=...) first')

        @contextlib.contextmanager
        def _ctx():
            import numpy as np
            import jax.numpy as jnp
            if not self._params:
                raise ValueError(
                    'ExponentialMovingAverage has no parameters '
                    'registered; call update(parameters=...) first')
            for i, p in enumerate(self._params):
                self._backup[i] = np.asarray(p.value).copy()
                p.set_value(jnp.asarray(self._shadow[i]))
            try:
                yield self
            finally:
                if need_restore:
                    self.restore()
        return _ctx()

    def restore(self, executor=None):
        import jax.numpy as jnp
        for i, p in enumerate(self._params):
            if i in self._backup:
                p.set_value(jnp.asarray(self._backup[i]))
        self._backup = {}


class PipelineOptimizer:
    """Reference PipelineOptimizer wraps an optimizer for pipeline
    sections.  In the TPU-native stack pipelining is a
    DistributedStrategy flag consumed by ParallelTrainer (the 1F1B
    engine); this wrapper keeps the API and forwards to the inner
    optimizer."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self._inner = optimizer
        self.num_microbatches = num_microbatches

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program,
                                    parameter_list, no_grad_set)


class RecomputeOptimizer:
    """Reference RecomputeOptimizer: activation recompute is a
    strategy flag here (strategy.recompute -> jax.checkpoint in
    ParallelTrainer); the wrapper keeps API parity."""

    def __init__(self, optimizer):
        self._inner = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program,
                                    parameter_list, no_grad_set)


__all__ += ['Adagrad', 'Adamax', 'Adadelta', 'LarsMomentum',
            'LarsMomentumOptimizer', 'ModelAverage',
            'LookaheadOptimizer', 'DecayedAdagrad',
            'DecayedAdagradOptimizer', 'Ftrl', 'FtrlOptimizer',
            'Dpsgd', 'DpsgdOptimizer', 'ExponentialMovingAverage',
            'PipelineOptimizer', 'RecomputeOptimizer']
