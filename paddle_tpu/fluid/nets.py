"""fluid.nets — the classic composed blocks (reference fluid/nets.py):
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention — built from static.nn/layers ops."""
from . import layers
from ..nn import functional as _F
from .. import tensor as _T

__all__ = ['simple_img_conv_pool', 'img_conv_group',
           'sequence_conv_pool', 'glu', 'scaled_dot_product_attention']


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type='max',
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    conv = layers.conv2d(input, num_filters, filter_size,
                         stride=conv_stride, padding=conv_padding,
                         dilation=conv_dilation, groups=conv_groups,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act)
    return layers.pool2d(conv, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride,
                         pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   param_attr=None, conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_stride=1,
                   pool_type='max', use_cudnn=True):
    tmp = input
    n = len(conv_num_filter)

    def at(v, i):
        return v[i] if isinstance(v, (list, tuple)) else v
    for i in range(n):
        tmp = layers.conv2d(tmp, conv_num_filter[i],
                            at(conv_filter_size, i),
                            padding=at(conv_padding, i),
                            param_attr=at(param_attr, i)
                            if isinstance(param_attr, (list, tuple))
                            else param_attr,
                            act=None if conv_with_batchnorm
                            else at(conv_act, i))
        if conv_with_batchnorm:
            tmp = layers.batch_norm(tmp, act=conv_act)
            rate = at(conv_batchnorm_drop_rate, i)
            if rate:
                tmp = layers.dropout(tmp, rate)
    return layers.pool2d(tmp, pool_size=pool_size,
                         pool_stride=pool_stride, pool_type=pool_type)


def sequence_conv_pool(input, seq_len, num_filters, filter_size,
                       param_attr=None, act='sigmoid', pool_type='max'):
    """Padded-dense rendering of the reference's LoD
    sequence_conv+sequence_pool pair (see static/sequence.py)."""
    from ..static import sequence as S
    conv = S.sequence_conv(input, seq_len, num_filters, filter_size)
    if act:
        conv = getattr(_F, act)(conv)
    return S.sequence_pool(conv, pool_type, seq_len)


def glu(input, dim=-1):
    a, b = _T.split(input, 2, axis=dim)
    return _T.multiply(a, _F.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head attention over [B, T, D] (reference nets.py; the hot
    path uses ops.flash_attention — this is the compatibility form)."""
    import math
    B, Tq, D = queries.shape
    if D % num_heads:
        raise ValueError('hidden size must divide num_heads')
    hd = D // num_heads

    def split_heads(x):
        T = x.shape[1]
        return _T.transpose(_T.reshape(x, [B, T, num_heads, hd]),
                            [0, 2, 1, 3])
    q, k, v = map(split_heads, (queries, keys, values))
    scores = _T.multiply(_T.matmul(q, _T.transpose(k, [0, 1, 3, 2])),
                         1.0 / math.sqrt(hd))
    w = _F.softmax(scores, axis=-1)
    if dropout_rate:
        w = _F.dropout(w, p=dropout_rate)
    out = _T.matmul(w, v)
    return _T.reshape(_T.transpose(out, [0, 2, 1, 3]), [B, Tq, D])
