"""fluid.average (reference: python/paddle/fluid/average.py)."""
import numpy as np

__all__ = ['WeightedAverage']


def _is_number_or_matrix(x):
    return isinstance(x, (int, float, np.ndarray)) or (
        hasattr(x, 'value') or hasattr(x, '__float__'))


class WeightedAverage:
    """Running weighted mean of scalars/arrays (reference average.py:40)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError('add(): value must be a number or ndarray')
        if not isinstance(weight, (int, float)):
            raise ValueError('add(): weight must be a number')
        v = np.mean(np.asarray(
            value.value if hasattr(value, 'value') else value,
            dtype=np.float64))
        if self.numerator is None:
            self.numerator = v * weight
            self.denominator = float(weight)
        else:
            self.numerator += v * weight
            self.denominator += float(weight)

    def eval(self):
        if not self.denominator:
            raise ValueError(
                'there is no data in WeightedAverage; call add() first')
        return self.numerator / self.denominator
