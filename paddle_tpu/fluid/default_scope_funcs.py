"""fluid.default_scope_funcs (reference: python/paddle/fluid/
default_scope_funcs.py) — a thread-local stack of nested variable
scopes rooted at the static global scope."""
import threading

from ..static.program import global_scope

__all__ = ['get_cur_scope', 'enter_local_scope', 'leave_local_scope',
           'var', 'find_var', 'scoped_function']


class _LocalScope:
    def __init__(self, parent):
        self.parent = parent
        self.vars = {}

    def find(self, name):
        if name in self.vars:
            return self.vars[name]
        if self.parent is not None:
            return self.parent.find(name)
        return None


class _RootAdapter:
    """Adapts the static global scope to the find() protocol."""

    def find(self, name):
        sc = global_scope()
        try:
            return sc.find_var(name)
        except Exception:
            return getattr(sc, 'vars', {}).get(name)


_tls = threading.local()


def get_cur_scope():
    stack = getattr(_tls, 'stack', None)
    if not stack:
        _tls.stack = stack = [_LocalScope(_RootAdapter())]
    return stack[-1]


def enter_local_scope():
    cur = get_cur_scope()
    _tls.stack.append(_LocalScope(cur))


def leave_local_scope():
    if len(_tls.stack) <= 1:
        raise RuntimeError('cannot leave the root scope')
    _tls.stack.pop()


def var(name):
    """Create (or fetch) `name` in the current scope."""
    cur = get_cur_scope()
    if name not in cur.vars:
        cur.vars[name] = _Placeholder(name)
    return cur.vars[name]


class _Placeholder:
    def __init__(self, name):
        self.name = name
        self.value = None


def find_var(name):
    return get_cur_scope().find(name)


def scoped_function(func):
    """Run func inside a fresh local scope (reference
    default_scope_funcs.py:72)."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
