"""fluid.trainer_desc (reference: python/paddle/fluid/trainer_desc.py).

Dict-backed trainer descriptors (the reference builds protobufs for
the C++ trainer registry; the TPU-native executor reads these dicts in
its dataset-training loop).
"""

__all__ = ['TrainerDesc', 'MultiTrainer', 'DistMultiTrainer',
           'PipelineTrainer', 'HeterXpuTrainer', 'HeterBoxWorker']


class TrainerDesc:
    def __init__(self):
        self.proto = {'class_name': '', 'thread_num': 1, 'debug': False,
                      'fetch_config': {}}
        self._device_worker = None
        self._program = None
        self._infer = False

    def _set_fetch_var_and_info(self, fetch_vars, fetch_info,
                                print_period):
        self.proto['fetch_config'] = {
            'fetch_vars': [getattr(v, 'name', str(v)) for v in fetch_vars],
            'fetch_info': list(fetch_info),
            'print_period': print_period}

    def _set_debug(self, debug):
        self.proto['debug'] = bool(debug)

    def _set_thread(self, thread_num):
        self.proto['thread_num'] = int(thread_num)

    def _set_device_worker(self, device_worker):
        self._device_worker = device_worker

    def _set_infer(self, infer):
        self._infer = bool(infer)

    def _set_program(self, program):
        self._program = program

    def _set_fleet_desc(self, fleet_desc):
        self.proto['fleet_desc'] = fleet_desc

    def _gen_trainer_desc(self):
        if self._device_worker is not None:
            self._device_worker._set_infer(self._infer)
            self._device_worker._set_program(self._program)
            self._device_worker._gen_worker_desc(self)
        return self.proto


class MultiTrainer(TrainerDesc):
    def _gen_trainer_desc(self):
        self.proto['class_name'] = 'MultiTrainer'
        return super()._gen_trainer_desc()


class DistMultiTrainer(TrainerDesc):
    def _gen_trainer_desc(self):
        self.proto['class_name'] = 'DistMultiTrainer'
        return super()._gen_trainer_desc()


class PipelineTrainer(TrainerDesc):
    def _gen_trainer_desc(self):
        self.proto['class_name'] = 'PipelineTrainer'
        return super()._gen_trainer_desc()


class HeterXpuTrainer(TrainerDesc):
    def _gen_trainer_desc(self):
        self.proto['class_name'] = 'HeterXpuTrainer'
        return super()._gen_trainer_desc()


class HeterBoxWorker(TrainerDesc):
    def _gen_trainer_desc(self):
        self.proto['class_name'] = 'HeterBoxWorker'
        return super()._gen_trainer_desc()
