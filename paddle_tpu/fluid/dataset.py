"""fluid.dataset (reference: python/paddle/fluid/dataset.py) — factory
over the fleet dataset implementations (distributed/dataset.py, with
the native C++ slot-file parser underneath)."""
from ..distributed.dataset import (  # noqa: F401
    DatasetBase, InMemoryDataset, QueueDataset)

__all__ = ['DatasetFactory', 'InMemoryDataset', 'QueueDataset']


class DatasetFactory:
    """Reference dataset.py:30 — create_dataset('InMemoryDataset')."""

    def create_dataset(self, datafeed_class='QueueDataset'):
        table = {'InMemoryDataset': InMemoryDataset,
                 'QueueDataset': QueueDataset}
        if datafeed_class not in table:
            raise ValueError(
                f'unknown dataset class {datafeed_class!r}; choose from '
                f'{sorted(table)}')
        return table[datafeed_class]()
