"""fluid.data_feeder (reference: python/paddle/fluid/data_feeder.py).

DataFeeder converts minibatch rows (lists/tuples of per-slot samples)
into the feed dict an Executor.run accepts.  TPU-native: the values
become numpy arrays batched on the host; device transfer happens once
inside the compiled program run.
"""
import numpy as np

from ..core.tensor import Tensor

__all__ = ['DataFeeder']


def _var_name(v):
    return v if isinstance(v, str) else getattr(v, 'name', str(v))


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        if not feed_list:
            raise ValueError('feed_list must name at least one variable')
        self.feed_names = [_var_name(v) for v in feed_list]
        self.place = place

    def feed(self, iterable):
        """Batch rows → {name: ndarray}.  Each row supplies one value
        per feed variable, in feed_list order."""
        cols = [[] for _ in self.feed_names]
        for row in iterable:
            if len(row) != len(self.feed_names):
                raise ValueError(
                    f'row has {len(row)} fields, feeder expects '
                    f'{len(self.feed_names)}')
            for c, v in zip(cols, row):
                c.append(np.asarray(
                    v.value if isinstance(v, Tensor) else v))
        return {name: self._stack(c)
                for name, c in zip(self.feed_names, cols)}

    @staticmethod
    def _stack(samples):
        """Batch one slot; ragged samples (the 1.x LoD feed case) are
        zero-padded to the per-dimension max — the padded-dense
        redesign of the reference's LoD batch."""
        shapes = {s.shape for s in samples}
        if len(shapes) == 1:
            return np.stack(samples)
        if len({s.ndim for s in samples}) != 1:
            raise ValueError('samples in one slot must share a rank, '
                             f'got shapes {sorted(shapes)}')
        dims = [max(s.shape[d] for s in samples)
                for d in range(samples[0].ndim)]
        out = np.zeros((len(samples), *dims), samples[0].dtype)
        for i, s in enumerate(samples):
            out[(i, *map(slice, s.shape))] = s
        return out

    def feed_parallel(self, iterable, num_places=None):
        """1.x multi-device feed: one feed dict per place.  Devices are
        fed by sharding the batch on the dp mesh axis here, so this
        yields the single batched dict (the sharding constraint does
        the split)."""
        yield self.feed(iterable)

    def decorate_reader(self, reader, multi_devices=False,
                        num_places=None, drop_last=True):
        def _reader():
            for batch in reader():
                yield self.feed(batch)
        return _reader
