"""fluid.lod_tensor (reference: python/paddle/fluid/lod_tensor.py).

LoD (level-of-detail) ragged tensors are redesigned away in the
TPU-native stack — variable-length data is padded dense + lengths
(static/sequence.py).  These builders keep the 1.x construction API:
they return a padded dense Tensor carrying its recursive sequence
lengths as `.recursive_sequence_lengths()`, which the sequence_* ops
accept.
"""
import numpy as np

from ..core.tensor import Tensor

__all__ = ['create_lod_tensor', 'create_random_int_lodtensor']


def _flatten_lengths(recursive_seq_lens):
    if not isinstance(recursive_seq_lens, (list, tuple)) or not all(
            isinstance(l, (list, tuple)) for l in recursive_seq_lens):
        raise TypeError('recursive_seq_lens must be a list of lists')
    return [list(map(int, l)) for l in recursive_seq_lens]


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Build a padded-dense tensor from flat `data` plus per-sequence
    lengths (reference lod_tensor.py:25).  The innermost length list
    partitions data's rows into sequences; rows pad to the max."""
    lens = _flatten_lengths(recursive_seq_lens)
    inner = lens[-1]
    arr = np.asarray(data.value if isinstance(data, Tensor) else data)
    if arr.ndim == 1:
        arr = arr[:, None]
    if sum(inner) != arr.shape[0]:
        raise ValueError(
            f'sum of innermost seq lens {sum(inner)} != rows '
            f'{arr.shape[0]}')
    maxlen = max(inner) if inner else 0
    out = np.zeros((len(inner), maxlen) + arr.shape[1:], arr.dtype)
    off = 0
    for i, n in enumerate(inner):
        out[i, :n] = arr[off:off + n]
        off += n
    t = Tensor(out)
    t._recursive_seq_lens = lens
    # the 1.x LoDTensor read-back API
    t.recursive_sequence_lengths = lambda: lens
    t.lod = lambda: [list(_accumulate(l)) for l in lens]
    return t


def _accumulate(lengths):
    off = 0
    yield off
    for n in lengths:
        off += n
        yield off


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high):
    """Random integer ragged tensor (reference lod_tensor.py:110)."""
    lens = _flatten_lengths(recursive_seq_lens)
    total = sum(lens[-1])
    data = np.random.randint(low, high + 1,
                             size=[total] + list(base_shape))
    return create_lod_tensor(data, recursive_seq_lens, place)
