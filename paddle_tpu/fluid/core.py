"""fluid.core — the pybind surface legacy code pokes at.

Reference analogue: paddle/fluid/pybind/ exposing C++ types.  There is
no C++ scope/LoD machinery here (XLA owns memory; ragged data is
padded-dense + seq_len — see static/sequence.py), so LoDTensor is the
minimal value-carrying shim and Scope aliases the Executor scope.
"""
import numpy as np

from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, XPUPlace, NPUPlace, CUDAPinnedPlace)
from ..static.program import global_scope, _Scope as Scope  # noqa: F401

__all__ = ['LoDTensor', 'LoDTensorArray', 'Scope', 'CPUPlace',
           'CUDAPlace', 'XPUPlace', 'NPUPlace', 'CUDAPinnedPlace']


class LoDTensor:
    """Value + level-of-detail offsets (reference core LoDTensor).  The
    TPU-native data path is padded-dense, so this only carries the
    array and its recursive_sequence_lengths for code that constructs
    feeds the 1.x way."""

    def __init__(self):
        self._array = None
        self._lod = []

    def set(self, array, place=None):
        self._array = np.asarray(array)

    def set_recursive_sequence_lengths(self, lengths):
        self._lod = [list(l) for l in lengths]

    def recursive_sequence_lengths(self):
        return [list(l) for l in self._lod]

    def __array__(self, dtype=None):
        a = self._array
        return a.astype(dtype) if dtype is not None else a

    def shape(self):
        return list(self._array.shape) if self._array is not None else []


class LoDTensorArray(list):
    pass
