"""fluid.metrics — the legacy streaming metric classes.

Reference analogue: /root/reference/python/paddle/fluid/metrics.py
(MetricBase, CompositeMetric, Precision, Recall, Accuracy,
ChunkEvaluator, EditDistance, DetectionMAP, Auc).  Precision/Recall/
Auc route to the jit-safe paddle_tpu.metric implementations; the
value-streaming Accuracy, EditDistance and DetectionMAP are host-side
accumulators like the reference's (they consume already-computed
per-batch values).  ChunkEvaluator is a documented non-goal
(chunk-scheme parsing; see fluid.contrib chunk_eval)."""
import numpy as np

from ..metric import Precision, Recall, Auc   # noqa: F401

__all__ = ['MetricBase', 'CompositeMetric', 'Precision', 'Recall',
           'Accuracy', 'ChunkEvaluator', 'EditDistance',
           'DetectionMAP', 'Auc']


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def get_config(self):
        return {'name': self._name}

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    """VALUE-streaming accuracy (reference fluid/metrics.py::Accuracy):
    update(value, weight) accumulates pre-computed batch accuracies —
    unlike paddle.metric.Accuracy, which consumes predictions."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if weight < 0:
            raise ValueError('weight must be nonnegative')
        self.value += float(np.asarray(value).ravel()[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError('no batches accumulated')
        return self.value / self.weight


class CompositeMetric(MetricBase):
    """Bundle several metrics updated with the same inputs."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase) and \
                not hasattr(metric, 'update'):
            raise ValueError('metric must expose update/eval')
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def eval(self):
        out = []
        for m in self._metrics:
            out.append(m.eval() if hasattr(m, 'eval')
                       else m.accumulate())
        return out


class EditDistance(MetricBase):
    """Streaming (average edit distance, instance error rate)
    (reference fluid/metrics.py::EditDistance): update() takes the
    per-batch distances the edit-distance op computed plus the
    sequence count."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances, np.float64).reshape(-1)
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((d > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError('no sequences accumulated')
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class DetectionMAP(MetricBase):
    """Streaming mean-average-precision over padded detection
    outputs (reference fluid/metrics.py::DetectionMAP +
    detection_map_op): update() takes one batch's detections
    [(label, score, x1, y1, x2, y2)] and ground truths
    [(label, x1, y1, x2, y2)]; eval() computes mAP with the
    '11point' or 'integral' rule."""

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version='integral'):
        super().__init__(name)
        if ap_version not in ('integral', '11point'):
            raise ValueError(f'unknown ap_version {ap_version!r}')
        if not evaluate_difficult:
            raise NotImplementedError(
                'evaluate_difficult=False needs a difficult flag in '
                'the gt rows, which the padded 5-column format does '
                'not carry — filter difficult gts before update() '
                'instead')
        self._thr = float(overlap_threshold)
        self._ap = ap_version
        self.reset()

    def reset(self):
        self._dets = []     # (label, score, box, image_id)
        self._gts = []      # (label, box, image_id)
        self._img = 0

    @staticmethod
    def _iou(a, b):
        x1 = max(a[0], b[0])
        y1 = max(a[1], b[1])
        x2 = min(a[2], b[2])
        y2 = min(a[3], b[3])
        iw, ih = max(x2 - x1, 0.0), max(y2 - y1, 0.0)
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gts):
        """One image's detections [[label, score, 4 coords]] and
        ground truths [[label, 4 coords]] (padded rows with label < 0
        are skipped)."""
        img = self._img
        for d in np.asarray(detections, np.float64).reshape(-1, 6):
            if d[0] >= 0:
                self._dets.append((int(d[0]), float(d[1]),
                                   tuple(d[2:6]), img))
        for g in np.asarray(gts, np.float64).reshape(-1, 5):
            if g[0] >= 0:
                self._gts.append((int(g[0]), tuple(g[1:5]), img))
        self._img += 1

    def eval(self):
        classes = sorted({g[0] for g in self._gts})
        if not classes:
            raise ValueError('no ground truths accumulated')
        aps = []
        for c in classes:
            gts_c = [(g[2], g[1]) for g in self._gts if g[0] == c]
            npos = len(gts_c)
            dets_c = sorted((d for d in self._dets if d[0] == c),
                            key=lambda d: -d[1])
            matched = set()
            tps, fps = [], []
            for _, score, box, img in dets_c:
                best, best_g = 0.0, None
                # VOC protocol: the detection is judged against its
                # MAX-IoU gt (matched or not) — a duplicate of an
                # already-claimed gt is a false positive, it may not
                # steal the next-best gt
                for gi, (gimg, gbox) in enumerate(gts_c):
                    if gimg != img:
                        continue
                    iou = self._iou(box, gbox)
                    if iou > best:
                        best, best_g = iou, gi
                if best >= self._thr and best_g is not None \
                        and best_g not in matched:
                    matched.add(best_g)
                    tps.append(1)
                    fps.append(0)
                else:
                    tps.append(0)
                    fps.append(1)
            tp = np.cumsum(tps) if tps else np.zeros(0)
            fp = np.cumsum(fps) if fps else np.zeros(0)
            rec = tp / max(npos, 1)
            prec = tp / np.maximum(tp + fp, 1e-12)
            if self._ap == '11point':
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    p = prec[rec >= t].max() if (rec >= t).any() \
                        else 0.0
                    ap += p / 11.0
            else:
                ap = 0.0
                prev_r = 0.0
                for r, p in zip(rec, prec):
                    ap += (r - prev_r) * p
                    prev_r = r
            aps.append(ap)
        return float(np.mean(aps))


class ChunkEvaluator(MetricBase):
    def __init__(self, *a, **k):
        raise NotImplementedError(
            'ChunkEvaluator is a documented non-goal (chunk-scheme '
            'parsing, see fluid.contrib chunk_eval): compute chunk F1 '
            'from crf_decoding output host-side')
