"""fluid.contrib — the reference's incubating utilities.

Reference analogue: /root/reference/python/paddle/fluid/contrib/
(layers/, extend_optimizer/, memory_usage_calc.py, op_frequence.py,
slim/, mixed_precision/, quantize/, decoder/).

What ships here (TPU-native implementations): `layers`
(ctr_metric_bundle, shuffle_batch, partial_concat, partial_sum,
multiclass_nms2, sparse_embedding, fused_elemwise_activation),
`extend_optimizer` (extend_with_decoupled_weight_decay),
`memory_usage_calc.memory_usage` and `op_frequence.op_freq_statistic`.

Explicit NON-GOALS (each already covered by a first-class subsystem or
tied to deleted machinery — see SURVEY.md non-goals):
  * contrib.slim / contrib.quantize → `paddle_tpu.quantization`
    (QAT + PTQ with STE custom_vjp) is the supported toolkit;
  * contrib.mixed_precision → `paddle_tpu.amp` / `static.amp`;
  * contrib.decoder (beam search) → `nn.decode.BeamSearchDecoder`;
  * tdm_child/tdm_sampler, search_pyramid_hash, var_conv_2d,
    match_matrix_tensor, tree_conv, bilateral_slice, correlation,
    rank_attention, batch_fc, _pull_box_extended_sparse → tree-index
    retrieval / LoD-sequence / BoxPS ops with no public users in the
    reference's 2.x API surface and no TPU-side demand; they raise
    with pointers when imported via __getattr__.
"""
from . import layers  # noqa: F401
from . import extend_optimizer  # noqa: F401
from . import memory_usage_calc  # noqa: F401
from . import op_frequence  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401
from .op_frequence import op_freq_statistic  # noqa: F401

__all__ = ['layers', 'extend_optimizer', 'memory_usage_calc',
           'op_frequence', 'memory_usage', 'op_freq_statistic']
