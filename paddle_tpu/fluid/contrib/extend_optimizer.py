"""fluid.contrib.extend_optimizer — decoupled weight decay mixin.

Reference analogue: /root/reference/python/paddle/fluid/contrib/
extend_optimizer/extend_optimizer_with_weight_decay.py:20
(DecoupledWeightDecay scales each parameter by (1 - coeff) outside
the gradient path; extend_with_decoupled_weight_decay:102 builds a
subclass of any optimizer with that behaviour — AdamW is
extend_with_decoupled_weight_decay(Adam)).

TPU-native: our optimizers are (init, update) cores, so the decay is
one extra `p - lr * coeff * p` term folded into the same compiled
update step, not a separate scale op."""

__all__ = ['DecoupledWeightDecay', 'extend_with_decoupled_weight_decay']


class DecoupledWeightDecay:
    """Mixin: apply `param -= lr * coeff * param` decoupled from the
    gradient-based update (Loshchilov & Hutter)."""

    def __init__(self, coeff=0.0, apply_decay_param_fun=None, **kwargs):
        if not isinstance(coeff, (float, int)):
            raise TypeError('coeff should be float or int')
        self._wd_coeff = float(coeff)
        self._wd_param_fun = apply_decay_param_fun
        super().__init__(**kwargs)

    def _decayed(self, p, new_p, lr, name=None):
        import jax.numpy as jnp
        if self._wd_coeff == 0.0:
            return new_p
        if self._wd_param_fun is not None and \
                not self._wd_param_fun(name):
            return new_p
        return new_p - jnp.asarray(lr, new_p.dtype) \
            * self._wd_coeff * p

    def __str__(self):
        return f'{type(self).__name__} (coeff={self._wd_coeff})'


def extend_with_decoupled_weight_decay(base_optimizer):
    """Return a subclass of `base_optimizer` whose update applies
    decoupled weight decay (reference :102).  Usage matches the
    reference:

        AdamWD = extend_with_decoupled_weight_decay(paddle.optimizer.Adam)
        opt = AdamWD(weight_decay=0.01, learning_rate=1e-3,
                     parameters=model.parameters())
    """
    from ...optimizer.optimizer import Optimizer
    if not (isinstance(base_optimizer, type)
            and issubclass(base_optimizer, Optimizer)):
        raise TypeError('input must be an Optimizer subclass')

    class OptimizerWithDecoupledWeightDecay(DecoupledWeightDecay,
                                            base_optimizer):
        def __init__(self, weight_decay=0.0,
                     apply_decay_param_fun=None, **kwargs):
            # the decoupled coeff REPLACES the base's coupled L2
            # weight_decay (the reference subclass does the same)
            kwargs.pop('weight_decay', None)
            super().__init__(coeff=weight_decay,
                             apply_decay_param_fun=apply_decay_param_fun,
                             **kwargs)

        def _rule(self, p, g, state, lr, t):
            new_p, new_state = super()._rule(p, g, state, lr, t)
            return (self._decayed(p, new_p, lr,
                                  self._ctx_param_name), new_state)

    OptimizerWithDecoupledWeightDecay.__name__ = (
        f'{base_optimizer.__name__}WithDecoupledWeightDecay')
    return OptimizerWithDecoupledWeightDecay
