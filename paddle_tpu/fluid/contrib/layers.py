"""fluid.contrib.layers — incubating layer ops.

Reference analogue:
/root/reference/python/paddle/fluid/contrib/layers/metric_op.py:30
(ctr_metric_bundle) and layers/nn.py (shuffle_batch:784,
partial_concat:848, partial_sum:911, multiclass_nms2:539,
sparse_embedding:965, fused_elemwise_activation:64).

All vectorized jnp; the LoD inputs of the reference become dense
tensors.  The long tail of tree-index / BoxPS ops is a documented
non-goal (see package docstring) and raises with a pointer.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...tensor._helpers import wrap

__all__ = ['ctr_metric_bundle', 'shuffle_batch', 'partial_concat',
           'partial_sum', 'multiclass_nms2', 'sparse_embedding',
           'fused_elemwise_activation']

_NON_GOALS = {
    'tdm_child', 'tdm_sampler', 'search_pyramid_hash', 'var_conv_2d',
    'match_matrix_tensor', 'tree_conv', 'bilateral_slice',
    'correlation', 'rank_attention', 'batch_fc',
    'fused_embedding_seq_pool', 'sequence_topk_avg_pooling',
    'fused_bn_add_act', '_pull_box_extended_sparse',
}


def __getattr__(name):
    if name in _NON_GOALS:
        raise NotImplementedError(
            f'fluid.contrib.layers.{name} is an explicit non-goal: '
            'tree-index retrieval / LoD-sequence / BoxPS machinery '
            'with no 2.x public API surface. See '
            'paddle_tpu/fluid/contrib/__init__.py for the supported '
            'equivalents.')
    raise AttributeError(name)


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """Per-batch CTR metric sums (reference metric_op.py:30): returns
    (local_sqrerr, local_abserr, local_prob, local_q, local_pos_num,
    local_ins_num) as 1-element tensors.  The reference accumulates
    into persistable vars; here each call returns THIS batch's sums —
    accumulate across batches, then allreduce via
    fleet.metrics.mae/rmse exactly like the reference's workflow."""
    def fn(p, y):
        p = p.reshape(-1).astype(jnp.float32)
        y = y.reshape(-1).astype(jnp.float32)
        err = p - y
        sqrerr = jnp.sum(err * err)[None]
        abserr = jnp.sum(jnp.abs(err))[None]
        prob = jnp.sum(p)[None]
        q = jnp.sum(p / jnp.maximum(1.0 - p, 1e-8))[None]
        pos = jnp.sum(y)[None]
        total = jnp.asarray([p.shape[0]], jnp.float32)
        return sqrerr, abserr, prob, q, pos, total
    return apply(fn, wrap(input), wrap(label),
                 op_name='ctr_metric_bundle')


_SHUFFLE_CALLS = [0]


def shuffle_batch(x, seed=None):
    """Shuffle rows (all dims but the last) of x (reference
    nn.py:784).  With seed=None each EAGER call draws a fresh
    permutation (a per-call counter folded into the global seed — the
    reference generates a fresh engine seed per execution); inside a
    compiled step pass an explicit traced-varying seed, since a jit
    trace bakes the counter value."""
    if seed is None:
        from ...core import rng as rng_mod
        _SHUFFLE_CALLS[0] += 1
        seed = rng_mod.get_seed() + 0x9e37 * _SHUFFLE_CALLS[0]

    def fn(v):
        lead = v.shape[:-1]
        n = 1
        for d in lead:
            n *= d
        flat = v.reshape(n, v.shape[-1])
        perm = jax.random.permutation(
            jax.random.PRNGKey(int(seed)), n)
        return flat[perm].reshape(v.shape)
    return apply(fn, wrap(x), op_name='shuffle_batch')


def partial_concat(input, start_index=0, length=-1):
    """Concat a column slice [start_index:start_index+length) of each
    input along axis 1 (reference nn.py:848)."""
    def fn(*vs):
        outs = []
        for v in vs:
            end = v.shape[1] if length < 0 else start_index + length
            outs.append(v[:, start_index:end])
        return jnp.concatenate(outs, axis=1)
    return apply(fn, *[wrap(v) for v in input],
                 op_name='partial_concat')


def partial_sum(input, start_index=0, length=-1):
    """Sum the same column slice across inputs (reference
    nn.py:911)."""
    def fn(*vs):
        acc = None
        for v in vs:
            end = v.shape[1] if length < 0 else start_index + length
            s = v[:, start_index:end]
            acc = s if acc is None else acc + s
        return acc
    return apply(fn, *[wrap(v) for v in input], op_name='partial_sum')


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                    keep_top_k, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0,
                    return_index=False, name=None):
    """Reference nn.py:539 — multiclass NMS that also returns the
    selected box indices.  Routes to the detection suite's
    fixed-shape implementation."""
    from ...vision.detection import multiclass_nms
    return multiclass_nms(
        bboxes, scores, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, normalized=normalized,
        nms_eta=nms_eta, background_label=background_label,
        return_index=return_index, name=name)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype='float32',
                     **unused):
    """Reference nn.py:965: a parameter-server-backed sparse embedding
    lookup.  The TPU-native PS substitute is
    incubate.HostOffloadEmbedding (host-resident table + async host
    sparse update); this builds one per call-site name and applies it.
    `padding_idx` rows read as zero and receive no updates (the output
    mask zeroes both the row and its gradient, reference semantics).
    For in-HBM tables use fleet.VocabParallelEmbedding instead."""
    from ...incubate import HostOffloadEmbedding
    key = ('sparse_embedding',
           getattr(param_attr, 'name', None) or 'default',
           tuple(size), dtype, bool(is_test))
    layer = _SPARSE_CACHE.get(key)
    if layer is None:
        layer = _SPARSE_CACHE[key] = HostOffloadEmbedding(
            size[0], size[1], dtype=dtype, entry=entry,
            trainable=not is_test)
    out = layer(input)
    if padding_idx is not None:
        if padding_idx < 0:
            padding_idx = size[0] + padding_idx
        def mask_fn(o, ids):
            keep = (ids != padding_idx).astype(o.dtype)
            return o * keep[..., None]
        out = apply(mask_fn, wrap(out), wrap(input),
                    op_name='sparse_embedding_pad_mask')
    return out


_SPARSE_CACHE = {}


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """Reference nn.py:64: ['unary', 'binary'] computes
    unary(binary(x, y)); ['binary', 'unary'] computes
    binary(x, unary(y)).  XLA fuses elementwise chains automatically,
    so this is the plain functional composition — same result,
    compiler-fused."""
    binaries = {
        'elementwise_add': lambda a, b: a + b,
        'elementwise_mul': lambda a, b: a * b,
    }
    unaries = {
        'relu': lambda a: jnp.maximum(a, 0),
        'sigmoid': jax.nn.sigmoid,
        'tanh': jnp.tanh,
        'scale': lambda a: a * scale,
    }
    if isinstance(functor_list, str):
        functor_list = functor_list.split(',')
    if not isinstance(functor_list, (list, tuple)) \
            or len(functor_list) != 2:
        raise ValueError('functor_list should be 2 operator names')
    f0, f1 = functor_list
    if f0 in binaries and f1 in unaries:
        def fn(a, b):
            return binaries[f0](a, unaries[f1](b))
    elif f0 in unaries and f1 in binaries:
        def fn(a, b):
            return unaries[f0](binaries[f1](a, b))
    else:
        raise ValueError(
            f'functor_list must pair one of {sorted(binaries)} with '
            f'one of {sorted(unaries)}, got {functor_list}')
    return apply(fn, wrap(x), wrap(y),
                 op_name='fused_elemwise_activation')
