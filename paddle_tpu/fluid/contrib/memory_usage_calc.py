"""fluid.contrib.memory_usage_calc — training memory estimation.

Reference analogue:
/root/reference/python/paddle/fluid/contrib/memory_usage_calc.py
(memory_usage walks the Program's var descs, sums dtype_size * numel,
scales -1 batch dims by the given batch_size, and prints a
low/high range).

TPU-native: there is no ProgramDesc; the estimate walks either a
Layer's parameters or a static Program's recorded op DAG outputs, and
on request asks XLA for the COMPILED memory analysis (exact, includes
fusion temps) via `jit(...).lower().compile().memory_analysis()` —
something the reference could never do pre-compilation.
"""
import numpy as np

__all__ = ['memory_usage']

DEBUG = False

_DTYPE_SIZES = {
    'float64': 8, 'float32': 4, 'float16': 2, 'bfloat16': 2,
    'int64': 8, 'int32': 4, 'int16': 2, 'int8': 1, 'uint8': 1,
    'bool': 1,
}


def _param_bytes(obj, batch_size):
    total = 0
    # nn.Layer: parameters + buffers
    if hasattr(obj, 'parameters'):
        for p in obj.parameters():
            v = getattr(p, 'value', p)
            total += v.size * _DTYPE_SIZES.get(str(v.dtype), 4)
        return total
    # static Program: recorded vars
    if hasattr(obj, 'list_vars'):
        for v in obj.list_vars():
            shape = [batch_size if (d is None or d < 0) else d
                     for d in getattr(v, 'shape', [])]
            n = int(np.prod(shape)) if shape else 1
            dt = str(getattr(v, 'dtype', 'float32'))
            total += n * _DTYPE_SIZES.get(dt, 4)
        return total
    raise TypeError(
        'memory_usage expects an nn.Layer or a static Program, got '
        f'{type(obj).__name__}')


def memory_usage(program, batch_size=1):
    """Estimated (low, high) memory bytes for training `program` with
    `batch_size` (reference memory_usage: the Program var walk; the
    ±30% band is the reference's own fudge factor).  Pass a jitted
    function's `.lower(...).compile()` object to get XLA's exact
    per-buffer analysis instead, or anything exposing
    ``compiled_text()`` (a ParallelTrainer after its first step) to
    get a liveness high-water estimate from the already-lowered HLO —
    no re-lowering, and free when the persistent compile cache holds
    the step's text."""
    if hasattr(program, 'memory_analysis'):   # compiled XLA exe
        ma = program.memory_analysis()
        exact = (ma.temp_size_in_bytes + ma.argument_size_in_bytes
                 + ma.output_size_in_bytes
                 + ma.generated_code_size_in_bytes)
        return exact, exact
    if hasattr(program, 'compiled_text'):     # e.g. ParallelTrainer:
        # reuse the trainer's (possibly cache-served) lowered step
        # instead of re-lowering from scratch — the liveness walk is
        # analysis.hlo's peak-memory estimate, per device
        from ...analysis import hlo as _hlo
        peak = _hlo.peak_memory(_hlo.parse_module(
            program.compiled_text()))
        return peak, peak
    size = _param_bytes(program, batch_size)
    return size * 0.7, size * 1.3
