"""fluid.contrib.op_frequence — op histogram of a program.

Reference analogue:
/root/reference/python/paddle/fluid/contrib/op_frequence.py
(op_freq_statistic walks Program.blocks counting op types, plus
adjacent op-pair frequencies).

TPU-native: the unit of execution is a jaxpr, not a ProgramDesc — the
count walks either a static Program's recorded op DAG or the jaxpr of
any traceable callable, so it also sees what XLA will actually
compile.  The traversal is paddle_tpu.analysis.walker — the SAME walk
the TPU lint rules use — so op counting and linting share one
recursion over scan/cond/while/pjit sub-jaxprs instead of two ad-hoc
ones (adjacent pairs count within one nesting level, matching the
reference's within-block semantics)."""
from collections import OrderedDict

__all__ = ['op_freq_statistic']


def _count_jaxpr(closed, uni, pair):
    from ...analysis import walker
    last_in = {}        # id(parent jaxpr) -> previous op at that level
    for parent, eqn in walker.walk(closed.jaxpr):
        name = eqn.primitive.name
        uni[name] = uni.get(name, 0) + 1
        prev = last_in.get(id(parent))
        if prev is not None:
            key = f'{prev}->{name}'
            pair[key] = pair.get(key, 0) + 1
        last_in[id(parent)] = name


def op_freq_statistic(program, *example_args):
    """Return (uni_op_freq, adj_2_op_freq) OrderedDicts sorted by
    count desc (the reference's exact return contract).

    `program` may be a static Program (counts its recorded ops) or a
    callable (its jaxpr is traced with `example_args`)."""
    uni, pair = {}, {}
    if hasattr(program, 'ops') or hasattr(program, '_ops'):
        ops = getattr(program, 'ops', None) or getattr(program, '_ops')
        prev = None
        for op in ops:
            name = getattr(op, 'type', None) or getattr(
                op, 'op_name', type(op).__name__)
            uni[name] = uni.get(name, 0) + 1
            if prev is not None:
                key = f'{prev}->{name}'
                pair[key] = pair.get(key, 0) + 1
            prev = name
    elif callable(program):
        from ...analysis import walker
        closed = walker.trace_jaxpr(program, *example_args)
        _count_jaxpr(closed, uni, pair)
    else:
        raise TypeError(
            'op_freq_statistic expects a static Program or a '
            f'callable, got {type(program).__name__}')
    uni_sorted = OrderedDict(
        sorted(uni.items(), key=lambda kv: -kv[1]))
    pair_sorted = OrderedDict(
        sorted(pair.items(), key=lambda kv: -kv[1]))
    return uni_sorted, pair_sorted
