// In-order bounded ring buffer for DataLoader prefetch.
//
// Reference analogue: paddle/fluid/operators/reader/buffered_reader.cc
// (the C++ double-buffered reader feeding GPU streams).  TPU-native
// version: producers (Python worker threads fetching+collating batches)
// copy packed batches into sequence-addressed slots; the single consumer
// pops strictly in order, so batch order is deterministic regardless of
// worker scheduling — ordering lives HERE, not in a Python reorder dict.
//
// pthread mutex + condvars; slots are malloc'd on demand and reused
// (grow-only), so steady-state has zero allocations.  Buffers are
// contiguous and 64-byte aligned — jax.device_put reads them without
// another gather.
//
// Built at import by paddle_tpu/io/native/__init__.py (g++ -O3 -shared).

#include <cstdlib>
#include <cstring>
#include <cstdint>
#include <pthread.h>

namespace {

struct Slot {
    char*   data = nullptr;
    int64_t cap = 0;       // allocated bytes
    int64_t size = 0;      // payload bytes
    bool    full = false;
};

struct Ring {
    Slot*          slots;
    int64_t        capacity;
    int64_t        head;       // next seq to pop
    bool           closed;
    pthread_mutex_t mu;
    pthread_cond_t  can_push;  // a slot freed or closed
    pthread_cond_t  can_pop;   // head slot filled or closed
};

char* ensure_cap(Slot* s, int64_t n) {
    if (s->cap < n) {
        free(s->data);
        int64_t cap = 64;
        while (cap < n) cap <<= 1;
        void* p = nullptr;
        if (posix_memalign(&p, 64, (size_t)cap) != 0) {
            s->data = nullptr;  // freed above: don't leave it dangling
            s->cap = 0;
            return nullptr;
        }
        s->data = (char*)p;
        s->cap = cap;
    }
    return s->data;
}

}  // namespace

extern "C" {

void* rb_create(int64_t capacity) {
    if (capacity < 1) capacity = 1;
    Ring* rb = new Ring();
    rb->slots = new Slot[capacity]();
    rb->capacity = capacity;
    rb->head = 0;
    rb->closed = false;
    pthread_mutex_init(&rb->mu, nullptr);
    pthread_cond_init(&rb->can_push, nullptr);
    pthread_cond_init(&rb->can_pop, nullptr);
    return rb;
}

void rb_destroy(void* h) {
    Ring* rb = (Ring*)h;
    for (int64_t i = 0; i < rb->capacity; i++) free(rb->slots[i].data);
    delete[] rb->slots;
    pthread_mutex_destroy(&rb->mu);
    pthread_cond_destroy(&rb->can_push);
    pthread_cond_destroy(&rb->can_pop);
    delete rb;
}

// Block until slot (seq % capacity) is free AND seq is within the live
// window [head, head+capacity); copy data in.  Returns 0, or -1 if the
// ring was closed (consumer went away).
int rb_push(void* h, int64_t seq, const void* data, int64_t nbytes) {
    Ring* rb = (Ring*)h;
    Slot* s = &rb->slots[seq % rb->capacity];
    pthread_mutex_lock(&rb->mu);
    while (!rb->closed && (s->full || seq >= rb->head + rb->capacity))
        pthread_cond_wait(&rb->can_push, &rb->mu);
    if (rb->closed) {
        pthread_mutex_unlock(&rb->mu);
        return -1;
    }
    if (!ensure_cap(s, nbytes)) {
        pthread_mutex_unlock(&rb->mu);
        return -2;
    }
    memcpy(s->data, data, (size_t)nbytes);
    s->size = nbytes;
    s->full = true;
    if (seq == rb->head) pthread_cond_broadcast(&rb->can_pop);
    pthread_mutex_unlock(&rb->mu);
    return 0;
}

// Block until the next in-order batch is ready; return its byte size.
// Returns -1 if closed with nothing pending.
int64_t rb_wait_next(void* h) {
    Ring* rb = (Ring*)h;
    pthread_mutex_lock(&rb->mu);
    Slot* s = &rb->slots[rb->head % rb->capacity];
    while (!s->full && !rb->closed)
        pthread_cond_wait(&rb->can_pop, &rb->mu);
    int64_t n = s->full ? s->size : -1;
    pthread_mutex_unlock(&rb->mu);
    return n;
}

// Copy the head batch out (call after rb_wait_next), free the slot,
// advance.  Returns payload size or -1.
int64_t rb_pop(void* h, void* out, int64_t max_bytes) {
    Ring* rb = (Ring*)h;
    pthread_mutex_lock(&rb->mu);
    Slot* s = &rb->slots[rb->head % rb->capacity];
    while (!s->full && !rb->closed)
        pthread_cond_wait(&rb->can_pop, &rb->mu);
    if (!s->full) {  // closed + drained
        pthread_mutex_unlock(&rb->mu);
        return -1;
    }
    int64_t n = s->size;
    if (n > max_bytes) {
        pthread_mutex_unlock(&rb->mu);
        return -2;
    }
    memcpy(out, s->data, (size_t)n);
    s->full = false;
    s->size = 0;
    rb->head++;
    pthread_cond_broadcast(&rb->can_push);
    // wake pop waiters in case the next slot is already full
    pthread_cond_broadcast(&rb->can_pop);
    pthread_mutex_unlock(&rb->mu);
    return n;
}

void rb_close(void* h) {
    Ring* rb = (Ring*)h;
    pthread_mutex_lock(&rb->mu);
    rb->closed = true;
    pthread_cond_broadcast(&rb->can_push);
    pthread_cond_broadcast(&rb->can_pop);
    pthread_mutex_unlock(&rb->mu);
}

int64_t rb_head(void* h) {
    Ring* rb = (Ring*)h;
    pthread_mutex_lock(&rb->mu);
    int64_t v = rb->head;
    pthread_mutex_unlock(&rb->mu);
    return v;
}

}  // extern "C"
