"""Shared compile-and-cache for the native components.

One implementation of the hash-tagged .so build (rebuilt when the
source changes, atomic install, per-process temp) used by both the
prefetch ring and the slot reader — fixes to flags/caching land once.
"""
import ctypes
import hashlib
import os
import subprocess

__all__ = ['compile_cached']


def compile_cached(src, prefix, extra_flags=()):
    """g++-compile `src` into a cached .so next to it; returns CDLL.
    Raises on any build failure — callers decide their fallback."""
    here = os.path.dirname(os.path.abspath(src))
    with open(src, 'rb') as f:
        hasher = hashlib.sha256(f.read())
    hasher.update(' '.join(extra_flags).encode())  # flags change → rebuild
    tag = hasher.hexdigest()[:16]
    so = os.path.join(here, f'_{prefix}_{tag}.so')
    if not os.path.exists(so):
        tmp = f'{so}.{os.getpid()}.tmp'  # unique per process: no race
        subprocess.run(
            ['g++', '-O3', '-shared', '-fPIC', '-std=c++17',
             *extra_flags, src, '-o', tmp],
            check=True, capture_output=True)
        os.replace(tmp, so)  # atomic: losers overwrite identical lib
    return ctypes.CDLL(so)
