"""ctypes bindings for the native MultiSlot file parser.

Reference analogue: the C++ reader threads of
paddle/fluid/framework/data_feed.cc feeding the fleet datasets.  Build
follows the ringbuf pattern (hash-cached .so, graceful Python
fallback).  parse_file() returns per-slot numpy columns for a whole
file in one native pass — the fleet datasets slice rows out of them.
"""
import ctypes
import os
import threading

import numpy as np

from .buildlib import compile_cached

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, 'slotreader.cpp')

_lib = None
_lib_err = None
_lock = threading.Lock()

__all__ = ['available', 'parse_file', 'parse_bytes']


def _build():
    lib = compile_cached(_SRC, 'slotreader')
    lib.sr_parse_buf.restype = ctypes.c_void_p
    lib.sr_parse_buf.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_int32),
                                 ctypes.c_int32]
    lib.sr_parse.restype = ctypes.c_void_p
    lib.sr_parse.argtypes = [ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_int64),
                             ctypes.POINTER(ctypes.c_int32),
                             ctypes.c_int32]
    lib.sr_count.restype = ctypes.c_int64
    lib.sr_count.argtypes = [ctypes.c_void_p]
    lib.sr_error.restype = ctypes.c_int64
    lib.sr_error.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_int64]
    lib.sr_read.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                            ctypes.c_void_p]
    lib.sr_free.argtypes = [ctypes.c_void_p]
    return lib


def available():
    global _lib, _lib_err
    if _lib is not None:
        return True
    if _lib_err is not None:
        return False
    with _lock:
        if _lib is None and _lib_err is None:
            try:
                _lib = _build()
            except Exception as e:   # no compiler → Python fallback
                _lib_err = e
    return _lib is not None


def parse_file(path, widths, int_mask):
    """Parse a slot file natively.

    widths: values per slot per line; int_mask: True for int64 slots.
    Returns a list of [n_samples, width] arrays (float32/int64), or
    None when the native parser is unavailable.
    Raises ValueError on malformed files (same contract as the Python
    parser).
    """
    if not available():
        return None
    if not os.path.exists(path):
        # match the Python parser's open() contract
        raise FileNotFoundError(path)
    n = len(widths)
    w = (ctypes.c_int64 * n)(*[int(x) for x in widths])
    m = (ctypes.c_int32 * n)(*[1 if b else 0 for b in int_mask])
    h = _lib.sr_parse(path.encode(), w, m, n)
    return _collect(h, path, widths, int_mask)


def parse_bytes(data, widths, int_mask, origin='<buffer>'):
    """Parse an in-memory chunk of complete lines (the streaming
    bounded-chunk path).  Same return/raise contract as parse_file."""
    if not available():
        return None
    n = len(widths)
    w = (ctypes.c_int64 * n)(*[int(x) for x in widths])
    m = (ctypes.c_int32 * n)(*[1 if b else 0 for b in int_mask])
    h = _lib.sr_parse_buf(data, len(data), w, m, n)
    return _collect(h, origin, widths, int_mask)


def _collect(h, path, widths, int_mask):
    n = len(widths)
    try:
        buf = ctypes.create_string_buffer(512)
        elen = _lib.sr_error(h, buf, 512)
        if elen:
            msg = buf.raw[:elen].decode(errors='replace')
            raise ValueError(f'slotreader: {msg} in {path}')
        count = _lib.sr_count(h)
        cols = []
        for k in range(n):
            dt = np.int64 if int_mask[k] else np.float32
            arr = np.empty((count, int(widths[k])), dt)
            if count:
                _lib.sr_read(h, k, arr.ctypes.data_as(ctypes.c_void_p))
            cols.append(arr)
        return cols
    finally:
        _lib.sr_free(h)
