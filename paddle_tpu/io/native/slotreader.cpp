// Native MultiSlot text parser.
//
// Reference analogue: paddle/fluid/framework/data_feed.cc
// (MultiSlotDataFeed::ParseOneInstance — the C++ reader threads that
// turn slot-format text into feed tensors).  TPU-native runtime keeps
// the same division of labor: Python owns orchestration, this code
// owns the byte crunching.  One call parses a whole file into
// contiguous per-slot columns ([n_samples, width] float32 or int64),
// which Python wraps as numpy arrays zero-copy-ish (one memcpy out).
//
// Format per line: for each slot, `width` whitespace-separated values.
// Build: g++ -O3 -shared -fPIC -std=c++17 slotreader.cpp -o _slotreader.so
#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct SlotCol {
  int64_t width = 0;
  int is_int = 0;
  std::vector<float> f;     // used when !is_int
  std::vector<int64_t> i;   // used when is_int
};

struct Parsed {
  std::vector<SlotCol> slots;
  int64_t n_samples = 0;
  std::string error;
};

}  // namespace

namespace {

Parsed* make_parsed(const int64_t* widths, const int32_t* is_int,
                    int32_t n_slots) {
  auto* p = new Parsed();
  p->slots.resize(n_slots);
  for (int32_t k = 0; k < n_slots; ++k) {
    p->slots[k].width = widths[k];
    p->slots[k].is_int = is_int[k];
  }
  return p;
}

void parse_buffer(Parsed* p, const char* data, size_t len,
                  int32_t n_slots);

}  // namespace

extern "C" {

// Parse `path`; widths[k] values per slot k per line; is_int[k] selects
// the int64 column.  Returns an opaque handle (never null).
void* sr_parse(const char* path, const int64_t* widths,
               const int32_t* is_int, int32_t n_slots) {
  Parsed* p = make_parsed(widths, is_int, n_slots);
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    p->error = std::string("cannot open ") + path;
    return p;
  }
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(sz), '\0');
  size_t got = std::fread(buf.data(), 1, static_cast<size_t>(sz), f);
  std::fclose(f);
  parse_buffer(p, buf.data(), got, n_slots);
  return p;
}

// Parse an in-memory chunk of complete lines (the streaming
// QueueDataset path: bounded chunks, native speed).
void* sr_parse_buf(const char* data, int64_t len, const int64_t* widths,
                   const int32_t* is_int, int32_t n_slots) {
  Parsed* p = make_parsed(widths, is_int, n_slots);
  parse_buffer(p, data, static_cast<size_t>(len), n_slots);
  return p;
}

}  // extern "C"

namespace {

void parse_buffer(Parsed* p, const char* data, size_t len,
                  int32_t n_slots) {
  // LINE-based parse matching the Python fallback's contract exactly:
  // each non-blank line is one sample; a line with too few tokens or a
  // token that is not fully numeric ('3.7' in an int slot) is an
  // ERROR, while extra trailing tokens are dropped (the Python parser
  // slices the first sum(widths) tokens).
  const char* s = data;
  const char* end = s + len;
  int64_t lineno = 0;
  while (s < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(s, '\n', static_cast<size_t>(end - s)));
    const char* line_end = nl ? nl : end;
    ++lineno;
    // blank line?
    const char* q = s;
    while (q < line_end && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    if (q == line_end) {
      s = line_end + 1;
      continue;
    }
    for (int32_t k = 0; k < n_slots && p->error.empty(); ++k) {
      SlotCol& col = p->slots[k];
      for (int64_t v = 0; v < col.width; ++v) {
        while (q < line_end && (*q == ' ' || *q == '\t' || *q == '\r'))
          ++q;
        if (q >= line_end) {
          p->error = "line " + std::to_string(lineno) +
                     ": too few values (slot " + std::to_string(k) +
                     ")";
          return;
        }
        const char* tok_end = q;
        while (tok_end < line_end && *tok_end != ' ' &&
               *tok_end != '\t' && *tok_end != '\r')
          ++tok_end;
        char* next = nullptr;
        if (col.is_int) {
          long long val = std::strtoll(q, &next, 10);
          if (next != tok_end) {
            p->error = "line " + std::to_string(lineno) +
                       ": bad int token '" +
                       std::string(q, tok_end) + "'";
            return;
          }
          col.i.push_back(static_cast<int64_t>(val));
        } else {
          float val = std::strtof(q, &next);
          if (next != tok_end) {
            p->error = "line " + std::to_string(lineno) +
                       ": bad float token '" +
                       std::string(q, tok_end) + "'";
            return;
          }
          col.f.push_back(val);
        }
        q = tok_end;
      }
    }
    p->n_samples += 1;
    s = line_end + 1;
  }
}

}  // namespace

extern "C" {

int64_t sr_count(void* h) { return static_cast<Parsed*>(h)->n_samples; }

int64_t sr_error(void* h, char* out, int64_t cap) {
  const std::string& e = static_cast<Parsed*>(h)->error;
  if (e.empty()) return 0;
  int64_t n = static_cast<int64_t>(e.size());
  if (n > cap) n = cap;
  std::memcpy(out, e.data(), static_cast<size_t>(n));
  return n;
}

// Copy slot k's column ([n_samples, width], row-major) into `out`
// (float32 or int64 per is_int at parse time).
void sr_read(void* h, int32_t k, void* out) {
  SlotCol& col = static_cast<Parsed*>(h)->slots[k];
  if (col.is_int)
    std::memcpy(out, col.i.data(), col.i.size() * sizeof(int64_t));
  else
    std::memcpy(out, col.f.data(), col.f.size() * sizeof(float));
}

void sr_free(void* h) { delete static_cast<Parsed*>(h); }

}  // extern "C"
