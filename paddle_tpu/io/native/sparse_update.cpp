// Native sparse-embedding update for the host parameter-server path.
//
// Reference analogue: the C++ sparse-table optimizers behind
// fleet/runtime/the_one_ps.py (paddle's distributed table
// sgd/adagrad rules run inside the brpc PS server).  Here the "server"
// is the host process (incubate/host_embedding.py); its Python/numpy
// merge (np.unique + np.add.at) dominates push latency at
// Wide&Deep-scale batches, so the merge + rule runs natively:
//
//   1. argsort ids (counting via std::sort over an index array),
//   2. merge duplicate rows' gradients in registers per run,
//   3. apply SGD or Adagrad in place on the table (and accumulator).
//
// Exported with extern "C"; loaded via ctypes (buildlib.compile_cached).
#include <algorithm>
#include <cstdint>
#include <cmath>
#include <vector>

extern "C" {

// ids[n] (already validated in range), grads[n*D] float32.
// opt: 0 = SGD, 1 = Adagrad (accum must be non-null, same shape as
// table).  Returns the number of distinct rows updated.
int64_t sparse_apply(float* table, float* accum, const int64_t* ids,
                     const float* grads, int64_t n, int64_t D,
                     float lr, int opt) {
    if (n <= 0) return 0;
    std::vector<int64_t> order(n);
    for (int64_t i = 0; i < n; ++i) order[i] = i;
    std::sort(order.begin(), order.end(),
              [ids](int64_t a, int64_t b) { return ids[a] < ids[b]; });

    std::vector<float> merged(D);
    int64_t updated = 0;
    int64_t i = 0;
    while (i < n) {
        const int64_t row = ids[order[i]];
        for (int64_t d = 0; d < D; ++d) merged[d] = 0.f;
        while (i < n && ids[order[i]] == row) {
            const float* g = grads + order[i] * D;
            for (int64_t d = 0; d < D; ++d) merged[d] += g[d];
            ++i;
        }
        float* trow = table + row * D;
        if (opt == 1) {
            float* arow = accum + row * D;
            for (int64_t d = 0; d < D; ++d) {
                arow[d] += merged[d] * merged[d];
                trow[d] -= lr * merged[d] /
                           std::sqrt(arow[d] + 1e-10f);
            }
        } else {
            for (int64_t d = 0; d < D; ++d)
                trow[d] -= lr * merged[d];
        }
        ++updated;
    }
    return updated;
}

// Gather rows: out[i] = table[ids[i]] — the pull half of the PS
// round trip (numpy fancy indexing copies through take(); this is a
// straight memcpy per row).
void sparse_gather(const float* table, const int64_t* ids, float* out,
                   int64_t n, int64_t D) {
    for (int64_t i = 0; i < n; ++i) {
        const float* src = table + ids[i] * D;
        float* dst = out + i * D;
        for (int64_t d = 0; d < D; ++d) dst[d] = src[d];
    }
}

}  // extern "C"
