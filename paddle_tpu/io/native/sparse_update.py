"""ctypes binding for the native sparse-table update (sparse_update.cpp).

Used by incubate.HostOffloadEmbedding's host push: merge duplicate ids
+ SGD/Adagrad in one native pass instead of np.unique + np.add.at.
Degrades to the numpy path when no compiler is available.
"""
import ctypes
import threading

import numpy as np

_lib = None
_lib_err = None
_lock = threading.Lock()


def _build():
    import os
    from .buildlib import compile_cached
    here = os.path.dirname(os.path.abspath(__file__))
    lib = compile_cached(os.path.join(here, 'sparse_update.cpp'),
                         'sparse_update')
    lib.sparse_apply.restype = ctypes.c_int64
    lib.sparse_apply.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_float, ctypes.c_int]
    lib.sparse_gather.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int64]
    return lib


def available():
    global _lib, _lib_err
    if _lib is not None:
        return True
    if _lib_err is not None:
        return False
    with _lock:
        if _lib is not None:
            return True
        try:
            _lib = _build()
            return True
        except Exception as e:
            _lib_err = e
            return False


def apply_update(table, accum, ids, grads, lr, optimizer):
    """In-place merged sparse update; True when the native path ran.

    table: [V, D] float32 C-contiguous; accum: same or None;
    ids: [n] int64; grads: [n, D] float32.
    """
    if not available():
        return False
    if table.dtype != np.float32 or not table.flags['C_CONTIGUOUS']:
        return False
    ids = np.ascontiguousarray(ids, np.int64)
    grads = np.ascontiguousarray(grads, np.float32)
    opt = 1 if optimizer == 'adagrad' else 0
    if opt == 1 and (accum is None or accum.dtype != np.float32
                     or not accum.flags['C_CONTIGUOUS']):
        return False
    _lib.sparse_apply(
        table.ctypes.data_as(ctypes.c_void_p),
        accum.ctypes.data_as(ctypes.c_void_p) if accum is not None
        else None,
        ids.ctypes.data_as(ctypes.c_void_p),
        grads.ctypes.data_as(ctypes.c_void_p),
        ids.shape[0], table.shape[1], float(lr), opt)
    return True


def gather(table, ids):
    """-> rows [n, D]; None when the native path is unavailable."""
    if not available() or table.dtype != np.float32 \
            or not table.flags['C_CONTIGUOUS']:
        return None
    ids = np.ascontiguousarray(ids, np.int64)
    out = np.empty((ids.shape[0], table.shape[1]), np.float32)
    _lib.sparse_gather(table.ctypes.data_as(ctypes.c_void_p),
                       ids.ctypes.data_as(ctypes.c_void_p),
                       out.ctypes.data_as(ctypes.c_void_p),
                       ids.shape[0], table.shape[1])
    return out
