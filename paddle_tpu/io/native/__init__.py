"""ctypes bindings + build for the native prefetch ring buffer.

Compiles ringbuf.cpp once per environment (cached .so next to the
source, rebuilt when the source changes); everything degrades to the
pure-Python queue path when no compiler is available.
"""
import ctypes
import os
import pickle
import struct
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, 'ringbuf.cpp')

_lib = None
_lib_err = None
_build_lock = threading.Lock()


def _build():
    from .buildlib import compile_cached
    lib = compile_cached(_SRC, 'ringbuf', extra_flags=('-pthread',))
    lib.rb_create.restype = ctypes.c_void_p
    lib.rb_create.argtypes = [ctypes.c_int64]
    lib.rb_destroy.argtypes = [ctypes.c_void_p]
    lib.rb_push.restype = ctypes.c_int
    lib.rb_push.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                            ctypes.c_char_p, ctypes.c_int64]
    lib.rb_wait_next.restype = ctypes.c_int64
    lib.rb_wait_next.argtypes = [ctypes.c_void_p]
    lib.rb_pop.restype = ctypes.c_int64
    lib.rb_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                           ctypes.c_int64]
    lib.rb_close.argtypes = [ctypes.c_void_p]
    return lib


def available():
    global _lib, _lib_err
    if _lib is not None:
        return True
    if _lib_err is not None:
        return False
    with _build_lock:
        if _lib is not None:
            return True
        try:
            _lib = _build()
            return True
        except Exception as e:  # no g++ / sandboxed build dir
            _lib_err = e
            return False


# -- batch packing -----------------------------------------------------------
# wire format: [kind u8]  kind 0 = arrays, 1 = pickled payload (errors,
# non-array batches).  arrays: [n u32] then per array
# [dtype_len u32][dtype utf8][ndim u32][shape i64*ndim][nbytes i64][data]

def pack_error(exc):
    """Exceptions cross the ring pickled.  The original object is kept
    when it survives a pickle round-trip (so `except FileNotFoundError`
    style handlers behave identically to the threaded path); otherwise a
    RuntimeError wrapper carries type name + traceback."""
    import traceback
    try:
        payload = pickle.dumps(exc, protocol=pickle.HIGHEST_PROTOCOL)
        pickle.loads(payload)  # multi-arg __init__s explode here, not
        return b'\x01' + payload  # in the consumer
    except Exception:
        msg = '{}: {}\n{}'.format(type(exc).__name__, exc,
                                   traceback.format_exc())
        return b'\x01' + pickle.dumps(RuntimeError(msg),
                                       protocol=pickle.HIGHEST_PROTOCOL)


def pack_batch(batch):
    parts = []
    arrays = None
    if isinstance(batch, (list, tuple)) and batch and all(
            isinstance(a, np.ndarray) and a.dtype.kind in 'biufc'
            for a in batch):
        arrays = list(batch)
    if arrays is None:
        payload = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
        return b'\x01' + payload
    parts.append(b'\x00')
    parts.append(struct.pack('<I', len(arrays)))
    for a in arrays:
        a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode()
        parts.append(struct.pack('<I', len(dt)))
        parts.append(dt)
        parts.append(struct.pack('<I', a.ndim))
        parts.append(struct.pack(f'<{a.ndim}q', *a.shape))
        raw = a.tobytes()
        parts.append(struct.pack('<q', len(raw)))
        # pad data to a 64B boundary so unpacked arrays are aligned
        off = sum(len(p) for p in parts)
        pad = (-off) % 64
        parts.append(b'\x00' * pad)
        parts.append(raw)
    return b''.join(parts)


def unpack_batch(buf):
    # buf: bytes or a uint8 numpy view (pop() returns the latter)
    if int(buf[0]) == 1:
        return pickle.loads(bytes(memoryview(buf)[1:]))
    off = 1
    (n,) = struct.unpack_from('<I', buf, off)
    off += 4
    out = []
    for _ in range(n):
        (dl,) = struct.unpack_from('<I', buf, off)
        off += 4
        dt = np.dtype(bytes(memoryview(buf)[off:off + dl]).decode())
        off += dl
        (nd,) = struct.unpack_from('<I', buf, off)
        off += 4
        shape = struct.unpack_from(f'<{nd}q', buf, off)
        off += 8 * nd
        (nb,) = struct.unpack_from('<q', buf, off)
        off += 8
        off += (-off) % 64  # skip alignment padding
        a = np.frombuffer(buf, dtype=dt, count=nb // dt.itemsize,
                          offset=off).reshape(shape)
        off += nb
        out.append(a)
    return out


class NativeRing:
    """In-order bounded ring over the C++ library."""

    def __init__(self, capacity):
        assert available()
        self._h = _lib.rb_create(capacity)
        self._closed = False

    def push(self, seq, payload: bytes):
        r = _lib.rb_push(self._h, seq, payload, len(payload))
        if r == -2:
            raise MemoryError('ring slot allocation failed')
        return r == 0  # False → ring closed

    def pop(self):
        """Next in-order payload as a writable, 64B-aligned uint8 view
        (so the per-array padding from pack_batch yields aligned numpy
        arrays); the slot->buffer memcpy is the only consumer-side copy.
        Returns None when closed+drained."""
        n = _lib.rb_wait_next(self._h)
        if n < 0:
            return None
        n = int(n)
        backing = np.empty(n + 63, dtype=np.uint8)
        start = (-backing.ctypes.data) % 64
        view = backing[start:start + n]
        c_buf = (ctypes.c_char * n).from_buffer(view)
        got = _lib.rb_pop(self._h, c_buf, n)
        if got < 0:
            return None
        return view

    def close(self):
        if not self._closed:
            self._closed = True
            _lib.rb_close(self._h)

    def __del__(self):
        try:
            self.close()
            if self._h:
                _lib.rb_destroy(self._h)
                self._h = None
        except Exception:
            pass
