"""paddle_tpu.io — datasets, samplers, DataLoader.

Reference analogue: /root/reference/python/paddle/io/ (dataset.py,
dataloader/*, sampler.py) whose DataLoader forks C++/Python workers and
pushes LoDTensors over a blocking queue.  TPU-native: the loader is a
host-side prefetch pipeline — a thread pool maps the dataset, a
ring-buffer queue of collated numpy batches keeps the accelerator fed,
and `jax.device_put` happens at dequeue so H2D copy overlaps compute
(double buffering).  TPU input pipelines are host-CPU-bound, not
device-bound, so threads (which release the GIL inside numpy) replace
the reference's process workers for typical decode/augment loads.

Worker-mode boundary (measured, tools/bench_dataloader_workers.py):
threads are the default — numpy-releasing-GIL augments run at sync
speed or better with zero IPC cost.  PIL/Python-heavy transforms hold
the GIL, so threads serialize; `use_process_workers=True` forks child
processes for those (start method `fork` like the reference —
closures allowed, no main-module guard; forkserver/spawn via
`mp_context=` pay a ~2-3 s framework re-import per child and need
picklable datasets).  Processes still cross an IPC queue per batch,
so they win only when spare cores exist and the GIL-bound transform
dominates.  1-core dev box, 96 samples, 4 workers (fork): numpy-heavy
sync 344/s, threads 290/s, process 226/s; PIL-heavy sync 86/s,
threads 77/s, process 67/s — with zero spare cores the worker modes
can only show their overhead (threads ~10%, processes ~25%); on an
n-core host the PIL-heavy pipeline scales with process workers while
threads stay GIL-serialized.
"""
import bisect
import itertools
import queue
import threading
import time

import numpy as np

from ..core.tensor import Tensor

__all__ = ['Dataset', 'IterableDataset', 'TensorDataset', 'ChainDataset',
           'ComposeDataset', 'Subset', 'random_split', 'ConcatDataset',
           'Sampler', 'SequenceSampler', 'RandomSampler', 'BatchSampler',
           'WeightedRandomSampler', 'DistributedBatchSampler', 'DataLoader',
           'default_collate_fn', 'get_worker_info']


# -- datasets ----------------------------------------------------------------

class Dataset:
    """Map-style dataset (reference: io/dataset.py)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {len(t) if isinstance(t, (list, np.ndarray)) else t.shape[0]
                for t in tensors}
        if len(lens) > 1:
            raise ValueError("tensors must share dim 0")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return len(t) if isinstance(t, (list, np.ndarray)) else t.shape[0]


class ComposeDataset(Dataset):
    """Zip several map datasets into one (fields concatenated)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    """Chain iterable datasets back-to-back."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(
            itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1] if self.cumulative_sizes else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds - 1] if ds > 0 else 0
        return self.datasets[ds][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    rng = np.random.RandomState(generator if isinstance(generator, int)
                                else None)
    perm = rng.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


# -- samplers ----------------------------------------------------------------

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState(
            self.generator if isinstance(self.generator, int) else None)
        if self.replacement:
            return iter(rng.randint(0, n, size=self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype='float64')
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(p), size=self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks.

    Reference: io/dataloader/batch_sampler.py::DistributedBatchSampler.
    On TPU the "rank" is a position on the `dp` mesh axis; with a global
    (pmap-free, jit-sharded) input pipeline each host feeds its own
    shard of the global batch.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        from ..distributed import env as dist_env
        self.nranks = (num_replicas if num_replicas is not None
                       else dist_env.get_world_size())
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])  # pad to even shards
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# -- collate / worker info ---------------------------------------------------

def default_collate_fn(batch):
    """Stack a list of samples into batched numpy arrays (stay on host;
    device transfer happens once per batch at dequeue)."""
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s.value) for s in batch])
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(field)) for field in zip(*batch)]
    return list(batch)


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, 'info', None)


def _process_worker(dataset, collate_fn, worker_init_fn, wid,
                    num_workers, task_q, result_q):
    """Process-worker loop (module-level so forkserver/spawn contexts
    can pickle it).  Tasks are (seq, indices); results are (seq,
    packed-payload bytes) — the same wire format the native ring
    carries, so the parent can feed either consumer path."""
    from . import native as _native
    _worker_info.info = _WorkerInfo(wid, num_workers, dataset)
    init_err = None
    try:
        if worker_init_fn is not None:
            worker_init_fn(wid)
    except Exception as e:     # fail every claimed batch, don't hang
        init_err = _native.pack_error(e)
    while True:
        task = task_q.get()
        if task is None:
            # explicit done-handshake: the parent can then tell a
            # cleanly-finished worker from one that exited mid-task
            result_q.put(('__done__', wid))
            return
        seq, indices = task
        if init_err is not None:
            result_q.put((seq, init_err))
            continue
        try:
            payload = _native.pack_batch(
                collate_fn([dataset[i] for i in indices]))
        except Exception as e:
            payload = _native.pack_error(e)
        result_q.put((seq, payload))


# -- DataLoader --------------------------------------------------------------

class _EndOfEpoch:
    pass


class DataLoader:
    """Prefetching loader (reference: io/dataloader/dataloader_iter.py).

    num_workers>0 → a thread pool maps __getitem__+collate concurrently
    and a bounded ring-buffer queue holds ready batches; the main thread
    dequeues host batches and (optionally) returns device Tensors.
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False, to_tensor=True,
                 use_native_loader=True, use_process_workers=False,
                 mp_context=None, device_prefetch=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(2, int(prefetch_factor))
        self.worker_init_fn = worker_init_fn
        self.to_tensor = to_tensor
        # opt-in OS-process workers for PIL/Python-heavy transforms
        # that hold the GIL (threads serialize there; the reference
        # forks workers for the same reason — dataloader_iter.py).
        # Requires picklable dataset/collate_fn/worker_init_fn.
        self.use_process_workers = bool(use_process_workers)
        self.mp_context = mp_context
        self.timeout = float(timeout) if timeout else 0.0
        if self.use_process_workers and (
                self.num_workers == 0
                or isinstance(dataset, IterableDataset)):
            import warnings
            warnings.warn(
                'use_process_workers=True has no effect with '
                'num_workers=0 or an IterableDataset — loading runs '
                'in the main process; set num_workers>0 on a '
                'map-style dataset to fork workers')
        # device_prefetch: double-buffered host->device staging — a
        # background thread jax.device_put's the NEXT batch while the
        # train loop executes the current one, so the H2D copy
        # overlaps compute (the fused K-step loop stages whole chunks
        # the same way — core.scan_loop.ChunkPrefetcher).  Off for
        # num_workers=0: there is no producer thread to overlap with,
        # and the extra queue hop would only add latency.
        self.device_prefetch = bool(device_prefetch)
        if self.device_prefetch and self.num_workers == 0:
            import warnings
            warnings.warn(
                'device_prefetch=True has no effect with '
                'num_workers=0 — batches are produced on the consumer '
                'thread, so there is nothing to overlap; set '
                'num_workers>0 to enable background device staging')
            self.device_prefetch = False
        # native ring serializes batches: arrays travel zero-pickle, but
        # exotic batch objects must be picklable — set False to keep the
        # in-process threaded path for those
        self.use_native_loader = use_native_loader
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        batch = [self.dataset[i] for i in indices]
        return self.collate_fn(batch)

    def _wrap(self, host_batch):
        if not self.to_tensor:
            return host_batch
        def dev(x):
            if isinstance(x, np.ndarray) and x.dtype != object and \
                    x.dtype.kind in 'biufc':
                return Tensor(x)
            return x
        if isinstance(host_batch, dict):
            return {k: dev(v) for k, v in host_batch.items()}
        if isinstance(host_batch, (tuple, list)):
            return [dev(v) for v in host_batch]
        return dev(host_batch)

    # -- iteration paths -----------------------------------------------------
    def _iter_sync(self):
        if self._iterable:
            it = iter(self.dataset)
            if self.batch_size is None:
                for item in it:
                    yield self._wrap(item)
                return
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self._wrap(self.collate_fn(batch))
        elif self.batch_sampler is None:
            # batch_size=None → yield raw samples, no collation
            for i in range(len(self.dataset)):
                yield self._wrap(self.dataset[i])
        else:
            for indices in self.batch_sampler:
                yield self._wrap(self._fetch(indices))

    def _iter_threaded(self):
        out_q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        work_q = queue.Queue()
        for pos, indices in enumerate(self.batch_sampler):
            work_q.put((pos, indices))
        n_batches = work_q.qsize()
        results = {}
        stop = threading.Event()

        def put(item):
            # bounded put that gives up once the consumer abandons the
            # generator — a worker parked forever on a full out_q is an
            # orphan daemon thread
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker(wid):
            try:
                _worker_info.info = _WorkerInfo(wid, self.num_workers,
                                                self.dataset)
                if self.worker_init_fn is not None:
                    self.worker_init_fn(wid)
            except Exception as e:
                # deliver the failure for every batch this worker would
                # have claimed, so the main thread raises instead of
                # deadlocking on out_q.get()
                while True:
                    try:
                        pos, _ = work_q.get_nowait()
                    except queue.Empty:
                        return
                    if not put((pos, e)):
                        return
            while not stop.is_set():
                try:
                    pos, indices = work_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    item = self._fetch(indices)
                except Exception as e:  # surface in main thread
                    item = e
                if not put((pos, item)):
                    return

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            # re-order: batches may finish out of order; emit sequentially
            next_pos = 0
            received = 0
            while next_pos < n_batches:
                if next_pos in results:
                    item = results.pop(next_pos)
                else:
                    pos, item = out_q.get()
                    received += 1
                    if pos != next_pos:
                        results[pos] = item
                        continue
                if isinstance(item, Exception):
                    raise item
                yield self._wrap(item)
                next_pos += 1
        finally:
            stop.set()
            # workers poll `stop` on every queue op, so they exit
            # within one 0.1s tick; the timeout only guards a
            # __getitem__ wedged mid-fetch
            for t in threads:
                t.join(timeout=2.0)

    def _iter_native(self):
        """Workers pack collated batches into the C++ in-order ring
        (paddle_tpu.io.native); the ring enforces sequencing and
        backpressure in native code — no Python-side reorder dict.
        Payloads come back as contiguous 64B-aligned buffers, which
        jax.device_put consumes without re-gathering."""
        from . import native as _native
        indices_list = list(self.batch_sampler)
        n_batches = len(indices_list)
        ring = _native.NativeRing(self.num_workers * self.prefetch_factor)
        next_seq = [0]
        seq_lock = threading.Lock()

        def worker(wid):
            try:
                _worker_info.info = _WorkerInfo(wid, self.num_workers,
                                                self.dataset)
                if self.worker_init_fn is not None:
                    self.worker_init_fn(wid)
            except Exception as e:
                payload = _native.pack_error(e)
                while True:
                    with seq_lock:
                        if next_seq[0] >= n_batches:
                            return
                        seq = next_seq[0]
                        next_seq[0] += 1
                    if not ring.push(seq, payload):
                        return
            while True:
                with seq_lock:
                    if next_seq[0] >= n_batches:
                        return
                    seq = next_seq[0]
                    next_seq[0] += 1
                try:
                    payload = _native.pack_batch(
                        self._fetch(indices_list[seq]))
                except Exception as e:
                    payload = _native.pack_error(e)
                try:
                    if not ring.push(seq, payload):
                        return
                except Exception:
                    # a claimed-but-unfilled seq would hang the consumer
                    # forever; closing the ring surfaces the failure
                    ring.close()
                    raise
        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            yield from self._consume_ring(ring, n_batches)
        finally:
            # close() makes every blocked ring.push return False, so
            # the workers fall out of their claim loops — then a
            # bounded join reaps them (no orphan daemon threads)
            ring.close()
            for t in threads:
                t.join(timeout=2.0)

    def _consume_ring(self, ring, n_batches, pending_error=None):
        """Shared consumer side of the in-order native ring: pop,
        unpack, surface worker exceptions, wrap.  `pending_error` is a
        one-slot list a producer thread fills before closing the ring
        early (a silent short epoch would corrupt training)."""
        from . import native as _native
        for i in range(n_batches):
            payload = ring.pop()
            if payload is None:
                if pending_error:
                    raise pending_error[0]
                raise RuntimeError(
                    f'native loader ring closed after {i}/'
                    f'{n_batches} batches (worker failure)')
            item = _native.unpack_batch(payload)
            if isinstance(item, Exception):
                raise item
            yield self._wrap(item)

    def _iter_process(self):
        """Opt-in OS-process workers (`use_process_workers=True`):
        child processes run __getitem__ + collate in parallel — the
        escape hatch for PIL/Python-heavy transforms where threads
        serialize on the GIL (reference
        io/dataloader/dataloader_iter.py forks workers for the same
        reason; see tools/bench_dataloader_workers.py for the measured
        thread-vs-process crossover).  Start method: `fork` where the
        platform has it (like the reference — no main-module guard
        needed, closures allowed, no per-child re-import; safe here
        because children never touch the accelerator), else
        forkserver/spawn, which require picklable dataset/collate_fn
        and an `if __name__ == '__main__'` guard in user scripts —
        override via `mp_context=`.  Children return packed payloads
        (the native ring wire format) over a bounded mp queue; the
        parent re-sequences and, when the C++ ring is built, feeds it
        so the consumer side is the same aligned zero-copy pop as the
        threaded native path.  Workers live per-epoch
        (persistent_workers is accepted but not persisted)."""
        import multiprocessing as mp
        if self.mp_context:
            ctx = mp.get_context(self.mp_context)
        else:
            methods = mp.get_all_start_methods()
            ctx = mp.get_context(
                'fork' if 'fork' in methods else
                'forkserver' if 'forkserver' in methods else 'spawn')
        from . import native as _native
        indices_list = list(self.batch_sampler)
        n_batches = len(indices_list)
        window = max(2, self.num_workers * self.prefetch_factor)
        task_q = ctx.Queue()
        result_q = ctx.Queue(maxsize=window)
        # windowed dispatch anchored at the CONSUMER cursor: only seqs
        # < want + window are ever dispatched, so one straggler worker
        # cannot make the parent stash more than `window` payloads
        # (dispatching per-result instead would bound dispatched-minus-
        # received but let the stash grow to the whole epoch)
        state = {'next_task': 0, 'received': 0, 'sentinels': False}

        def dispatch_upto(want):
            while state['next_task'] < min(n_batches, want + window):
                seq = state['next_task']
                task_q.put((seq, list(indices_list[seq])))
                state['next_task'] = seq + 1
            if state['next_task'] == n_batches \
                    and not state['sentinels']:
                for _ in range(self.num_workers):
                    task_q.put(None)
                state['sentinels'] = True

        dispatch_upto(0)
        procs = [ctx.Process(
            target=_process_worker,
            args=(self.dataset, self.collate_fn, self.worker_init_fn,
                  w, self.num_workers, task_q, result_q), daemon=True)
            for w in range(self.num_workers)]
        try:
            for p in procs:
                p.start()
        except Exception as e:
            raise RuntimeError(
                'process workers could not start — under '
                f'{ctx.get_start_method()!r} the dataset/collate_fn/'
                'worker_init_fn must be picklable and user scripts '
                "need an `if __name__ == '__main__'` guard; use "
                'threads (use_process_workers=False) for closures, or '
                "mp_context='fork' where available") from e

        poll_s = self.timeout or 5.0
        stash = {}
        done_wids = set()

        def ordered_payloads():
            """Yield payloads in seq order; a dead child must raise,
            not hang the epoch.  A worker is 'dead' when its process
            exited without the done-handshake — exit code 0 from a
            dataset calling sys.exit(0) mid-task counts; a slow batch
            on a live worker does not."""
            import queue as _queue
            for want in range(n_batches):
                dispatch_upto(want)
                stalled_polls = 0
                while want not in stash:
                    try:
                        seq, payload = result_q.get(timeout=poll_s)
                    except _queue.Empty:
                        died = [(i, p.exitcode)
                                for i, p in enumerate(procs)
                                if p.exitcode is not None
                                and i not in done_wids]
                        if died:
                            raise RuntimeError(
                                f'process worker {died[0][0]} died '
                                f'(exitcode {died[0][1]}) after '
                                f"{state['received']}/{n_batches} "
                                'batches') from None
                        if self.timeout:
                            raise RuntimeError(
                                f'DataLoader timed out after '
                                f'{self.timeout}s waiting for batch '
                                f'{want}') from None
                        stalled_polls += 1
                        if stalled_polls % 12 == 0:   # ~once a minute
                            # children are alive but silent: a genuine
                            # slow sample, OR a fork-inherited-lock
                            # deadlock (forking a threaded jax parent)
                            # — surface the escape hatches instead of
                            # hanging mutely forever
                            import warnings
                            waited = stalled_polls * poll_s
                            warnings.warn(
                                f'DataLoader batch {want} has produced '
                                f'no data for {waited:.0f}s with '
                                'workers alive; if this is not a slow '
                                "sample, try mp_context='forkserver' "
                                '(fork can deadlock on locks inherited '
                                'from a threaded parent) or set '
                                'timeout= to fail fast')
                        continue
                    if seq == '__done__':
                        done_wids.add(payload)
                        continue
                    stash[seq] = payload
                    state['received'] += 1
                yield want, stash.pop(want)

        use_ring = self.use_native_loader and _native.available()
        try:
            if use_ring:
                ring = _native.NativeRing(window)
                drain_err = []

                def drain():
                    try:
                        for seq, payload in ordered_payloads():
                            if not ring.push(seq, payload):
                                return     # consumer closed the ring
                    except BaseException as e:
                        drain_err.append(e)
                        ring.close()

                t = threading.Thread(target=drain, daemon=True)
                t.start()
                try:
                    yield from self._consume_ring(ring, n_batches,
                                                  drain_err)
                finally:
                    # close() unblocks a drain parked on ring.push (it
                    # returns False), so the bounded join reaps it
                    ring.close()
                    t.join(timeout=2.0)
            else:
                for _, payload in ordered_payloads():
                    # bytearray copy: frombuffer over the queue's bytes
                    # would yield READ-ONLY arrays, unlike every other
                    # loader path
                    item = _native.unpack_batch(
                        np.frombuffer(bytearray(payload), np.uint8))
                    if isinstance(item, Exception):
                        raise item
                    yield self._wrap(item)
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=2)

    @staticmethod
    def _device_put_batch(item):
        """Stage one (possibly wrapped) batch onto device: numpy
        leaves become committed device arrays; Tensors re-wrap their
        transferred value; non-array leaves pass through."""
        import jax

        def dev(x):
            if isinstance(x, Tensor):
                return Tensor._from_value(jax.device_put(x.value))
            if isinstance(x, np.ndarray) and x.dtype != object and \
                    x.dtype.kind in 'biufc':
                return jax.device_put(x)
            return x
        if isinstance(item, dict):
            return {k: dev(v) for k, v in item.items()}
        if isinstance(item, (tuple, list)):
            return [dev(v) for v in item]
        return dev(item)

    def _iter_device_prefetch(self, inner):
        """Double-buffered device staging: a daemon thread pulls from
        the worker pipeline, ``jax.device_put``s each batch, and parks
        up to two staged batches in a bounded queue.  The dequeue wait
        is the OVERLAP gauge: ~0 ms means the transfer fully hid
        behind compute; a persistent positive value means the loader
        (or the H2D link) is the bottleneck."""
        from .. import telemetry
        out_q = queue.Queue(maxsize=2)
        err = []
        closed = []             # consumer-gone flag (one-slot list)
        _SENTINEL = _EndOfEpoch

        def put(item):
            while not closed:
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for item in inner:
                    if not put(self._device_put_batch(item)):
                        return
            except BaseException as e:
                err.append(e)
            finally:
                put(_SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        _perf = time.perf_counter
        try:
            while True:
                t0 = _perf()
                item = out_q.get()
                dt = _perf() - t0
                telemetry.add('io.device_prefetch.wait_s', dt)
                telemetry.set_gauge('io.device_prefetch.last_wait_ms',
                                    round(dt * 1000.0, 4))
                if item is _SENTINEL:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # an abandoned iterator (early stop, preemption, raised
            # callback) must release the producer parked on the full
            # queue — otherwise each broken-off epoch leaks a thread
            # plus two device-staged batches for the process lifetime
            closed.append(True)
            try:
                while True:
                    out_q.get_nowait()
            except queue.Empty:
                pass
            # producer's put-poll re-checks `closed` every 0.1s; the
            # timeout only guards a device_put wedged mid-transfer
            t.join(timeout=2.0)

    def _telemetry_iter(self, inner):
        """Time each dequeue — the HOST-WAIT gauge: how long the
        training loop blocked on this loader per batch (for the
        threaded/native paths that is queue-pop time, i.e. true
        starvation; for the sync path it is fetch+collate).  Pure
        perf_counter deltas on the host — never touches the device."""
        from .. import telemetry
        _perf = time.perf_counter
        while True:
            t0 = _perf()
            try:
                item = next(inner)
            except StopIteration:
                return
            dt = _perf() - t0
            telemetry.add('io.dataloader.wait_s', dt)
            telemetry.add('io.dataloader.batches', 1)
            telemetry.set_gauge('io.dataloader.last_wait_ms',
                                round(dt * 1000.0, 4))
            yield item

    def __iter__(self):
        if self.num_workers > 0 and not self._iterable \
                and self.batch_sampler is not None:
            if self.use_process_workers:
                it = self._iter_process()
            else:
                from . import native as _native
                if self.use_native_loader and _native.available():
                    it = self._iter_native()
                else:
                    it = self._iter_threaded()
        else:
            it = self._iter_sync()
        if self.device_prefetch and self.num_workers > 0 \
                and not self._iterable and self.batch_sampler is not None:
            it = self._iter_device_prefetch(it)
        from ..telemetry import active as _telemetry_active
        if _telemetry_active():
            return self._telemetry_iter(it)
        return it
