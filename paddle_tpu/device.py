"""paddle.device namespace.

Reference analogue: /root/reference/python/paddle/device.py (set_device,
get_device, XPUPlace, is_compiled_with_*).  The implementations live in
core/device.py (TPU is the native accelerator; cuda/xpu/npu report not
compiled); this module is the public namespace the reference exposes as
`paddle.device`.
"""
from .core.device import (  # noqa: F401
    set_device, get_device, XPUPlace, is_compiled_with_xpu,
    is_compiled_with_npu, is_compiled_with_cuda, get_cudnn_version,
    device_count, CPUPlace, CUDAPlace, TPUPlace, NPUPlace,
    CUDAPinnedPlace)

__all__ = ['get_cudnn_version', 'XPUPlace', 'is_compiled_with_xpu',
           'is_compiled_with_cuda', 'is_compiled_with_npu',
           'get_device', 'set_device']
