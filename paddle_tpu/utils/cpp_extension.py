"""paddle.utils.cpp_extension — build/load native extensions.

Reference analogue:
/root/reference/python/paddle/utils/cpp_extension/cpp_extension.py
(setup/CppExtension/CUDAExtension building pybind11 custom-op modules).

TPU-native: the compute path is XLA — custom device kernels are Pallas,
not C++.  What native code still buys is HOST-side speed (parsers,
ring buffers, schedulers — see io/native/), so `load()` here compiles
C++ sources into a shared library with the system toolchain and hands
back a ctypes.CDLL (the same mechanism io/native uses).  pybind11 isn't
in this image; exported functions use extern "C".
"""
import os
import subprocess
import tempfile

__all__ = ['CppExtension', 'CUDAExtension', 'load', 'setup',
           'get_build_directory']


def get_build_directory(verbose=False):
    """Root directory for JIT-built extensions (reference
    cpp_extension/extension_utils.py:741); override with
    PADDLE_EXTENSION_DIR."""
    root = os.environ.get('PADDLE_EXTENSION_DIR') or os.path.join(
        tempfile.gettempdir(), 'paddle_tpu_extensions')
    if verbose:
        print(f'paddle_tpu extensions build directory: {root}')
    return root


def CppExtension(sources, *args, **kwargs):
    """Describe a host C++ extension (reference cpp_extension.py
    CppExtension); consumed by load()/setup()."""
    return {'sources': list(sources), 'kind': 'cpp', **kwargs}


def CUDAExtension(sources, *args, **kwargs):
    raise RuntimeError(
        'CUDAExtension: no CUDA in the TPU-native build. Device kernels '
        'are Pallas (paddle_tpu.ops); host-side native code uses '
        'CppExtension/load.')


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False, **kwargs):
    """Compile `sources` to <name>.so and return a ctypes.CDLL
    (reference cpp_extension.py::load builds+imports a pybind module;
    here: extern \"C\" symbols over ctypes — zero non-baked deps)."""
    import ctypes
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f'{name}.so')
    srcs = [os.path.abspath(s) for s in sources]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(out) or os.path.getmtime(out) < newest_src:
        cmd = ['g++', '-O2', '-shared', '-fPIC', '-std=c++17',
               *(extra_cxx_cflags or []), *srcs, '-o', out]
        if verbose:
            print(' '.join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f'cpp_extension build failed:\n{proc.stderr[-2000:]}')
    return ctypes.CDLL(out)


def setup(**kwargs):
    """The reference's setuptools entry point for shipping custom-op
    wheels; out of scope for the in-process build — use load()."""
    raise NotImplementedError(
        'cpp_extension.setup: package with your own setup.py; for '
        'in-process native code use paddle_tpu.utils.cpp_extension.load')
