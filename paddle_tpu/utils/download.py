"""paddle.utils.download — weight-file cache resolution.

Reference analogue: /root/reference/python/paddle/utils/download.py
(get_weights_path_from_url downloads to ~/.cache/paddle/hapi/weights).
Zero-egress build: resolves against the local cache and raises with the
expected path when absent (the vision/text model zoos initialize
randomly instead of fetching pretrained weights).
"""
import os

__all__ = ['get_weights_path_from_url']

WEIGHTS_HOME = os.path.expanduser('~/.cache/paddle/hapi/weights')


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    fname = url.split('/')[-1]
    path = os.path.join(root_dir, fname)
    if os.path.exists(path):
        return path
    raise RuntimeError(
        f'{fname} not in local cache ({root_dir}) and this build has no '
        f'egress to fetch {url}; place the file there manually')


def get_weights_path_from_url(url, md5sum=None):
    """-> local path of the cached weight file (reference
    download.py::get_weights_path_from_url)."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
