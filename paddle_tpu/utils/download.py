"""paddle.utils.download — weight-file cache resolution.

Reference analogue: /root/reference/python/paddle/utils/download.py
(get_weights_path_from_url downloads to ~/.cache/paddle/hapi/weights,
with an ad-hoc DOWNLOAD_RETRY_LIMIT loop).  Zero-egress build:
resolves against the local cache and raises with the expected path
when absent (the vision/text model zoos initialize randomly instead of
fetching pretrained weights).

Robustness: the cache typically lives on a shared filesystem on TPU
pods, where reads flake and concurrent writers leave half-copied
files.  Resolution therefore verifies the md5 when one is given and
retries through resilience.retry — the shared policy that replaced
the reference's hand-rolled loop.
"""
import os

from ..resilience import file_checksum, retry

__all__ = ['get_weights_path_from_url']

WEIGHTS_HOME = os.path.expanduser('~/.cache/paddle/hapi/weights')


class _CorruptCacheFile(OSError):
    """md5 mismatch — retriable: a concurrent fetcher may still be
    writing the file; stable corruption exhausts the retries and
    surfaces as the final error."""


@retry(retries=3, backoff=0.2, retry_on=(OSError,))
def _verify(path, md5sum):
    """Retried: the shared-fs read can flake, and a mismatch may be a
    concurrent fetcher still writing — both settle on retry; stable
    corruption exhausts the attempts."""
    got = file_checksum(path, 'md5')
    if got != md5sum:
        raise _CorruptCacheFile(
            f'{path}: md5 {got} != expected {md5sum}')
    return path


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    fname = url.split('/')[-1]
    path = os.path.join(root_dir, fname)
    # a missing file is NOT retried — zero egress means it cannot
    # appear on its own, and the model zoos probe this path on every
    # cold init (a backoff loop here would tax every random-init)
    if not os.path.isfile(path):
        raise RuntimeError(
            f'{fname} not in local cache ({root_dir}) and this build '
            f'has no egress to fetch {url}; place the file there '
            'manually')
    if not md5sum:
        return path
    try:
        return _verify(path, md5sum)
    except _CorruptCacheFile as e:
        raise RuntimeError(
            f'{fname} in local cache ({root_dir}) is corrupt ({e}); '
            'delete it and place a good copy — this build has no '
            f'egress to re-fetch {url}') from e


def get_weights_path_from_url(url, md5sum=None):
    """-> local path of the cached weight file (reference
    download.py::get_weights_path_from_url)."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
