"""paddle.utils.unique_name — prefix-numbered name generation.

Reference analogue: /root/reference/python/paddle/fluid/unique_name.py
(UniqueNameGenerator:25, generate:84, guard:160, switch:134) — there it
names ProgramDesc vars; here it names parameters/ops in the lazy DAG
and anywhere user code expects `fc_0, fc_1, ...` numbering.
"""
import contextlib

__all__ = ['generate', 'switch', 'guard']


class UniqueNameGenerator:
    """Numbered names per prefix: generate('fc') -> fc_0, fc_1, ..."""

    def __init__(self, prefix=''):
        self.prefix = prefix
        self.ids = {}

    def __call__(self, key):
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return '_'.join([self.prefix, key, str(n)]) if self.prefix \
            else '_'.join([key, str(n)])


generator = UniqueNameGenerator()


def generate(key):
    """-> '<key>_<i>' with i counting per key (reference
    unique_name.py:84)."""
    return generator(key)


def switch(new_generator=None):
    """Replace the global generator; returns the old one (reference
    unique_name.py:134)."""
    global generator
    old = generator
    generator = new_generator if new_generator is not None \
        else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope a fresh (or given) generator; restores on exit (reference
    unique_name.py:160).  A string/bytes argument becomes the prefix."""
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    elif isinstance(new_generator, bytes):
        new_generator = UniqueNameGenerator(new_generator.decode())
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
