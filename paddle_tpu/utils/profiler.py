"""`paddle.utils.profiler` (reference: python/paddle/utils/profiler.py).

Thin option-driven wrapper over `paddle_tpu.profiler` (jax.profiler
traces + host step timers).  The reference's option keys are preserved;
keys that only make sense for the legacy op-table profiler
(sorted_key, op_summary_path, ...) are accepted and carried but the
trace output is an XProf logdir, not a text op table.
"""
import sys
import warnings

from ..profiler import (start_profiler, stop_profiler, profiler,
                        reset_profiler, cuda_profiler)
# the one step timer (telemetry-backed; paddle_tpu.profiler re-exports
# the same class — the old per-module duplicates are gone)
from ..telemetry import StepTimer  # noqa: F401

__all__ = ['Profiler', 'get_profiler', 'ProfilerOptions', 'cuda_profiler',
           'start_profiler', 'profiler', 'stop_profiler', 'reset_profiler',
           'StepTimer']


class ProfilerOptions:
    """Option bag with the reference's keys and 'none'→None reads
    (reference utils/profiler.py:39)."""

    def __init__(self, options=None):
        self.options = {
            'state': 'All',
            'sorted_key': 'default',
            'tracer_level': 'Default',
            'batch_range': [0, sys.maxsize],
            'output_thread_detail': False,
            'profile_path': 'none',
            'timeline_path': 'none',
            'op_summary_path': 'none',
        }
        if options is not None:
            for key in self.options:
                if options.get(key, None) is not None:
                    self.options[key] = options[key]

    def with_state(self, state):
        self.options['state'] = state
        return self

    def __getitem__(self, name):
        if self.options.get(name, None) is None:
            raise ValueError(
                f'ProfilerOptions does not have an option named {name}.')
        v = self.options[name]
        return None if (isinstance(v, str) and v == 'none') else v


_current_profiler = None


class Profiler:
    """Batch-range-aware profiling context (reference utils/profiler.py:76).

    `add_step` drives the batch counter; tracing starts/stops when the
    counter crosses options['batch_range'].
    """

    def __init__(self, enabled=True, options=None):
        self.profiler_options = options if options is not None \
            else ProfilerOptions()
        self.batch_id = 0
        self.enabled = enabled
        self._tracing = False

    def __enter__(self):
        global _current_profiler
        self.previous_profiler = _current_profiler
        _current_profiler = self
        if self.enabled and self.profiler_options['batch_range'][0] == 0:
            self.start()
        return self

    def __exit__(self, *exc):
        global _current_profiler
        _current_profiler = self.previous_profiler
        if self.enabled:
            self.stop()

    def start(self):
        if self.enabled and not self._tracing:
            try:
                start_profiler(
                    state=self.profiler_options['state'],
                    tracer_option=self.profiler_options['tracer_level'])
                self._tracing = True
            except Exception as e:
                warnings.warn('Profiler is not enabled because following '
                              f'exception:\n{e}')

    def stop(self):
        if self.enabled and self._tracing:
            try:
                stop_profiler(
                    sorted_key=self.profiler_options['sorted_key'],
                    profile_path=self.profiler_options['profile_path'])
                self._tracing = False
            except Exception as e:
                warnings.warn('Profiler is not disabled because following '
                              f'exception:\n{e}')

    def reset(self):
        if self.enabled and self._tracing:
            reset_profiler()

    def record_step(self, change_profiler_status=True):
        if not self.enabled:
            return
        self.batch_id += 1
        if not change_profiler_status:
            return
        lo, hi = self.profiler_options['batch_range']
        if self.batch_id == lo:
            self.start() if not self._tracing else self.reset()
        elif self.batch_id == hi:
            self.stop()


def get_profiler():
    """The innermost active Profiler, creating a disabled default when
    none is live (reference utils/profiler.py:144)."""
    global _current_profiler
    if _current_profiler is None:
        _current_profiler = Profiler(enabled=False)
    return _current_profiler
