"""Training-robustness utilities (SURVEY.md §2 item 39; reference:
fleet launch_utils watchdogs + debug tooling).

- NaN/Inf detection: `debug_nans` (XLA-level trap) and `check_numerics`
  (explicit guard for compiled steps).
- Watchdog: wall-clock heartbeat monitor for hung steps (a stuck ICI
  collective or input pipeline shows up as a missed heartbeat).
- try_load_latest / save_step: step-level checkpoint/resume helpers used
  with paddle_tpu.save/load for elastic restarts.
"""
import os
import threading
import time
import warnings

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['debug_nans', 'check_numerics', 'Watchdog', 'save_step',
           'try_load_latest']


def debug_nans(enable=True):
    """XLA-level NaN trap: any op producing NaN raises immediately
    (reference analogue: FLAGS_check_nan_inf)."""
    jax.config.update('jax_debug_nans', bool(enable))


def check_numerics(tree, name='tensors'):
    """Host-side finite check over a pytree of arrays; raises
    FloatingPointError naming the first offending leaf."""
    from ..core.tensor import Tensor
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(
        jax.tree_util.tree_map(
            lambda v: v.value if isinstance(v, Tensor) else v, tree))[0]
    for path, leaf in leaves_with_paths:
        arr = np.asarray(leaf)
        if arr.dtype.kind in 'fc' and not np.isfinite(arr).all():
            where = '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                             for k in path)
            raise FloatingPointError(
                f'non-finite values in {name}[{where}]')
    return True


class Watchdog:
    """Fires `on_stall` if `beat()` is not called within `timeout_s`.

    Use around training loops: a hung collective, a wedged input
    pipeline or a dead worker surfaces as a stall instead of silence.
    """

    def __init__(self, timeout_s=300.0, on_stall=None, name='train'):
        self.timeout_s = timeout_s
        self.on_stall = on_stall
        self.name = name
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = None
        self.stalled = False

    def _run(self):
        while not self._stop.wait(min(self.timeout_s / 4, 10.0)):
            if time.monotonic() - self._last > self.timeout_s:
                self.stalled = True
                msg = (f'watchdog[{self.name}]: no heartbeat for '
                       f'{self.timeout_s:.0f}s')
                if self.on_stall is not None:
                    self.on_stall(msg)
                else:
                    warnings.warn(msg)
                self._last = time.monotonic()  # don't spam

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def beat(self):
        self._last = time.monotonic()
        self.stalled = False

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def save_step(state_dict, directory, step, keep=3, prefix='ckpt'):
    """Write `<dir>/<prefix>_<step>.pdparams` and prune old ones."""
    from ..framework.io import save
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f'{prefix}_{step}.pdparams')
    save(state_dict, path)
    # prune (ignore non-numeric suffixes: foreign files in the dir)
    ckpts = sorted(
        (f for f in os.listdir(directory)
         if f.startswith(prefix + '_') and f.endswith('.pdparams')
         and f[len(prefix) + 1:-len('.pdparams')].isdigit()),
        key=lambda f: int(f[len(prefix) + 1:-len('.pdparams')]))
    for old in ckpts[:-keep]:
        try:
            os.remove(os.path.join(directory, old))
        except OSError:
            pass
    return path


def try_load_latest(directory, prefix='ckpt'):
    """Return (state_dict, step) for the newest checkpoint, or
    (None, -1) when none exists — elastic-restart entry point."""
    from ..framework.io import load
    if not os.path.isdir(directory):
        return None, -1
    ckpts = sorted(
        (f for f in os.listdir(directory)
         if f.startswith(prefix + '_') and f.endswith('.pdparams')
         and f[len(prefix) + 1:-len('.pdparams')].isdigit()),
        key=lambda f: int(f[len(prefix) + 1:-len('.pdparams')]))
    if not ckpts:
        return None, -1
    newest = ckpts[-1]
    step = int(newest[len(prefix) + 1:-len('.pdparams')])
    return load(os.path.join(directory, newest)), step


# -- reference paddle.utils surface ------------------------------------------

def deprecated(update_to='', since='', reason='', level=0):
    """Decorator marking an API deprecated (reference
    utils/deprecated.py): appends a note to the docstring and warns on
    call.  Levels match the reference: 0/1 warn, 2 raises."""
    import functools
    import warnings

    def wrap(fn):
        msg = f'API "{fn.__module__}.{fn.__name__}" is deprecated'
        if since:
            msg += f' since {since}'
        if update_to:
            msg += f', use "{update_to}" instead'
        if reason:
            msg += f'; reason: {reason}'
        fn.__doc__ = (fn.__doc__ or '') + f'\n\n    .. warning:: {msg}\n'

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return inner
    return wrap


def run_check():
    """Installation self-check (reference utils/install_check.py):
    run a tiny compiled train step on the default device and report."""
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    x = jnp.ones((4, 8))
    w = jnp.ones((8, 2)) * 0.1

    @jax.jit
    def step(w):
        loss = ((x @ w) ** 2).mean()
        return loss, jax.grad(lambda w: ((x @ w) ** 2).mean())(w)

    loss, g = step(w)
    jax.block_until_ready(g)
    assert bool(jnp.isfinite(loss)), 'non-finite loss in run_check'
    print(f'paddle_tpu is installed successfully! '
          f'(compiled a train step on {dev.platform}:{dev.id})')


def require_version(min_version, max_version=None):
    """Raise unless min_version <= __version__ (<= max_version)
    (reference utils/__init__.py::require_version)."""
    from .. import __version__

    def key(v):
        return [int(p) for p in str(v).replace('-', '.').split('.')
                if p.isdigit()]
    cur = key(__version__)
    if key(min_version) > cur:
        raise Exception(
            f'paddle_tpu>={min_version} required, found {__version__}')
    if max_version is not None and key(max_version) < cur:
        raise Exception(
            f'paddle_tpu<={max_version} required, found {__version__}')


def try_import(module_name, err_msg=None):
    """Import a soft dependency with an actionable error (reference
    utils/lazy_import.py::try_import)."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"Failed to import '{module_name}'; this "
            f"environment is zero-egress, so only baked-in packages "
            f"are importable") from e


from . import unique_name  # noqa: E402,F401
from . import download  # noqa: E402,F401
from . import cpp_extension  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from ..dataset import image as image_util  # noqa: E402,F401
from ..profiler import Profiler  # noqa: E402,F401

__all__ += ['deprecated', 'run_check', 'require_version', 'try_import',
            'unique_name', 'download', 'cpp_extension', 'profiler',
            'image_util', 'Profiler']
