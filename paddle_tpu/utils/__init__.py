"""Training-robustness utilities (SURVEY.md §2 item 39; reference:
fleet launch_utils watchdogs + debug tooling).

- NaN/Inf detection: `debug_nans` (XLA-level trap) and `check_numerics`
  (explicit guard for compiled steps).
- Watchdog: wall-clock heartbeat monitor for hung steps (a stuck ICI
  collective or input pipeline shows up as a missed heartbeat).
- try_load_latest / save_step: step-level checkpoint/resume helpers used
  with paddle_tpu.save/load for elastic restarts.
"""
import os
import threading
import time
import warnings

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['debug_nans', 'check_numerics', 'Watchdog', 'save_step',
           'try_load_latest']


def debug_nans(enable=True):
    """XLA-level NaN trap: any op producing NaN raises immediately
    (reference analogue: FLAGS_check_nan_inf)."""
    jax.config.update('jax_debug_nans', bool(enable))


def check_numerics(tree, name='tensors'):
    """Host-side finite check over a pytree of arrays; raises
    FloatingPointError naming the first offending leaf."""
    from ..core.tensor import Tensor
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(
        jax.tree_util.tree_map(
            lambda v: v.value if isinstance(v, Tensor) else v, tree))[0]
    for path, leaf in leaves_with_paths:
        arr = np.asarray(leaf)
        if arr.dtype.kind in 'fc' and not np.isfinite(arr).all():
            where = '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                             for k in path)
            raise FloatingPointError(
                f'non-finite values in {name}[{where}]')
    return True


class Watchdog:
    """Fires `on_stall` if `beat()` is not called within `timeout_s`.

    Use around training loops: a hung collective, a wedged input
    pipeline or a dead worker surfaces as a stall instead of silence.
    """

    def __init__(self, timeout_s=300.0, on_stall=None, name='train'):
        self.timeout_s = timeout_s
        self.on_stall = on_stall
        self.name = name
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = None
        self.stalled = False

    def _run(self):
        while not self._stop.wait(min(self.timeout_s / 4, 10.0)):
            if time.monotonic() - self._last > self.timeout_s:
                self.stalled = True
                msg = (f'watchdog[{self.name}]: no heartbeat for '
                       f'{self.timeout_s:.0f}s')
                if self.on_stall is not None:
                    self.on_stall(msg)
                else:
                    warnings.warn(msg)
                self._last = time.monotonic()  # don't spam

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def beat(self):
        self._last = time.monotonic()
        self.stalled = False

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def save_step(state_dict, directory, step, keep=3, prefix='ckpt'):
    """Write `<dir>/<prefix>_<step>.pdparams` and prune old ones."""
    from ..framework.io import save
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f'{prefix}_{step}.pdparams')
    save(state_dict, path)
    # prune (ignore non-numeric suffixes: foreign files in the dir)
    ckpts = sorted(
        (f for f in os.listdir(directory)
         if f.startswith(prefix + '_') and f.endswith('.pdparams')
         and f[len(prefix) + 1:-len('.pdparams')].isdigit()),
        key=lambda f: int(f[len(prefix) + 1:-len('.pdparams')]))
    for old in ckpts[:-keep]:
        try:
            os.remove(os.path.join(directory, old))
        except OSError:
            pass
    return path


def try_load_latest(directory, prefix='ckpt'):
    """Return (state_dict, step) for the newest checkpoint, or
    (None, -1) when none exists — elastic-restart entry point."""
    from ..framework.io import load
    if not os.path.isdir(directory):
        return None, -1
    ckpts = sorted(
        (f for f in os.listdir(directory)
         if f.startswith(prefix + '_') and f.endswith('.pdparams')
         and f[len(prefix) + 1:-len('.pdparams')].isdigit()),
        key=lambda f: int(f[len(prefix) + 1:-len('.pdparams')]))
    if not ckpts:
        return None, -1
    newest = ckpts[-1]
    step = int(newest[len(prefix) + 1:-len('.pdparams')])
    return load(os.path.join(directory, newest)), step
