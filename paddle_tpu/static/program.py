"""Static ("declarative") graph mode.

Reference analogue: /root/reference/python/paddle/fluid/framework.py
(Program/Block/Operator protos) + executor.py + the C++ Executor
(/root/reference/paddle/fluid/framework/executor.cc) that schedules op
kernels one by one.  TPU-native redesign: building a Program records a
LAZY OP DAG of python closures over symbolic Variables; Executor.run
topologically evaluates that DAG *inside one jax.jit trace*, so the
whole Program — forward, backward (jax.grad), optimizer update — lowers
to a single fused XLA module.  There is no op-by-op scheduling at run
time at all; that is the point of the redesign (XLA owns scheduling,
streams and memory).

The op-recording hook lives in core/dispatch.py: when any input of an
eager op is a `Variable`, the op is recorded instead of executed.
nn.Layer forwards therefore work unchanged in static mode, like the
reference where the same paddle.nn code builds ops into the default
Program.
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dispatch
from ..core.dtype import convert_dtype, get_default_dtype

__all__ = ['Program', 'program_guard', 'default_main_program',
           'default_startup_program', 'data', 'Executor', 'Variable',
           'in_static_mode', 'enable_static', 'disable_static',
           'global_scope', 'scope_guard']

_static_mode = False


def _param_names(params):
    """Unique real names for a parameter list (the key set shared by
    the Executor's pytrees and apply_gradients' name-based hooks)."""
    names, seen = [], set()
    for i, p in enumerate(params):
        n = getattr(p, 'name', None) or f'param_{i}'
        if n in seen:
            n = f'{n}_{i}'
        seen.add(n)
        names.append(n)
    return names


def enable_static():
    global _static_mode
    _static_mode = True
    dispatch.set_static_handler(_record_op)


def disable_static():
    global _static_mode
    _static_mode = False
    dispatch.set_static_handler(None)


def in_static_mode():
    return _static_mode


class Variable(Tensor):
    """Symbolic node in a Program's DAG.

    Reference analogue: framework.py::Variable (a name in a BlockDesc).
    Holds a compute thunk instead of storage; shape/dtype come from
    jax.eval_shape over the recorded subgraph (free shape inference —
    the reference hand-writes InferShape per op).
    """

    def __init__(self, program, name, kind, thunk=None, aval=None):
        # deliberately do NOT call Tensor.__init__ — no storage
        self.program = program
        self.name = name
        self.kind = kind          # 'feed' | 'op' | 'param-read'
        self._thunk = thunk       # fn(env) -> jax value
        self._aval_cache = aval
        self.stop_gradient = kind == 'feed'
        self.persistable = False
        self._grad = None
        self.grad_node = None
        self.grad_index = 0

    # -- symbolic evaluation -------------------------------------------------
    def _eval(self, env):
        if id(self) in env:
            return env[id(self)]
        v = self._thunk(env)
        env[id(self)] = v
        return v

    @property
    def aval(self):
        if self._aval_cache is None:
            feed_objs = list(self.program.feed_vars.values())
            structs = [jax.ShapeDtypeStruct(v._feed_shape, v._feed_dtype)
                       for v in feed_objs]

            def run(*fv):
                env = {id(v): val for v, val in zip(feed_objs, fv)}
                return self._eval(env)
            self._aval_cache = jax.eval_shape(run, *structs)
        return self._aval_cache

    @property
    def shape(self):
        return list(self.aval.shape)

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def value(self):
        raise RuntimeError(
            f"Variable '{self.name}' has no value outside Executor.run — "
            "fetch it via exe.run(fetch_list=[...])")

    @value.setter
    def value(self, v):
        # buffer write-back during static trace (e.g. BatchNorm running
        # stats): record as a program side-effect
        if isinstance(v, Variable):
            self.program.side_effects.append((self, v))
        # concrete assignment replaces the thunk with a constant
        else:
            self._thunk = lambda env, _v=v: _v
            self._aval_cache = None

    def backward(self, *a, **k):
        raise RuntimeError("call optimizer.minimize(loss) in static mode")

    def detach(self):
        # no eager tape in static mode; gradients come from jax.grad over
        # the recorded graph, and Executor treats side-effect sources as
        # non-differentiable roots already
        return self

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' has no value — fetch it via "
            "exe.run(fetch_list=[...])")

    def __repr__(self):
        try:
            return (f"Variable(name={self.name}, shape={self.shape}, "
                    f"dtype={self.dtype})")
        except Exception:
            return f"Variable(name={self.name})"


class Program:
    """Reference: framework.py::Program (ProgramDesc proto).  Records
    feed vars, the op DAG (implicit in Variable thunks), side effects,
    and the training section appended by optimizer.minimize."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self.id = Program._counter
        self.feed_vars = {}        # name -> Variable(kind='feed')
        self.side_effects = []     # [(target Variable/Tensor, source Var)]
        self.train_section = None  # (loss_var, optimizer)
        self.amp_policy = None     # auto_cast kwargs (static.amp)
        self.random_seed = 0
        self._version = 0
        self._cache = {}
        self._params = {}          # id -> param Tensor seen during record

    def all_parameters(self):
        """Every parameter Tensor read by recorded ops (reference
        Program.all_parameters walks the blocks' var list)."""
        return list(self._params.values())

    def trainable_parameters(self, no_grad_set=None):
        """all_parameters minus stop_gradient and no_grad_set — the
        selection both append_backward and optimizer.minimize use."""
        ng = {id(p) for p in (no_grad_set or [])}
        return [p for p in self._params.values()
                if not getattr(p, 'stop_gradient', False)
                and id(p) not in ng]

    def bump(self):
        self._version += 1
        self._cache.clear()

    def clone(self, for_test=False):
        import copy
        p = copy.copy(self)
        if for_test:
            p = copy.copy(self)
            p.train_section = None
        return p

    def global_block(self):
        return self

    # Block-API shim: list "vars" like the reference's Block
    @property
    def vars(self):
        return dict(self.feed_vars)

    # -- static validation (paddle_tpu.analysis) -----------------------------
    def lint(self, fetch_list=None, mesh=None, disable=(),
             thresholds=None):
        """Run the TPU lint rules over the program's recorded DAG.

        Builds the same closure Executor._compile evaluates — feeds
        become ShapeDtypeStruct placeholders (declared shapes, batch
        dim 1), parameters become explicit arguments (so they are NOT
        reported as captured constants) — and traces it abstractly.
        Returns a LintReport; nothing executes on device.
        """
        import jax as _jax
        from .. import analysis

        fetch_vars = [v for v in (fetch_list or [])
                      if isinstance(v, Variable)]
        if not fetch_vars and self.train_section is not None:
            fetch_vars = [self.train_section[0]]
        feed_objs = list(self.feed_vars.values())
        structs = [_jax.ShapeDtypeStruct(v._feed_shape, v._feed_dtype)
                   for v in feed_objs]
        params = list(self._params.values())
        p_structs = [_jax.ShapeDtypeStruct(tuple(p.value.shape),
                                           p.value.dtype)
                     for p in params]
        side_sources = [v for _, v in self.side_effects]

        def run(feed_vals, pvals):
            env = {'__params__':
                   {id(p): v for p, v in zip(params, pvals)}}
            for v, val in zip(feed_objs, feed_vals):
                env[id(v)] = val
            outs = [fv._eval(env) for fv in fetch_vars]
            side = [sv._eval(env) for sv in side_sources]
            return outs, side

        return analysis.lint(run, structs, p_structs, mesh=mesh,
                             disable=disable, thresholds=thresholds,
                             name=f'Program#{self.id}', source=False)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    prev_m, prev_s = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev_m, prev_s


def data(name, shape, dtype='float32', lod_level=0):
    """Declare a feed Variable (reference: static/input.py::data).
    shape may contain None/-1 (dynamic batch) — resolved at run time;
    the abstract batch dim defaults to 1 for shape inference."""
    prog = default_main_program()
    v = Variable(prog, name, 'feed')
    v._feed_shape = tuple(1 if (d is None or d == -1) else int(d)
                          for d in shape)
    v._feed_dtype = convert_dtype(dtype) or get_default_dtype()
    v._declared_shape = tuple(-1 if (d is None or d == -1) else int(d)
                              for d in shape)
    prog.feed_vars[name] = v
    prog.bump()
    return v


# -- op recording hook (installed into core.dispatch) ------------------------

def _record_op(fn, args, kwargs, op_name):
    """Called by dispatch.apply BEFORE eager execution.  If any arg is a
    Variable, record the op into its Program and return a new Variable.
    Returns NotImplemented to fall through to eager execution."""
    vars_in = [a for a in args if isinstance(a, Variable)]
    if not vars_in:
        return NotImplemented
    prog = vars_in[0].program
    arg_slots = []
    for a in args:
        if isinstance(a, Variable):
            arg_slots.append(('var', a))
        elif isinstance(a, Tensor):
            arg_slots.append(('tensor', a))   # param: read value at run
            prog._params[id(a)] = a
        else:
            arg_slots.append(('const', a))
    kw_slots = {}
    for k, v in kwargs.items():
        if isinstance(v, Variable):
            kw_slots[k] = ('var', v)
        elif isinstance(v, Tensor):
            kw_slots[k] = ('tensor', v)
            prog._params[id(v)] = v
        else:
            kw_slots[k] = ('const', v)

    def resolve(slot, env):
        kind, obj = slot
        if kind == 'var':
            return obj._eval(env)
        if kind == 'tensor':
            pe = env.get('__params__')
            if pe is not None and id(obj) in pe:
                return pe[id(obj)]
            return obj.value
        return obj

    def thunk(env):
        a = [resolve(s, env) for s in arg_slots]
        kw = {k: resolve(s, env) for k, s in kw_slots.items()}
        # static AMP: the eager path runs dispatch's amp hook per op;
        # recorded thunks must consult it too, at EVAL time — so the
        # auto_cast state active while the Executor compiles (see
        # static.amp.decorate) casts the whole program the same way
        hook = dispatch._amp_hook
        if hook is not None:
            arrs = [v for v in a if hasattr(v, 'dtype')]
            if arrs:
                it = iter(hook(op_name or '', arrs))
                a = [next(it) if hasattr(v, 'dtype') else v for v in a]
        out = fn(*a, **kw)
        return out

    prefix = '/'.join(_name_scopes)
    base = f"{prefix}/{op_name or 'op'}" if prefix else (op_name or 'op')
    out_var = Variable(prog, f"{base}_{id(thunk)}", 'op', thunk)
    # multi-output ops: build child selector Variables
    try:
        aval = out_var.aval
    except Exception:
        aval = None
    if isinstance(aval, (tuple, list)):
        outs = []
        for i in range(len(aval)):
            outs.append(Variable(
                prog, f"{out_var.name}.{i}", 'op',
                lambda env, i=i: out_var._eval(env)[i]))
        return tuple(outs)
    return out_var


# -- Executor ----------------------------------------------------------------

class _Scope:
    pass


_scope = _Scope()


def global_scope():
    return _scope


@contextlib.contextmanager
def scope_guard(scope):
    yield scope


class Executor:
    """Reference: python/paddle/fluid/executor.py + C++ executor.cc.
    run() compiles the whole Program into one jitted function, keyed by
    (program version, feed shapes, fetch ids)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, check=None):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        if hasattr(program, '_run_loaded'):     # load_inference_model
            return program._run_loaded(feed, fetch_list, return_numpy)
        if hasattr(program, '_unwrap'):          # CompiledProgram
            program = program._unwrap()
        lint_key = (program.id, program._version, str(check))
        if check and lint_key not in getattr(self, '_linted_versions',
                                             set()):
            # validate once per (program, version, mode) — a 'warn'
            # run never satisfies a later 'error' gate — before the
            # first compile; safe_emit lets only LintError (the
            # 'error'-mode verdict) escape, and the key is recorded
            # only after a PASSED gate so a failed gate re-gates on
            # retry
            from .. import analysis
            self._linted_versions = getattr(self, '_linted_versions',
                                            set())
            analysis.safe_emit(
                lambda: program.lint(fetch_list=fetch_list), check)
            self._linted_versions.add(lint_key)
        if program is _default_startup or (
                not program.feed_vars and not fetch_list):
            return []  # startup: params already initialized eagerly

        feed_names = sorted(program.feed_vars.keys() & feed.keys())
        feed_vals = [jnp.asarray(feed[n]) for n in feed_names]
        fetch_vars = [v for v in fetch_list if isinstance(v, Variable)]

        train = program.train_section
        params = []
        if train is not None:
            loss_var, optimizer = train
            params = [p for p in optimizer._params if not p.stop_gradient]

        key = (program._version, tuple(f.shape + (str(f.dtype),)
                                       for f in feed_vals),
               tuple(id(v) for v in fetch_vars), bool(train))
        compiled = program._cache.get(key)
        if compiled is None:
            compiled = self._compile(program, feed_names, fetch_vars,
                                     params)
            program._cache[key] = compiled
        if program.amp_policy:
            # jit traces lazily on first call: the policy must be live
            # while the thunks run so their amp-hook consult casts the
            # recorded ops (static.amp.decorate semantics)
            from ..amp import auto_cast
            amp_ctx = auto_cast(**program.amp_policy)
        else:
            amp_ctx = contextlib.nullcontext()

        side_targets = [t for t, _ in program.side_effects]
        with amp_ctx:
            if train is not None:
                loss_var, optimizer = train
                step = optimizer._global_step + 1
                names = _param_names(params)
                pvals = {n: p.value for n, p in zip(names, params)}
                svals = {n: optimizer._accumulators_for(p)
                         for n, p in zip(names, params)}
                fetched, new_p, new_s, side_vals = compiled(
                    feed_vals, pvals, svals, jnp.asarray(step))
                for n, p in zip(names, params):
                    p.value = new_p[n]
                    optimizer._accumulators[id(p)] = new_s[n]
                optimizer._global_step = step
            else:
                fetched, side_vals = compiled(feed_vals)
        # apply recorded buffer write-backs (e.g. BN running stats)
        for t, v in zip(side_targets, side_vals):
            t.value = v.astype(t.value.dtype)

        if return_numpy:
            return [np.asarray(v) for v in fetched]
        return [Tensor._from_value(v) for v in fetched]

    def _compile(self, program, feed_names, fetch_vars, params):
        feed_var_objs = [program.feed_vars[n] for n in feed_names]
        side_sources = [v for _, v in program.side_effects]

        train = program.train_section
        if train is None:
            @jax.jit
            def run_eval(feed_vals):
                env = {'__params__': None}
                for v, val in zip(feed_var_objs, feed_vals):
                    env[id(v)] = val
                outs = [fv._eval(env) for fv in fetch_vars]
                side = [sv._eval(env) for sv in side_sources]
                return outs, side
            return run_eval

        loss_var, optimizer = train

        names = _param_names(params)

        @jax.jit
        def run_train(feed_vals, pvals, svals, step):
            def loss_fn(pvals):
                param_env = {id(p): pvals[n]
                             for n, p in zip(names, params)}
                env = {'__params__': param_env}
                for v, val in zip(feed_var_objs, feed_vals):
                    env[id(v)] = val
                loss = loss_var._eval(env)
                outs = [fv._eval(env) for fv in fetch_vars]
                side = [sv._eval(env) for sv in side_sources]
                return loss.astype(jnp.float32).sum(), (outs, side)
            grads, (outs, side) = jax.grad(loss_fn, has_aux=True)(pvals)
            # apply_gradients applies grad clipping + weight decay exactly
            # like the eager step() path; params travel as dicts keyed by
            # REAL parameter names so name-based exemptions
            # (apply_decay_param_fun excluding bias/norm) keep working
            new_p, new_s = optimizer.apply_gradients(
                pvals, grads, svals, step)
            return outs, new_p, new_s, side

        return run_train


# -- graph-surgery-free equivalents of the reference's backward pass ---------

_name_scopes = []


@contextlib.contextmanager
def name_scope(prefix):
    """Prefix recorded op names (reference fluid.framework.name_scope —
    there it nests ProgramDesc name scopes; here names are diagnostic)."""
    _name_scopes.append(str(prefix))
    try:
        yield
    finally:
        _name_scopes.pop()


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Symbolic grads of sum(targets) w.r.t. each input (reference
    static/gradient helpers: paddle.static.gradients →
    fluid/backward.py::gradients).

    TPU-native: instead of appending grad-op descs to the Program, each
    returned Variable's thunk re-evaluates the recorded subgraph under
    jax.grad with the input substituted — XLA CSE merges the recompute
    with the forward, so the compiled module matches a hand-appended
    backward.
    """
    targets = list(targets) if isinstance(targets, (list, tuple)) \
        else [targets]
    inputs_l = list(inputs) if isinstance(inputs, (list, tuple)) \
        else [inputs]
    if target_gradients is not None:
        tgs = list(target_gradients) if isinstance(
            target_gradients, (list, tuple)) else [target_gradients]
    else:
        tgs = [None] * len(targets)
    prog = targets[0].program
    ng_vars = [v for v in (no_grad_set or []) if isinstance(v, Variable)]

    def make_thunk(inp):
        def thunk(env):
            feeds = {id(v): env[id(v)]
                     for v in prog.feed_vars.values() if id(v) in env}

            def f(val):
                env2 = dict(feeds)
                pe = env.get('__params__')
                if isinstance(inp, Variable):
                    env2['__params__'] = pe
                    env2[id(inp)] = val
                else:               # parameter Tensor
                    pe2 = dict(pe) if pe else {}
                    pe2[id(inp)] = val
                    env2['__params__'] = pe2
                # no_grad_set: pre-seed those vars with stop_gradient'd
                # values so flow through them is cut (Paddle contract)
                for ng in ng_vars:
                    env2[id(ng)] = jax.lax.stop_gradient(ng._eval(env))
                total = 0.0
                for t, g in zip(targets, tgs):
                    tv = t._eval(env2).astype(jnp.float32)
                    if g is not None:
                        gv = g._eval(env2) if isinstance(g, Variable) \
                            else jnp.asarray(getattr(g, 'value', g))
                        tv = tv * gv.astype(jnp.float32)
                    total = total + tv.sum()
                return total

            if isinstance(inp, Variable):
                val0 = inp._eval(env)
            else:
                pe = env.get('__params__')
                val0 = pe[id(inp)] if pe and id(inp) in pe else inp.value
            return jax.grad(f)(val0)
        return thunk

    outs = []
    for inp in inputs_l:
        nm = getattr(inp, 'name', None) or 'x'
        outs.append(Variable(prog, f'{nm}@GRAD', 'op', make_thunk(inp)))
    return outs


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Reference fluid/backward.py::append_backward — returns
    [(param, grad_variable)] for every trainable parameter the Program
    has read (no graph mutation needed; see gradients())."""
    params = parameter_list if parameter_list is not None else \
        loss.program.trainable_parameters(no_grad_set)
    grads = gradients([loss], params, no_grad_set=no_grad_set)
    return list(zip(params, grads))


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase='both'):
    """Debug-print op (reference fluid/layers/control_flow.py::Print):
    passes `input` through unchanged and prints it when the compiled
    program executes (jax.debug.print survives jit)."""
    prog = input.program
    tag = message or (input.name if print_tensor_name else 'Print')

    def thunk(env):
        v = input._eval(env)
        jax.debug.print(tag + ': {x}', x=v)
        return v
    return Variable(prog, f'{input.name}.print', 'op', thunk)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Embed arbitrary host Python into the program (reference
    fluid/layers/nn.py::py_func, which registers a C++ callback op).
    TPU-native: jax.pure_callback — XLA yields to the host at this node.

    `out` declares the result spec: an InputSpec, a (shape, dtype)
    tuple, or a feed Variable template (or a list of those).
    backward_func(x..., out..., dout...) -> dx... runs on host too, via
    jax.custom_vjp.
    """
    from .input_spec import InputSpec

    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    prog = next(a.program for a in xs if isinstance(a, Variable))

    def spec_of(o, batch):
        """Declared spec -> concrete ShapeDtypeStruct.  A dynamic
        (None/-1) dim is allowed in position 0 only and resolves to the
        first input's leading (batch) dim at trace time."""
        if isinstance(o, InputSpec):
            shape, dt = o.shape, o.numpy_dtype() or np.float32
        elif isinstance(o, Variable):
            shape, dt = getattr(o, '_declared_shape', o._feed_shape), \
                o._feed_dtype
        else:
            shape, dt = o[0], convert_dtype(o[1])
        resolved = []
        for i, d in enumerate(shape):
            if d is None or d == -1:
                if i != 0:
                    raise ValueError(
                        'py_func: dynamic out dims are only supported in '
                        f'position 0 (batch), got dynamic dim {i} in '
                        f'{tuple(shape)}')
                resolved.append(int(batch))
            else:
                resolved.append(int(d))
        return jax.ShapeDtypeStruct(tuple(resolved), dt)
    single = not isinstance(out, (list, tuple))

    def make_host_fwd(out_specs):
        def host_fwd(*vals):
            res = func(*[np.asarray(v) for v in vals])
            res = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r, s.dtype).reshape(s.shape)
                         for r, s in zip(res, out_specs))
        return host_fwd

    def make_call(out_specs):
        host_fwd = make_host_fwd(out_specs)
        if backward_func is None:
            def call(*vals):
                return jax.pure_callback(host_fwd, tuple(out_specs), *vals)
            return call

        @jax.custom_vjp
        def call(*vals):
            return jax.pure_callback(host_fwd, tuple(out_specs), *vals)

        def fwd(*vals):
            res = jax.pure_callback(host_fwd, tuple(out_specs), *vals)
            return res, (vals, res)

        def bwd(resid, douts):
            vals, res = resid

            def host_bwd(*flat):
                grads = backward_func(*[np.asarray(v) for v in flat])
                grads = grads if isinstance(grads, (list, tuple)) \
                    else [grads]
                return tuple(np.asarray(g, v.dtype).reshape(v.shape)
                             for g, v in zip(grads, vals))
            in_specs = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                             for v in vals)
            return jax.pure_callback(host_bwd, in_specs,
                                     *vals, *res, *douts)
        call.defvjp(fwd, bwd)
        return call

    def thunk(env):
        vals = [a._eval(env) if isinstance(a, Variable)
                else jnp.asarray(getattr(a, 'value', a)) for a in xs]
        batch = vals[0].shape[0] if vals and vals[0].ndim else 1
        res = make_call([spec_of(o, batch) for o in outs])(*vals)
        return res[0] if single else tuple(res)
    return Variable(prog, f'py_func_{id(func)}', 'op', thunk)


_global_var_count = [0]


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Persistent scalar/tensor var (reference
    fluid/layers/tensor.py::create_global_var).  Lives eagerly as a
    Tensor (XLA owns placement; force_cpu is advisory) and is registered
    with the default Program so static save/load picks it up."""
    _global_var_count[0] += 1
    t = Tensor(jnp.full(tuple(int(s) for s in shape), value,
                        convert_dtype(dtype)))
    t.name = name or f'global_var_{_global_var_count[0]}'
    t.persistable = persistable
    t.stop_gradient = True
    default_main_program()._params[id(t)] = t
    return t


__all__ += ['gradients', 'append_backward', 'Print', 'py_func',
            'name_scope', 'create_global_var']
