"""Executor-strategy API shims.

Reference analogue: /root/reference/python/paddle/fluid/compiler.py
(CompiledProgram), framework BuildStrategy/ExecutionStrategy pybinds, and
parallel_executor.py.  On TPU these are knob objects without an engine
behind them BY DESIGN: XLA owns scheduling, stream assignment, memory
reuse and op fusion, and multi-device execution is SPMD via
paddle_tpu.parallel.ParallelTrainer / fleet — not a per-op graph
scheduler.  The classes accept the reference's attributes (so ported
code runs) and warn when a knob that implies a different execution
engine is turned on.
"""
import warnings

from ..core import device as _device

__all__ = ['BuildStrategy', 'ExecutionStrategy', 'CompiledProgram',
           'ParallelExecutor', 'cpu_places', 'cuda_places',
           'WeightNormParamAttr']


class _KnobBag:
    """Accepts arbitrary attribute writes like the reference's pybind
    structs; records them for introspection."""

    def __init__(self):
        object.__setattr__(self, '_knobs', {})

    def __setattr__(self, k, v):
        self._knobs[k] = v

    def __getattr__(self, k):
        if k.startswith('_'):
            raise AttributeError(k)
        return self._knobs.get(k)


class BuildStrategy(_KnobBag):
    """Graph-build knobs (fuse_*, memory_optimize, reduce_strategy).
    XLA performs the equivalent passes unconditionally; values are
    recorded, never dispatched."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1


class ExecutionStrategy(_KnobBag):
    """num_threads / num_iteration_per_drop_scope etc. — scheduling is
    XLA's; recorded only."""


class CompiledProgram:
    """Reference compiler.py::CompiledProgram.  Executor.run already
    compiles the whole Program to one XLA module, so this wrapper only
    carries the program through (and keeps .with_data_parallel for
    ported code — data parallelism on TPU is ParallelTrainer/fleet)."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        warnings.warn(
            'CompiledProgram.with_data_parallel is a no-op here: use '
            'paddle_tpu.parallel.ParallelTrainer or fleet for SPMD data '
            'parallelism over a device mesh', stacklevel=2)
        return self

    # Executor.run unwraps this
    def _unwrap(self):
        return self._program


class ParallelExecutor:
    """Reference parallel_executor.py — a multi-stream op scheduler.
    Superseded by SPMD: kept as a thin veneer over Executor so legacy
    call-sites run single-process."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, scope=None,
                 share_vars_from=None):
        from .program import Executor, default_main_program
        warnings.warn(
            'ParallelExecutor maps to the single XLA Executor on TPU; '
            'use fleet/ParallelTrainer for real multi-device SPMD',
            stacklevel=2)
        self._program = main_program or default_main_program()
        self._exe = Executor()

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return self._exe.run(program=self._program, feed=feed,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


def cpu_places(device_count=None):
    """List of CPU places (reference framework.cpu_places)."""
    n = device_count or 1
    return [_device.CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places; on TPU these resolve to TPU devices
    (reference framework.cuda_places)."""
    if device_ids is None:
        device_ids = range(_device.device_count())
    return [_device.TPUPlace(i) for i in device_ids]


def WeightNormParamAttr(dim=None, name=None, initializer=None,
                        learning_rate=1.0, regularizer=None,
                        trainable=True, do_model_average=False,
                        need_clip=True):
    """Reference static/param_attr WeightNormParamAttr: requests the
    weight-norm reparameterization w = g * v / ||v||.  Here the
    reparameterization is a Layer transform — apply
    paddle_tpu.nn.utils.weight_norm(layer, dim=dim) — so this returns a
    plain ParamAttr carrying the trainability knobs and warns that the
    norm itself must come from the layer transform."""
    from ..nn.layer.layers import ParamAttr
    warnings.warn(
        'WeightNormParamAttr: apply paddle_tpu.nn.utils.weight_norm('
        'layer, dim=...) for the actual reparameterization; this attr '
        'carries initializer/trainability only', stacklevel=2)
    return ParamAttr(name=name, initializer=initializer,
                     learning_rate=learning_rate, regularizer=regularizer,
                     trainable=trainable, need_clip=need_clip)
