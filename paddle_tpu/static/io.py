"""Static-mode persistence.

Reference analogue: /root/reference/python/paddle/static/io.py
(save/load, save_inference_model/load_inference_model) and
fluid/io.py (load_program_state/set_program_state).

TPU-native: a Program's parameters are eager Tensors registered while
recording (Program._params), so save/load is a named-array dict; the
inference model is the Program's eval function exported to serialized
StableHLO via jax.export with the parameters baked in as constants —
the artifact is self-contained and reloads without Python model code.
"""
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .program import (Program, Variable, default_main_program,
                      _param_names)

__all__ = ['save', 'load', 'save_inference_model', 'load_inference_model',
           'load_program_state', 'set_program_state']


def _named_params(program):
    params = program.all_parameters()
    return dict(zip(_param_names(params), params))


def save(program, model_path, protocol=4):
    """paddle.static.save — persist every parameter the program read
    (reference static/io.py::save writes <path>.pdparams + .pdmodel)."""
    named = _named_params(program)
    real = [getattr(p, 'name', None) for p in named.values()]
    # duplicate real names get order-dependent <name>_<i> suffixes from
    # _param_names — they pair wrongly if the program is re-recorded in
    # a different op order.  (Fully positional param_<i> keys are fine:
    # they are stable for a fixed build script, and warning on every
    # default-named model would just train users to ignore it.)
    dupes = [n for n in set(real) if n and real.count(n) > 1]
    if dupes:
        import warnings
        warnings.warn(
            f'static.save: duplicated parameter name(s) {sorted(dupes)[:3]} '
            'were disambiguated positionally; a program recorded in a '
            'different op order will pair them wrongly on load',
            stacklevel=2)
    state = {n: np.asarray(p.value) for n, p in named.items()}
    os.makedirs(os.path.dirname(model_path) or '.', exist_ok=True)
    with open(model_path + '.pdparams', 'wb') as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """paddle.static.load — restore parameters saved by save()."""
    set_program_state(program, load_program_state(model_path,
                                                  var_list=var_list))


def load_program_state(model_path, var_list=None):
    """-> {name: ndarray} (reference fluid/io.py::load_program_state)."""
    with open(model_path + '.pdparams', 'rb') as f:
        state = pickle.load(f)
    if var_list is not None:
        keep = {getattr(v, 'name', v) for v in var_list}
        state = {k: v for k, v in state.items() if k in keep}
    return state


def set_program_state(program, state_dict):
    """Assign a load_program_state dict back onto the program's params
    (reference fluid/io.py::set_program_state)."""
    named = _named_params(program)
    missing = set(state_dict) - set(named)
    if missing:
        raise KeyError(f'state has no matching program params for '
                       f'{sorted(missing)[:5]}...')
    for n, arr in state_dict.items():
        p = named[n]
        a = jnp.asarray(arr)
        if tuple(a.shape) != tuple(p.value.shape):
            raise ValueError(
                f'set_program_state: shape mismatch for {n!r}: saved '
                f'{tuple(a.shape)} vs program param {tuple(p.value.shape)} '
                '(op-recording order may differ from save time)')
        p.value = a.astype(p.value.dtype)


class _LoadedInferenceProgram:
    """load_inference_model result: wraps the deserialized XLA module.
    Executor.run detects it and calls straight into the compiled fn."""

    def __init__(self, exported, feed_names, n_fetch):
        self._exported = exported
        self._feed_names = list(feed_names)
        self._n_fetch = n_fetch

    def _run_loaded(self, feed, fetch_list, return_numpy=True):
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise KeyError(f'feed missing inputs: {missing}')
        vals = [jnp.asarray(feed[n]) for n in self._feed_names]
        outs = self._exported.call(*vals)
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        if fetch_list:
            outs = [outs[i if isinstance(i, int) else i._fetch_index]
                    for i in fetch_list]
        return [np.asarray(o) for o in outs] if return_numpy else outs


class _FetchTarget:
    def __init__(self, index):
        self._fetch_index = index


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Export the inference slice of a Program (reference
    static/io.py::save_inference_model writes __model__+params; here one
    self-contained serialized StableHLO module with params embedded)."""
    from jax import export as jexport

    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    for v in feed_vars:
        if not isinstance(v, Variable) or v.kind != 'feed':
            raise TypeError('feed_vars must be static.data Variables')

    def fn(*feed_vals):
        env = {'__params__': None}
        for v, val in zip(feed_vars, feed_vals):
            env[id(v)] = val
        return tuple(fv._eval(env) for fv in fetch_vars)

    # dynamic (None/-1) feed dims export as jax.export symbolic dims so
    # the artifact accepts any batch, not the build-time template of 1
    structs, sym_i = [], 0
    for v in feed_vars:
        decl = getattr(v, '_declared_shape', v._feed_shape)
        if any(d == -1 for d in decl):
            parts = []
            for d in decl:
                if d == -1:
                    parts.append(f'_dyn{sym_i}')
                    sym_i += 1
                else:
                    parts.append(str(d))
            shp = jexport.symbolic_shape(', '.join(parts))
        else:
            shp = tuple(decl)
        structs.append(jax.ShapeDtypeStruct(shp, v._feed_dtype))
    try:
        exp = jexport.export(jax.jit(fn))(*structs)
    except Exception as e:
        if sym_i == 0:
            raise
        raise ValueError(
            'save_inference_model: this program does not support '
            'shape-polymorphic export over its dynamic feed dims '
            f'({e}); declare fixed shapes in static.data to export'
        ) from e
    os.makedirs(os.path.dirname(path_prefix) or '.', exist_ok=True)
    with open(path_prefix + '.pdmodel', 'wb') as f:
        f.write(exp.serialize())
    with open(path_prefix + '.pdiparams', 'wb') as f:
        pickle.dump({'feed_names': [v.name for v in feed_vars],
                     'n_fetch': len(fetch_vars)}, f)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """-> [program, feed_target_names, fetch_targets] (reference
    static/io.py::load_inference_model contract)."""
    from jax import export as jexport
    with open(path_prefix + '.pdmodel', 'rb') as f:
        exp = jexport.deserialize(f.read())
    with open(path_prefix + '.pdiparams', 'rb') as f:
        meta = pickle.load(f)
    prog = _LoadedInferenceProgram(exp, meta['feed_names'],
                                   meta['n_fetch'])
    fetch_targets = [_FetchTarget(i) for i in range(meta['n_fetch'])]
    return [prog, list(meta['feed_names']), fetch_targets]
