"""paddle_tpu.static — graph ("declarative") mode.

Reference analogue: /root/reference/python/paddle/static/ (Program,
Executor, program_guard, data).  TPU-native static mode records a lazy
op DAG and lowers the WHOLE program to one jitted XLA module at
Executor.run — see program.py.
"""
from .input_spec import InputSpec  # noqa: F401
from .program import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    data, Executor, Variable, in_static_mode, enable_static, disable_static,
    global_scope, scope_guard, gradients, append_backward, Print, py_func,
    name_scope, create_global_var)
from .io import (  # noqa: F401
    save, load, save_inference_model, load_inference_model,
    load_program_state, set_program_state)
from .compat import (  # noqa: F401
    BuildStrategy, ExecutionStrategy, CompiledProgram, ParallelExecutor,
    cpu_places, cuda_places, WeightNormParamAttr)

from . import nn  # noqa: F401
from . import amp  # noqa: F401

__all__ = ['InputSpec', 'nn', 'amp', 'Program', 'program_guard', 'default_main_program',
           'default_startup_program', 'data', 'Executor', 'Variable',
           'enable_static', 'disable_static', 'global_scope', 'scope_guard',
           'gradients', 'append_backward', 'Print', 'py_func', 'name_scope',
           'create_global_var', 'save', 'load', 'save_inference_model',
           'load_inference_model', 'load_program_state', 'set_program_state',
           'BuildStrategy', 'ExecutionStrategy', 'CompiledProgram',
           'ParallelExecutor', 'cpu_places', 'cuda_places',
           'WeightNormParamAttr']
