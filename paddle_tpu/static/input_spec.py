"""InputSpec — symbolic input signature.

Reference analogue: /root/reference/python/paddle/static/input.py
(class InputSpec).  Here a spec is exactly what jax.jit needs to build a
ShapeDtypeStruct: shape (None/-1 = dynamic batch), dtype, name.
"""
import numpy as np

from ..core.dtype import convert_dtype

__all__ = ['InputSpec']


class InputSpec:
    def __init__(self, shape, dtype='float32', name=None):
        self.shape = tuple(-1 if d is None else int(d) for d in shape)
        self.dtype = dtype
        self.name = name

    def numpy_dtype(self):
        d = convert_dtype(self.dtype)
        return np.dtype(str(d)) if d is not None else None

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")
