"""paddle.static.amp — mixed precision for static-graph Programs.

Reference analogue: /root/reference/python/paddle/static/amp/__init__.py
re-exporting fluid.contrib.mixed_precision (decorate,
AutoMixedPrecisionLists, cast_model_to_fp16, ...).  There, decorate()
wraps the optimizer in OptimizerWithMixedPrecision which rewrites the
ProgramDesc with cast ops + loss scaling.

TPU-native: no graph rewrite.  The recorded thunks consult the same
dispatch-level AMP hook the eager path uses (program.py::_record_op), so
wrapping the optimizer just pins an auto_cast policy that the Executor
activates while it TRACES the program — XLA sees bf16 matmuls directly.
Loss scaling is a numeric no-op in bfloat16 (8-bit exponent = fp32
range), so the scaler settings are accepted for API parity; the
non-finite guard lives in the trainer (parallel/engine.py).
"""
from ...amp import auto_cast as _auto_cast
from ...amp import WHITE_LIST, BLACK_LIST
from ...optimizer.optimizer import Optimizer

__all__ = ['decorate', 'AutoMixedPrecisionLists', 'CustomOpLists',
           'fp16_guard', 'cast_model_to_fp16', 'cast_parameters_to_fp16']


class AutoMixedPrecisionLists:
    """White/black op lists (reference
    fluid/contrib/mixed_precision/fp16_lists.py::AutoMixedPrecisionLists)."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
        self.black_varnames = set(custom_black_varnames or [])


CustomOpLists = AutoMixedPrecisionLists


class OptimizerWithMixedPrecision(Optimizer):
    """Decorated optimizer: minimize() records the training section as
    usual and attaches the AMP policy to the Program; Executor._compile
    traces under that policy (reference
    fluid/contrib/mixed_precision/decorator.py:51)."""

    def __init__(self, inner, amp_lists, level, dtype,
                 init_loss_scaling, use_dynamic_loss_scaling):
        self._inner = inner
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._amp_level = level
        self._amp_dtype = dtype
        self._init_loss_scaling = init_loss_scaling
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling

    # everything not overridden delegates to the wrapped optimizer
    def __getattr__(self, item):
        return getattr(self._inner, item)

    def amp_policy(self):
        return dict(enable=True,
                    custom_white_list=self._amp_lists.white_list,
                    custom_black_list=self._amp_lists.black_list,
                    level=self._amp_level, dtype=self._amp_dtype)

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        """Reference decorator.py::amp_init casts trained fp32 params
        for pure-fp16 runs; pure-bf16 Programs read fp32 master params
        and cast in-graph, so this is a documented no-op."""

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        out = self._inner.minimize(loss, startup_program=startup_program,
                                   parameters=parameters,
                                   no_grad_set=no_grad_set)
        prog = getattr(loss, 'program', None)
        if prog is not None:
            prog.amp_policy = self.amp_policy()
            # the policy changes compiled numerics: invalidate cache
            prog.bump()
        return out

    def step(self):
        with _auto_cast(**self.amp_policy()):
            self._inner.step()

    def apply_gradients(self, params, grads, state, step, lr=None):
        return self._inner.apply_gradients(params, grads, state, step,
                                           lr=lr)

    def init(self, params):
        return self._inner.init(params)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_pure_fp16=False,
             use_fp16_guard=None, use_bf16=None, level=None):
    """Wrap `optimizer` for static-graph mixed precision (reference
    static/amp re-export of mixed_precision.decorate).  use_pure_fp16
    maps to O2 (everything not blacklisted runs low precision); default
    is O1 white-list casting.  TPU low dtype is bfloat16."""
    lvl = level or ('O2' if use_pure_fp16 else 'O1')
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, lvl, 'bfloat16',
        init_loss_scaling, use_dynamic_loss_scaling)


def fp16_guard():
    """Reference mixed_precision.fp16_guard marks a region for casting
    under use_fp16_guard; equivalent here is amp.auto_cast."""
    return _auto_cast(enable=True, level='O1')


def cast_model_to_fp16(program, amp_lists=None, use_fp16_guard=True):
    """Graph-rewrite API with no TPU analogue: the policy casts at
    trace time instead.  Attach an O2 policy to the program."""
    program.amp_policy = dict(enable=True, level='O2', dtype='bfloat16')
    program.bump()
    return program


def cast_parameters_to_fp16(place, program, scope=None, to_fp16_var_names=None):
    """No-op: parameters stay fp32 masters; in-graph casts produce the
    bf16 compute (see module docstring)."""
