"""Sequence ops — TPU-native redesign of the reference's LoD-tensor ops.

Reference analogue: /root/reference/python/paddle/fluid/layers/
sequence_lod.py (sequence_conv, sequence_pool, sequence_expand, ...).
There variable-length sequences travel as LoD ("level of detail")
tensors: a flat [sum(len_i), D] buffer plus host-side offsets, and each
op's CPU/CUDA kernel walks the offsets.  LoD breaks XLA's static-shape
compilation model, so this redesign uses the TPU idiom instead:

    dense padded [B, T, ...] data  +  an explicit `seq_len` [B] tensor

Every op takes `seq_len` where the reference consulted the LoD, masks
with `arange(T) < seq_len[:, None]`, and compiles to fully static
shapes.  `sequence_pad` converts a flat LoD-style buffer into this
representation; `sequence_unpad` (host-side, eager only) converts back.
Ops whose reference semantics *require* a data-dependent output shape
(true LoD expansion) take static python sizes instead and say so in
their docstrings.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..tensor._helpers import wrap

__all__ = [
    'sequence_mask', 'sequence_conv', 'sequence_softmax', 'sequence_pool',
    'sequence_concat', 'sequence_first_step', 'sequence_last_step',
    'sequence_slice', 'sequence_expand', 'sequence_expand_as',
    'sequence_pad', 'sequence_unpad', 'sequence_reshape',
    'sequence_scatter', 'sequence_enumerate', 'sequence_reverse',
]


def _mask(T, seq_len, dtype=jnp.bool_):
    """[B, T] validity mask from lengths."""
    return (jnp.arange(T)[None, :] < seq_len[:, None]).astype(dtype)


def sequence_mask(seq_len, maxlen=None, dtype='bool'):
    """[B] lengths -> [B, maxlen] mask: the 2-D case of
    nn.functional.sequence_mask (single implementation, shared guards —
    maxlen=None needs a concrete eager seq_len)."""
    from ..nn.functional.common import sequence_mask as _seq_mask
    return _seq_mask(seq_len, maxlen=maxlen, dtype=dtype)


def sequence_conv(x, seq_len, num_filters, filter_size=3, weight=None,
                  bias=None, padding_start=None):
    """Context-window conv over time.

    Reference: sequence_lod.py::sequence_conv — gathers a window of
    filter_size timesteps around each position (LoD-aware), multiplies
    by a [filter_size*D, num_filters] weight.  Here: static pad+stack
    of the window, positions beyond seq_len zeroed.
    weight/bias: pass existing params, or None to create them.
    """
    from ..tensor.creation import create_parameter
    x, ln = wrap(x), wrap(seq_len)
    B, T, D = x.shape
    if weight is None:
        weight = create_parameter([filter_size * D, num_filters],
                                  str(x.dtype))
    w = wrap(weight)
    start = -((filter_size - 1) // 2) if padding_start is None \
        else padding_start
    ins = [x, ln, w]
    if bias is not None:
        ins.append(wrap(bias))

    def fn(v, lens, wv, *b):
        m = _mask(T, lens, v.dtype)[..., None]
        v = v * m
        cols = []
        for k in range(filter_size):
            off = start + k
            rolled = jnp.roll(v, -off, axis=1)
            if off > 0:       # window reaches past the end: zero tail
                keep = jnp.arange(T) < (T - off)
            elif off < 0:     # window reaches before start: zero head
                keep = jnp.arange(T) >= (-off)
            else:
                keep = None
            if keep is not None:
                rolled = rolled * keep[None, :, None].astype(v.dtype)
            cols.append(rolled)
        win = jnp.concatenate(cols, axis=-1)      # [B,T,filter*D]
        out = jnp.einsum('btf,fn->btn', win, wv)
        if b:
            out = out + b[0]
        return out * m

    return apply(fn, *ins, op_name='sequence_conv')


def sequence_softmax(x, seq_len):
    """Softmax over the time axis, restricted to valid positions."""
    x, ln = wrap(x), wrap(seq_len)
    T = x.shape[1]

    def fn(v, lens):
        m = _mask(T, lens)
        if v.ndim > 2:
            mm = m.reshape(m.shape + (1,) * (v.ndim - 2))
        else:
            mm = m
        neg = jnp.asarray(-1e9, v.dtype)
        z = jnp.where(mm, v, neg)
        z = jax.nn.softmax(z, axis=1)
        return jnp.where(mm, z, 0.0).astype(v.dtype)

    return apply(fn, x, ln, op_name='sequence_softmax')


def sequence_pool(x, pool_type, seq_len, pad_value=0.0):
    """sum/average/sqrt/max/min/first/last over valid timesteps.

    Reference: sequence_lod.py::sequence_pool; empty sequences produce
    pad_value like the reference."""
    x, ln = wrap(x), wrap(seq_len)
    T = x.shape[1]
    pt = pool_type.lower()

    def fn(v, lens):
        m = _mask(T, lens, v.dtype)[..., None]
        mb = _mask(T, lens)[..., None]
        n = jnp.maximum(lens, 1).astype(v.dtype)[:, None]
        if pt == 'sum':
            out = (v * m).sum(axis=1)
        elif pt == 'average':
            out = (v * m).sum(axis=1) / n
        elif pt == 'sqrt':
            out = (v * m).sum(axis=1) / jnp.sqrt(n)
        elif pt == 'max':
            out = jnp.where(mb, v, -jnp.inf).max(axis=1)
        elif pt == 'min':
            out = jnp.where(mb, v, jnp.inf).min(axis=1)
        elif pt == 'first':
            out = v[:, 0]
        elif pt == 'last':
            idx = jnp.maximum(lens - 1, 0)
            out = jnp.take_along_axis(
                v, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        else:
            raise ValueError(f'unknown pool_type {pool_type!r}')
        empty = (lens == 0)[:, None]
        return jnp.where(empty, jnp.asarray(pad_value, v.dtype), out)

    return apply(fn, x, ln, op_name='sequence_pool')


def sequence_first_step(x, seq_len):
    return sequence_pool(x, 'first', seq_len)


def sequence_last_step(x, seq_len):
    return sequence_pool(x, 'last', seq_len)


def sequence_concat(xs, seq_lens):
    """Concatenate per-row sequences: row i of the result is
    xs[0][i, :l0] ++ xs[1][i, :l1] ++ ...  padded to sum(T_k).

    Returns (out, out_len).  Reference: sequence_lod.py::sequence_concat
    on LoD tensors."""
    xs = [wrap(x) for x in xs]
    lns = [wrap(l) for l in seq_lens]
    T_out = sum(int(x.shape[1]) for x in xs)

    def fn(*args):
        k = len(args) // 2
        vs, lens = args[:k], args[k:]
        total = sum(lens)
        out = jnp.zeros((vs[0].shape[0], T_out) + vs[0].shape[2:],
                        vs[0].dtype)
        pos = jnp.arange(T_out)[None, :]                    # [1, T_out]
        offset = jnp.zeros_like(lens[0])[:, None]
        for v, ln in zip(vs, lens):
            T_k = v.shape[1]
            # positions [offset, offset+len) come from v[:, pos-offset]
            rel = pos - offset                              # [B, T_out]
            inside = (rel >= 0) & (rel < ln[:, None])
            rel_c = jnp.clip(rel, 0, T_k - 1)
            gathered = jnp.take_along_axis(
                v, rel_c.reshape(rel_c.shape + (1,) * (v.ndim - 2))
                .astype(jnp.int32), axis=1)
            out = jnp.where(
                inside.reshape(inside.shape + (1,) * (v.ndim - 2)),
                gathered, out)
            offset = offset + ln[:, None]
        return out, total

    outs = apply(fn, *(xs + lns), op_name='sequence_concat')
    return outs


def sequence_slice(x, seq_len, offset, length):
    """Per-row slice [offset_i, offset_i+length_i) of the valid part.
    Returns (out, new_len) with out padded to x's T."""
    x, ln = wrap(x), wrap(seq_len)
    off, lth = wrap(offset), wrap(length)
    T = x.shape[1]

    def fn(v, lens, o, m):
        o = o.reshape(-1)
        m_ = m.reshape(-1)
        new_len = jnp.clip(jnp.minimum(m_, lens - o), 0, T)
        pos = jnp.arange(T)[None, :]
        src = jnp.clip(pos + o[:, None], 0, T - 1)
        g = jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2))
            .astype(jnp.int32), axis=1)
        keep = pos < new_len[:, None]
        return (g * keep.reshape(keep.shape + (1,) * (v.ndim - 2))
                .astype(v.dtype), new_len)

    return apply(fn, x, ln, off, lth, op_name='sequence_slice')


def sequence_expand(x, times):
    """Repeat each row of x `times` (a static python int) along a new
    time dim.  The reference's LoD-driven per-row expansion has a
    data-dependent output shape; the static-shape equivalent (each row
    repeated the same number of times) covers the common broadcast-to-
    candidates use; per-row counts need sequence_expand_as + masks."""
    x = wrap(x)
    t = int(times)
    return apply(lambda v: jnp.repeat(v, t, axis=0), x,
                 op_name='sequence_expand')


def sequence_expand_as(x, y, y_len=None):
    """Broadcast x [B, D] (one vector per sequence) over y's time dim:
    out [B, T_y, D], zeroed past y_len."""
    x, y = wrap(x), wrap(y)
    T = y.shape[1]
    ins = [x]
    if y_len is not None:
        ins.append(wrap(y_len))

    def fn(v, *rest):
        out = jnp.broadcast_to(v[:, None], (v.shape[0], T) + v.shape[1:])
        if rest:
            m = _mask(T, rest[0], v.dtype)
            out = out * m.reshape(m.shape + (1,) * (v.ndim - 1))
        return out

    return apply(fn, *ins, op_name='sequence_expand_as')


def sequence_pad(x_flat, seq_len, maxlen, pad_value=0.0):
    """Flat LoD-style [sum(len), D] buffer -> padded [B, maxlen, D].

    This is the bridge from ragged host data into the padded-dense
    representation (reference: sequence_lod.py::sequence_pad).  maxlen
    must be static (python int)."""
    x, ln = wrap(x_flat), wrap(seq_len)
    T = int(maxlen)

    def fn(v, lens):
        B = lens.shape[0]
        starts = jnp.cumsum(lens) - lens              # exclusive cumsum
        pos = jnp.arange(T)[None, :]
        src = starts[:, None] + pos                   # [B, T]
        src = jnp.clip(src, 0, v.shape[0] - 1).astype(jnp.int32)
        out = v[src]                                  # [B, T, ...]
        keep = pos < lens[:, None]
        keep = keep.reshape(keep.shape + (1,) * (v.ndim - 1))
        return jnp.where(keep, out, jnp.asarray(pad_value, v.dtype))

    return apply(fn, x, ln, op_name='sequence_pad')


def sequence_unpad(x, seq_len):
    """Padded [B, T, D] -> flat [sum(len), D].  Output shape is data
    dependent, so this is an EAGER-ONLY host helper (raises under jit),
    mirroring how the reference materializes LoD on the host side."""
    x, ln = wrap(x), wrap(seq_len)
    if isinstance(x.value, jax.core.Tracer) or \
            isinstance(ln.value, jax.core.Tracer):
        raise RuntimeError(
            'sequence_unpad has a data-dependent output shape and '
            'cannot run inside jit; call it eagerly (host side), or '
            'keep the padded representation + seq_len through the '
            'compiled region')
    v = np.asarray(jax.device_get(x.value))
    lens = np.asarray(jax.device_get(ln.value)).astype(np.int64)
    flat = np.concatenate([v[i, :lens[i]] for i in range(v.shape[0])],
                          axis=0) if len(lens) else v[:0, 0]
    from ..core.tensor import Tensor
    return Tensor._from_value(jnp.asarray(flat))


def sequence_reshape(x, new_dim):
    """[B, T, D] -> [B, T*D/new_dim, new_dim] (reference reshapes the
    flat LoD buffer; padded rows reshape identically)."""
    x = wrap(x)
    B, T, D = x.shape
    assert (T * D) % int(new_dim) == 0, (T, D, new_dim)
    return apply(lambda v: v.reshape(B, (T * D) // int(new_dim),
                                     int(new_dim)),
                 x, op_name='sequence_reshape')


def sequence_scatter(x, index, updates, seq_len=None):
    """out[b, index[b, k]] += updates[b, k] for valid k.

    Reference: sequence_lod.py::sequence_scatter (LoD-grouped scatter).
    seq_len masks trailing (padded) update slots."""
    x, idx, upd = wrap(x), wrap(index), wrap(updates)
    ins = [x, idx, upd]
    if seq_len is not None:
        ins.append(wrap(seq_len))

    def fn(v, ix, up, *rest):
        if rest:
            K = ix.shape[1]
            m = _mask(K, rest[0], up.dtype)
            up = up * m.reshape(m.shape + (1,) * (up.ndim - 2))
        B = v.shape[0]
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], ix.shape)
        return v.at[bidx, ix].add(up)

    return apply(fn, *ins, op_name='sequence_scatter')


def sequence_enumerate(x, win_size, pad_value=0):
    """Sliding windows over id sequences: [B, T] -> [B, T, win_size],
    positions past the end filled with pad_value."""
    x = wrap(x)
    T = x.shape[1]

    def fn(v):
        cols = []
        for k in range(int(win_size)):
            shifted = jnp.roll(v, -k, axis=1)
            valid = jnp.arange(T) < (T - k)
            cols.append(jnp.where(valid[None, :], shifted,
                                  jnp.asarray(pad_value, v.dtype)))
        return jnp.stack(cols, axis=-1)

    return apply(fn, x, op_name='sequence_enumerate')


def sequence_reverse(x, seq_len):
    """Reverse each row's valid prefix; padding stays in place."""
    x, ln = wrap(x), wrap(seq_len)
    T = x.shape[1]

    def fn(v, lens):
        pos = jnp.arange(T)[None, :]
        rev = lens[:, None] - 1 - pos
        src = jnp.where(pos < lens[:, None], rev, pos)
        src = src.reshape(src.shape + (1,) * (v.ndim - 2))
        return jnp.take_along_axis(v, src.astype(jnp.int32), axis=1)

    return apply(fn, x, ln, op_name='sequence_reverse')
