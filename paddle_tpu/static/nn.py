"""static.nn — graph-mode layer helpers.

Reference analogue: python/paddle/static/nn/__init__.py (~40 helpers
from common.py + fluid/layers).  Each helper builds the live Layer
eagerly (parameters materialize immediately, like the reference's
startup program) and applies it to the symbolic Variable, so the op
lands in the current Program's DAG and compiles into the Executor's XLA
module.  Control flow (cond/while_loop/case/switch_case) lowers to
lax.cond/lax.while_loop/lax.switch via the dy2static shims instead of
the reference's conditional_block/while ProgramDesc ops; sequence_* ops
live in static/sequence.py (padded-dense redesign of LoD).
"""
import numpy as np
import jax
import jax.numpy as jnp

from .. import nn as _nn
from ..nn import functional as F
from ..core.dispatch import apply
from ..tensor import manipulation
from ..tensor._helpers import wrap
from ..tensor.creation import create_parameter  # noqa: F401 (re-export)
from .sequence import (  # noqa: F401 (re-export, reference surface)
    sequence_mask, sequence_conv, sequence_softmax, sequence_pool,
    sequence_concat, sequence_first_step, sequence_last_step,
    sequence_slice, sequence_expand, sequence_expand_as, sequence_pad,
    sequence_unpad, sequence_reshape, sequence_scatter,
    sequence_enumerate, sequence_reverse)

__all__ = [
    'fc', 'conv2d', 'conv3d', 'conv2d_transpose', 'conv3d_transpose',
    'batch_norm', 'embedding', 'sparse_embedding', 'dropout',
    'layer_norm', 'group_norm', 'instance_norm', 'data_norm',
    'spectral_norm', 'prelu', 'create_parameter',
    'bilinear_tensor_product', 'row_conv', 'nce', 'crf_decoding',
    'deform_conv2d', 'py_func', 'multi_box_head',
    'cond', 'while_loop', 'case', 'switch_case',
    'sequence_mask', 'sequence_conv', 'sequence_softmax',
    'sequence_pool', 'sequence_concat', 'sequence_first_step',
    'sequence_last_step', 'sequence_slice', 'sequence_expand',
    'sequence_expand_as', 'sequence_pad', 'sequence_unpad',
    'sequence_reshape', 'sequence_scatter', 'sequence_enumerate',
    'sequence_reverse',
]


def _apply_act(x, act):
    if act is None:
        return x
    fn = getattr(F, act, None)
    if fn is None:
        raise ValueError(f'unknown activation {act!r}')
    return fn(x)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, act=None, name=None):
    # `act` is the fluid-1.x spelling of `activation`
    activation = activation if activation is not None else act
    shape = x.shape
    in_dim = int(np.prod(shape[num_flatten_dims:]))
    layer = _nn.Linear(in_dim, size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    if len(shape) > num_flatten_dims + 1:
        # flatten keeps leading dims symbolic (batch may be None/dynamic)
        x = manipulation.flatten(x, start_axis=num_flatten_dims,
                                 stop_axis=-1)
    return _apply_act(layer(x), activation)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format='NCHW', name=None):
    ch_axis = 1 if data_format == 'NCHW' else -1
    in_ch = input.shape[ch_axis]
    layer = _nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    return _apply_act(layer(input), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format='NCDHW', name=None):
    ch_axis = 1 if data_format == 'NCDHW' else -1
    in_ch = input.shape[ch_axis]
    layer = _nn.Conv3D(in_ch, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    return _apply_act(layer(input), act)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               is_test=False, name=None):
    ch_axis = 1 if data_layout == 'NCHW' else -1
    layer = _nn.BatchNorm(input.shape[ch_axis], momentum=momentum,
                          epsilon=epsilon, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_layout)
    if is_test:
        layer.eval()
    return _apply_act(layer(input), act)


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype='float32', name=None):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    return layer(input)


def dropout(x, dropout_prob=0.5, is_test=False, name=None):
    return F.dropout(x, p=dropout_prob, training=not is_test)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = list(input.shape[begin_norm_axis:])
    layer = _nn.LayerNorm(shape, epsilon=epsilon,
                          weight_attr=param_attr if scale else False,
                          bias_attr=bias_attr if shift else False)
    return _apply_act(layer(input), act)


def prelu(x, mode='all', param_attr=None, name=None):
    ch = 1 if mode == 'all' else x.shape[1]
    layer = _nn.PReLU(num_parameters=ch, weight_attr=param_attr)
    return layer(x)


def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=1, param_attr=None, bias_attr=None,
                     act=None, data_format='NCHW', name=None,
                     output_size=None):
    ch_axis = 1 if data_format == 'NCHW' else -1
    layer = _nn.Conv2DTranspose(
        input.shape[ch_axis], num_filters, filter_size, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format)
    return _apply_act(layer(input, output_size), act)


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=1, param_attr=None, bias_attr=None,
                     act=None, data_format='NCDHW', name=None,
                     output_size=None):
    ch_axis = 1 if data_format == 'NCDHW' else -1
    layer = _nn.Conv3DTranspose(
        input.shape[ch_axis], num_filters, filter_size, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format)
    return _apply_act(layer(input, output_size), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout='NCHW', name=None):
    layer = _nn.GroupNorm(
        groups, input.shape[1 if data_layout == 'NCHW' else -1],
        epsilon=epsilon, weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_layout)
    return _apply_act(layer(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    layer = _nn.InstanceNorm2D(input.shape[1], epsilon=epsilon,
                               weight_attr=param_attr,
                               bias_attr=bias_attr)
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     dtype='float32', is_test=False, entry=None,
                     name=None):
    """Large-vocab embedding (reference: fluid/contrib sparse_embedding,
    backed by the parameter server).  TPU-native: the table is a dense
    mesh-shardable parameter; fleet's VocabParallelEmbedding (tp-sharded
    rows) or incubate.HostOffloadEmbedding cover the beyond-HBM case.
    `entry` admission (ProbabilityEntry/CountFilterEntry) is enforced by
    incubate.HostOffloadEmbedding(entry=...); the dense path warns."""
    if entry is not None:
        import warnings
        warnings.warn(
            'sparse_embedding(entry=...): admission filtering applies on '
            'the host-offloaded table — use incubate.HostOffloadEmbedding('
            'entry=entry) for enforced admission; the dense static path '
            'ignores it', stacklevel=2)
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype, name=name)


def data_norm(input, epsilon=1e-4, param_attr=None, name=None,
              moving_mean=None, moving_var=None,
              do_model_average_for_mean_and_var=True,
              slot_dim=-1, summary_decay_rate=0.9999999,
              accumulators=None, is_test=False):
    """Normalization by accumulated batch statistics WITHOUT scale/shift
    (reference: fluid/layers/nn.py::data_norm — used by CTR models where
    gamma/beta would destroy sparse-feature scale).

    The three accumulators (batch_size, batch_sum, batch_square_sum)
    normalize the CURRENT batch with the totals of PREVIOUS batches and
    are then advanced by gradient-free running totals (the batch_norm
    running-stat pattern).  Pass `accumulators=(n, s, sq)` to share
    state across calls (each call with accumulators=None creates fresh
    state); is_test=True freezes them."""
    from ..nn import initializer as I
    from ..core.autograd import no_grad
    x = wrap(input)
    D = x.shape[-1]
    if accumulators is None:
        size = create_parameter([D], 'float32',
                                default_initializer=I.Constant(1.0))
        summ = create_parameter([D], 'float32',
                                default_initializer=I.Constant(0.0))
        sqsum = create_parameter([D], 'float32',
                                 default_initializer=I.Constant(1.0))
    else:
        size, summ, sqsum = (wrap(a) for a in accumulators)

    def fn(v, n, s, sq):
        mean = s / n
        scale = jax.lax.rsqrt(jnp.maximum(sq / n - jnp.square(mean),
                                          0.0) + epsilon)
        return (v - mean) * scale

    out = apply(fn, x, size, summ, sqsum, op_name='data_norm')
    if not is_test:
        with no_grad():
            B = x.shape[0]
            size.set_value(size + float(B))
            summ.set_value(summ + x.detach().sum(axis=0))
            sqsum.set_value(sqsum + (x.detach() * x.detach()).sum(axis=0))
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectral normalization: W / sigma_max(W), sigma estimated by
    `power_iters` rounds of power iteration (reference:
    fluid/layers/nn.py::spectral_norm with persistent u/v; here u is
    re-estimated from a fixed seed each call — stateless and traceable,
    converging to the same sigma)."""
    w = wrap(weight)

    def fn(wv):
        mat = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
        u = jax.random.normal(jax.random.PRNGKey(0), (mat.shape[0],),
                              jnp.float32).astype(mat.dtype)
        v = None
        for _ in range(max(int(power_iters), 1)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ (mat @ v)
        return wv / sigma

    return apply(fn, w, op_name='spectral_norm')


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    """out[b,k] = x[b] @ W[k] @ y[b] + b[k]
    (reference: fluid/layers/nn.py::bilinear_tensor_product)."""
    x, y = wrap(x), wrap(y)
    dx, dy = x.shape[-1], y.shape[-1]
    w = create_parameter([size, dx, dy], 'float32')
    b = create_parameter([size], 'float32', is_bias=True)

    def fn(xv, yv, wv, bv):
        out = jnp.einsum('bi,kij,bj->bk', xv, wv, yv) + bv
        return out

    return _apply_act(apply(fn, x, y, wrap(w), wrap(b),
                            op_name='bilinear_tensor_product'), act)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead convolution (reference: fluid/layers/nn.py::row_conv,
    Deep Speech 2): out[t] = sum_{i=0..k} W[i] * x[t+i]."""
    x = wrap(input)
    D = x.shape[-1]
    k = int(future_context_size)
    w = create_parameter([k + 1, D], 'float32')

    def fn(v, wv):
        out = jnp.zeros_like(v)
        T = v.shape[1]
        for i in range(k + 1):
            shifted = jnp.roll(v, -i, axis=1)
            valid = (jnp.arange(T) < T - i)[None, :, None]
            out = out + shifted * valid.astype(v.dtype) * wv[i]
        return out

    return _apply_act(apply(fn, x, wrap(w), op_name='row_conv'), act)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=5,
        name=None, sampler='uniform', custom_dist=None, seed=0,
        is_sparse=False):
    """Noise-contrastive estimation loss (reference:
    fluid/layers/nn.py::nce backed by the nce CUDA op).  TPU-native:
    sample `num_neg_samples` noise classes per batch with jax.random,
    one [B, 1+S] logits matmul against the gathered class rows, BCE
    with the true class positive — fully traceable, fixed shapes."""
    from ..core import rng as rng_mod
    x, lb = wrap(input), wrap(label)
    D = x.shape[-1]
    w = create_parameter([num_total_classes, D], 'float32')
    b = create_parameter([num_total_classes], 'float32', is_bias=True)
    S = int(num_neg_samples)

    def fn(v, y, wv, bv):
        # key drawn inside the traced fn (the codebase's dropout
        # pattern): eager calls re-sample, functional scopes thread it
        key = rng_mod.next_key()
        B = v.shape[0]
        y = y.reshape(B).astype(jnp.int32)
        if custom_dist is not None:
            p = jnp.asarray(np.asarray(custom_dist, 'float32'))
            neg = jax.random.categorical(
                key, jnp.log(p + 1e-20), shape=(B, S))
        elif sampler == 'log_uniform':
            u = jax.random.uniform(key, (B, S))
            neg = (jnp.exp(u * jnp.log(num_total_classes + 1.0)) - 1.0)
            neg = jnp.clip(neg.astype(jnp.int32), 0,
                           num_total_classes - 1)
        else:
            neg = jax.random.randint(key, (B, S), 0, num_total_classes)
        cls = jnp.concatenate([y[:, None], neg], axis=1)   # [B, 1+S]
        wc = wv[cls]                                       # [B,1+S,D]
        bc = bv[cls]
        logits = jnp.einsum('bd,bsd->bs', v, wc) + bc
        labels = jnp.concatenate(
            [jnp.ones((B, 1)), jnp.zeros((B, S))], axis=1)
        ls = jax.nn.log_sigmoid(logits)
        loss = -(labels * ls + (1 - labels) * (ls - logits))
        return loss.sum(axis=1, keepdims=True)

    return apply(fn, x, lb, wrap(w), wrap(b), op_name='nce')


def crf_decoding(input, transition, seq_len=None, label=None, name=None):
    """Viterbi decode (reference: fluid/layers/nn.py::crf_decoding on
    linear_chain_crf's transition layout: row 0 = start scores, row 1 =
    stop scores, rows 2.. = [N, N] transitions).  TPU-native: the
    dynamic program runs as ONE lax.scan over time — no host loop.
    input: [B, T, N] emissions, padded; seq_len: [B] or None."""
    x, tr = wrap(input), wrap(transition)
    B, T, N = x.shape
    ins = [x, tr]
    if seq_len is not None:
        ins.append(wrap(seq_len))

    def fn(em, trans, *rest):
        start, stop, A = trans[0], trans[1], trans[2:]
        lens = rest[0] if rest else jnp.full((B,), T, jnp.int32)

        def step(carry, t):
            alpha, back = carry
            # alpha: [B, N] best score ending in tag j at prev step
            scores = alpha[:, :, None] + A[None]        # [B, N, N]
            best_prev = jnp.argmax(scores, axis=1)      # [B, N]
            best = jnp.max(scores, axis=1) + em[:, t]
            live = (t < lens)[:, None]
            alpha2 = jnp.where(live, best, alpha)
            return (alpha2, best_prev), best_prev

        alpha0 = start[None] + em[:, 0]
        (alpha, _), backs = jax.lax.scan(
            step, (alpha0, jnp.zeros((B, N), jnp.int32)),
            jnp.arange(1, T))
        alpha = alpha + stop[None]
        last = jnp.argmax(alpha, axis=-1)               # [B]

        def walk(carry, t):
            # t runs T-2 .. 0; backs[t] holds best_prev for step t+1;
            # the emitted value is tag_{t+1}, the new carry is tag_t
            tag = carry
            prev = jnp.take_along_axis(backs[t], tag[:, None],
                                       axis=1)[:, 0]
            tag2 = jnp.where(t + 1 < lens, prev, tag)  # freeze padding
            return tag2, tag

        tag0, path_rev = jax.lax.scan(walk, last,
                                      jnp.arange(T - 2, -1, -1))
        # [tag_0] ++ reversed([tag_{T-1} .. tag_1]) = tags for t=0..T-1
        full = jnp.concatenate([tag0[None], jnp.flip(path_rev, axis=0)],
                               axis=0)
        return jnp.swapaxes(full, 0, 1)  # int32 tags (x64 is off)

    return apply(fn, *ins, op_name='crf_decoding')


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    """Deformable conv v2 (v1 when mask is None).  Reference:
    static/nn/common.py::deform_conv2d (deformable_conv CUDA op).
    TPU-native: bilinear sampling at offset positions expressed as 4
    static gathers per kernel tap, then one einsum over taps×channels —
    everything batched, no scalar loops.

    x: [B, Cin, H, W]; offset: [B, 2*dg*kh*kw, H, W]; mask (v2):
    [B, dg*kh*kw, H, W].  Only deformable_groups=1, groups=1 here."""
    assert groups == 1 and deformable_groups == 1, \
        'deform_conv2d: groups/deformable_groups > 1 not implemented'
    x, off = wrap(x), wrap(offset)
    kh, kw = (filter_size, filter_size) if isinstance(filter_size, int) \
        else filter_size
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    Cin = x.shape[1]
    w = create_parameter([num_filters, Cin, kh, kw], 'float32')
    b = create_parameter([num_filters], 'float32', is_bias=True)
    ins = [x, off, wrap(w), wrap(b)]
    if mask is not None:
        ins.append(wrap(mask))

    def fn(v, o, wv, bv, *m):
        B, C, H, W = v.shape
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        o = o.reshape(B, kh * kw, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - ph)[None, :, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, None, :]
        taps = []
        for i in range(kh):
            for j in range(kw):
                t = i * kw + j
                py = base_y + i * dh + o[:, t, 0]
                px = base_x + j * dw + o[:, t, 1]
                y0 = jnp.floor(py)
                x0 = jnp.floor(px)
                wy = py - y0
                wx = px - x0

                # gather per corner: v is [B,C,H,W]; advanced indexing
                # with the slice between index arrays lands [B,Ho,Wo,C]
                def gather(yy, xx):
                    yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
                    xi = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
                    inb = ((yy >= 0) & (yy <= H - 1) & (xx >= 0)
                           & (xx <= W - 1)).astype(v.dtype)
                    g = v[jnp.arange(B)[:, None, None], :, yi, xi]
                    return g * inb[..., None]

                g00 = gather(y0, x0)
                g01 = gather(y0, x0 + 1)
                g10 = gather(y0 + 1, x0)
                g11 = gather(y0 + 1, x0 + 1)
                wy_ = wy[..., None]
                wx_ = wx[..., None]
                tap = (g00 * (1 - wy_) * (1 - wx_)
                       + g01 * (1 - wy_) * wx_
                       + g10 * wy_ * (1 - wx_)
                       + g11 * wy_ * wx_)          # [B,Ho,Wo,C]
                if m:
                    tap = tap * m[0].reshape(
                        B, kh * kw, Ho, Wo)[:, t][..., None]
                taps.append(tap)
        stacked = jnp.stack(taps, axis=3)           # [B,Ho,Wo,k,C]
        out = jnp.einsum('bhwkc,okc->bohw', stacked,
                         wv.reshape(num_filters, Cin, kh * kw)
                         .transpose(0, 2, 1)) + bv[None, :, None, None]
        return out

    return apply(fn, *ins, op_name='deform_conv2d')


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op inside the compiled graph (reference:
    fluid/layers/nn.py::py_func).  TPU-native: jax.pure_callback — XLA
    calls back into the host; `out` provides the result template
    (a Tensor or (shape, dtype))."""
    xs = [wrap(v) for v in (x if isinstance(x, (list, tuple)) else [x])]

    if hasattr(out, 'shape'):
        res_shape = jax.ShapeDtypeStruct(tuple(out.shape),
                                         np.dtype(str(out.dtype)
                                                  .replace('paddle.', '')))
    else:
        shape, dtype = out
        res_shape = jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))

    def fn(*vals):
        def host(*arrs):
            r = func(*arrs)
            return np.asarray(r, res_shape.dtype)

        if backward_func is None:
            # no custom gradient: the callback is non-differentiable
            # (pure_callback has no VJP) — fine off the loss path
            return jax.pure_callback(host, res_shape, *vals)

        # backward_func(*inputs, out, out_grad) -> grad(s) w.r.t inputs
        # (the reference feeds x, out, out@GRAD to the backward op)
        @jax.custom_vjp
        def cb(*vs):
            return jax.pure_callback(host, res_shape, *vs)

        def fwd(*vs):
            y = cb(*vs)
            return y, (vs, y)

        def bwd(res, ct):
            vs, y = res
            in_shapes = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                              for v in vs)

            def bhost(ctv, yv, *arrs):
                grads = backward_func(*arrs, yv, ctv)
                if not isinstance(grads, (tuple, list)):
                    grads = [grads]
                return tuple(np.asarray(g, s.dtype)
                             for g, s in zip(grads, in_shapes))

            return jax.pure_callback(bhost, in_shapes, ct, y, *vs)

        cb.defvjp(fwd, bwd)
        return cb(*vals)

    return apply(fn, *xs, op_name='py_func')


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """SSD detection head (reference: fluid/layers/detection.py::
    multi_box_head): per feature map a loc conv (4 coords per prior), a
    conf conv (num_classes per prior) and SSD prior boxes.  Returns
    (mbox_locs [B, P, 4], mbox_confs [B, P, C], boxes [P, 4],
    variances [P, 4])."""
    n = len(inputs)
    if min_sizes is None:
        assert min_ratio is not None and max_ratio is not None
        step = int((max_ratio - min_ratio) / max(n - 2, 1))
        min_sizes, max_sizes = [], []
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n - 1]

    locs, confs, boxes, vars_ = [], [], [], []
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i]
        # priors per cell: min_size box + sqrt(min*max) box + one (two
        # when flipped) per non-1.0 aspect ratio — must equal what the
        # width/height generator below emits
        num_priors = (1 + (1 if max_sizes else 0)
                      + sum(1 for a in ar if a != 1.0)
                      * (2 if flip else 1))
        H, W = feat.shape[2], feat.shape[3]
        loc = conv2d(feat, num_priors * 4, kernel_size, stride=stride,
                     padding=pad)
        conf = conv2d(feat, num_priors * num_classes, kernel_size,
                      stride=stride, padding=pad)
        B = feat.shape[0]
        locs.append(manipulation.reshape(
            manipulation.transpose(loc, [0, 2, 3, 1]), [B, -1, 4]))
        confs.append(manipulation.reshape(
            manipulation.transpose(conf, [0, 2, 3, 1]),
            [B, -1, num_classes]))
        # prior boxes (host-side constants, like the reference's
        # prior_box op output)
        img_h = image.shape[2] or base_size
        img_w = image.shape[3] or base_size
        step_h = steps[i] if steps else img_h / H
        step_w = steps[i] if steps else img_w / W
        cy = (np.arange(H) + offset) * step_h
        cx = (np.arange(W) + offset) * step_w
        widths, heights = [], []
        smin, smax = min_sizes[i], (max_sizes[i] if max_sizes else None)
        widths.append(smin)
        heights.append(smin)
        if smax:
            s = np.sqrt(smin * smax)
            widths.append(s)
            heights.append(s)
        for a in ar:
            if a == 1.0:
                continue
            widths += [smin * np.sqrt(a)]
            heights += [smin / np.sqrt(a)]
            if flip:
                widths += [smin / np.sqrt(a)]
                heights += [smin * np.sqrt(a)]
        pw = np.asarray(widths)
        ph_ = np.asarray(heights)
        cyg, cxg = np.meshgrid(cy, cx, indexing='ij')
        bx = np.stack([
            (cxg[..., None] - pw / 2) / img_w,
            (cyg[..., None] - ph_ / 2) / img_h,
            (cxg[..., None] + pw / 2) / img_w,
            (cyg[..., None] + ph_ / 2) / img_h], axis=-1)
        bx = bx.reshape(-1, 4).astype('float32')
        if clip:
            bx = np.clip(bx, 0.0, 1.0)
        boxes.append(bx)
        vars_.append(np.tile(np.asarray([0.1, 0.1, 0.2, 0.2], 'float32'),
                             (bx.shape[0], 1)))

    from ..tensor.creation import to_tensor
    mbox_locs = manipulation.concat(locs, axis=1)
    mbox_confs = manipulation.concat(confs, axis=1)
    return (mbox_locs, mbox_confs,
            to_tensor(np.concatenate(boxes, 0)),
            to_tensor(np.concatenate(vars_, 0)))


# -- control flow (lax-backed) ----------------------------------------------

def _reject_program_variable(op, *vals):
    """The lax-backed control-flow helpers read concrete/traced values;
    a static-Program Variable has neither at build time.  Recording a
    lax.cond as a single Program op would need sub-graph capture the
    DAG doesn't model yet — reject loudly instead of crashing inside
    dy2static (reference static graphs use their own
    conditional_block/while ops)."""
    from .program import Variable
    for v in vals:
        if isinstance(v, Variable):
            raise NotImplementedError(
                f'static.nn.{op} does not support static-Program '
                'Variables yet: build the model eagerly or via '
                'jit.to_static (dy2static), where tensor control flow '
                'compiles to lax.cond/while_loop/switch')


def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    """paddle.static.nn.cond -> lax.cond (via the dy2static shim, so a
    concrete python predicate short-circuits to plain execution).
    Reference: fluid/layers/control_flow.py::cond."""
    from ..jit.dy2static import convert_ifelse
    _reject_program_variable('cond', pred)
    t = true_fn if true_fn is not None else (lambda: None)
    f = false_fn if false_fn is not None else (lambda: None)
    return convert_ifelse(pred, t, f)


def while_loop(cond_, body, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop -> lax.while_loop.
    Reference: fluid/layers/control_flow.py::while_loop."""
    from ..jit.dy2static import convert_while_loop
    _reject_program_variable('while_loop', *loop_vars)
    out = convert_while_loop(cond_, body, tuple(loop_vars))
    return list(out)


def case(pred_fn_pairs, default=None, name=None):
    """First true predicate wins (reference:
    fluid/layers/control_flow.py::case).  Lowers to a chain of
    lax.cond; concrete predicates collapse at trace time."""
    from ..jit.dy2static import convert_ifelse
    if not pred_fn_pairs:
        raise ValueError('case: pred_fn_pairs must be non-empty')
    _reject_program_variable('case', *[p for p, _ in pred_fn_pairs])

    def build(pairs):
        (p, fn) = pairs[0]
        if len(pairs) == 1:
            fallback = default if default is not None else fn
            return convert_ifelse(p, fn, fallback)
        return convert_ifelse(p, fn, lambda: build(pairs[1:]))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer dispatch -> lax.switch (reference:
    fluid/layers/control_flow.py::switch_case).  branch_fns: dict
    {index: fn} or list of (index, fn) or list of fns."""
    from ..jit.dy2static import _is_traced, _raw, _unwrap_tree, _wrap_tree
    _reject_program_variable('switch_case', branch_index)
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(i), f) for i, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    idx_of = {i: k for k, (i, _) in enumerate(items)}
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]
    n = len(fns)

    bi = _raw(branch_index)
    if not _is_traced(bi):
        return fns[idx_of[int(bi)]]() if int(bi) in idx_of else default()

    # map the runtime index onto the dense fn table; unknown -> default
    keys = jnp.asarray([i for i, _ in items])
    dense = jnp.argmax(keys == jnp.asarray(bi).astype(keys.dtype))
    known = jnp.any(keys == jnp.asarray(bi).astype(keys.dtype))
    sel = jnp.where(known, dense, n)

    branches = [(lambda f: (lambda _: _unwrap_tree(f())))(f)
                for f in fns + [default]]
    return _wrap_tree(jax.lax.switch(sel, branches, None))
