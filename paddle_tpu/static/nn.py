"""static.nn — graph-mode layer helpers.

Reference analogue: python/paddle/static/nn/common.py (fc, conv2d,
batch_norm, embedding, ...).  Each helper builds the live Layer eagerly
(parameters materialize immediately, like the reference's startup
program) and applies it to the symbolic Variable, so the op lands in the
current Program's DAG and compiles into the Executor's XLA module.
"""
import numpy as np

from .. import nn as _nn
from ..nn import functional as F
from ..tensor import manipulation

__all__ = ['fc', 'conv2d', 'conv3d', 'batch_norm', 'embedding', 'dropout',
           'layer_norm', 'prelu']


def _apply_act(x, act):
    if act is None:
        return x
    fn = getattr(F, act, None)
    if fn is None:
        raise ValueError(f'unknown activation {act!r}')
    return fn(x)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    shape = x.shape
    in_dim = int(np.prod(shape[num_flatten_dims:]))
    layer = _nn.Linear(in_dim, size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    if len(shape) > num_flatten_dims + 1:
        # flatten keeps leading dims symbolic (batch may be None/dynamic)
        x = manipulation.flatten(x, start_axis=num_flatten_dims,
                                 stop_axis=-1)
    return _apply_act(layer(x), activation)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format='NCHW', name=None):
    ch_axis = 1 if data_format == 'NCHW' else -1
    in_ch = input.shape[ch_axis]
    layer = _nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    return _apply_act(layer(input), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format='NCDHW', name=None):
    ch_axis = 1 if data_format == 'NCDHW' else -1
    in_ch = input.shape[ch_axis]
    layer = _nn.Conv3D(in_ch, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    return _apply_act(layer(input), act)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               is_test=False, name=None):
    ch_axis = 1 if data_layout == 'NCHW' else -1
    layer = _nn.BatchNorm(input.shape[ch_axis], momentum=momentum,
                          epsilon=epsilon, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_layout)
    if is_test:
        layer.eval()
    return _apply_act(layer(input), act)


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype='float32', name=None):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    return layer(input)


def dropout(x, dropout_prob=0.5, is_test=False, name=None):
    return F.dropout(x, p=dropout_prob, training=not is_test)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = list(input.shape[begin_norm_axis:])
    layer = _nn.LayerNorm(shape, epsilon=epsilon,
                          weight_attr=param_attr if scale else False,
                          bias_attr=bias_attr if shift else False)
    return _apply_act(layer(input), act)


def prelu(x, mode='all', param_attr=None, name=None):
    ch = 1 if mode == 'all' else x.shape[1]
    layer = _nn.PReLU(num_parameters=ch, weight_attr=param_attr)
    return layer(x)
