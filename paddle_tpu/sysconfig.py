"""Build-environment paths (reference python/paddle/sysconfig.py).

The reference points at its bundled C++ headers/libs for extension
builds; here the native pieces are the C++ sources under io/native (and
any future ones), compiled on demand with the system toolchain.
"""
import os

__all__ = ['get_include', 'get_lib']

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory containing native sources/headers shipped with the
    package (reference sysconfig.get_include -> paddle/include)."""
    return os.path.join(_PKG, 'io', 'native')


def get_lib():
    """Directory holding the compiled native libraries (reference
    sysconfig.get_lib -> paddle/libs)."""
    return os.path.join(_PKG, 'io', 'native')
