"""ONNX export surface.

Reference analogue: paddle.onnx.export (via paddle2onnx).  Explicit
non-goal for this TPU build (SURVEY.md §2 note): the portable export
format here is StableHLO via paddle_tpu.jit.save — it round-trips
through any XLA-compatible runtime.  export() raises with that pointer
rather than failing obscurely.
"""

__all__ = ['export']


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        'ONNX export is not supported in the TPU build; use '
        'paddle_tpu.jit.save(layer, path, input_spec=...) which writes a '
        'portable StableHLO module + params, reloadable with '
        'paddle_tpu.jit.load or any XLA-compatible runtime.')
