"""TensorArray ops — reference: python/paddle/tensor/array.py
(create_array / array_read / array_write / array_length over the
C++ LoDTensorArray).

TPU-native: in eager mode the array is a plain Python list of Tensors.
Inside traced control flow (lax.while_loop/scan), a Python list cannot
be a carry of unknown length — use a pre-sized dense Tensor with
`paddle.zeros([n, ...])` + `scatter_`/indexing instead (static shapes
are what XLA compiles); these helpers are the dygraph/compatibility
surface.
"""
from ..core.tensor import Tensor
from ._helpers import wrap

__all__ = ['create_array', 'array_read', 'array_write', 'array_length']


def _idx(i):
    import numpy as np
    import jax
    if isinstance(i, Tensor):
        i = i.value
    if isinstance(i, jax.core.Tracer):
        raise ValueError(
            'TensorArray indices must be concrete (python int or eager '
            'tensor); inside jit use a pre-sized dense tensor instead '
            '(see paddle_tpu.tensor.array docstring)')
    return int(np.asarray(i))


def create_array(dtype='float32', initialized_list=None):
    arr = []
    if initialized_list is not None:
        arr.extend(wrap(v) for v in initialized_list)
    return arr


def array_write(x, i, array=None):
    if array is None:
        array = []
    i = _idx(i)
    x = wrap(x)
    if i > len(array):
        raise IndexError(
            f'array_write index {i} past the array length {len(array)}: '
            'TensorArray grows by appending (i == length) or '
            'overwriting (i < length), like the reference '
            'LoDTensorArray')
    if i == len(array):
        array.append(x)
    else:
        array[i] = x
    return array


def array_read(array, i):
    return array[_idx(i)]


def array_length(array):
    return len(array)
