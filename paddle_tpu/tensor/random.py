"""Random sampling ops.

Reference analogue: /root/reference/python/paddle/tensor/random.py (cuRAND
Philox kernels + global generator).  TPU-native: jax.random with the
explicit global key in core/rng.py — every draw splits the key, so eager
code matches paddle's stateful-generator feel while staying reproducible.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core import rng
from ..core import dtype as _dt
from ..core.tensor import Tensor
from ..core.dtype import convert_dtype, get_default_dtype
from ._helpers import wrap, raw, normalize_shape as _shape

__all__ = [
    'rand', 'randn', 'randint', 'randint_like', 'uniform', 'normal',
    'standard_normal', 'randperm', 'bernoulli', 'multinomial', 'poisson',
    'shuffle', 'seed', 'uniform_', 'normal_', 'exponential_',
    'check_shape',
]

seed = rng.seed


def check_shape(shape, op_name='check_shape'):
    """Validate a shape argument (reference exports
    fluid.data_feeder.check_shape via tensor.random): accepts an int, a
    list/tuple of ints / 0-D int Tensors, or a 1-D int Tensor.  Raises
    TypeError on anything else.  Returns the normalized tuple."""
    try:
        return _shape(shape)
    except (TypeError, ValueError) as e:
        raise TypeError(
            f'{op_name}: invalid shape {shape!r} — expected int, '
            f'sequence of ints, or 1-D integer Tensor') from e


def rand(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor._from_value(
        jax.random.uniform(rng.next_key(), _shape(shape), d))


def randn(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor._from_value(
        jax.random.normal(rng.next_key(), _shape(shape), d))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype='int64', name=None):
    if high is None:
        low, high = 0, low
    return Tensor._from_value(
        jax.random.randint(rng.next_key(), _shape(shape), low, high,
                           convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = wrap(x)
    return randint(low, high, tuple(x.shape), dtype or x.dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor._from_value(
        jax.random.uniform(rng.next_key(), _shape(shape), d,
                           minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = raw(mean), raw(std)
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        z = jax.random.normal(rng.next_key(), shp, get_default_dtype())
        return Tensor._from_value(m + s * z)
    z = jax.random.normal(rng.next_key(), _shape(shape), get_default_dtype())
    return Tensor._from_value(mean + std * z)


def randperm(n, dtype='int64', name=None):
    return Tensor._from_value(
        jax.random.permutation(rng.next_key(), n).astype(convert_dtype(dtype)))


def bernoulli(x, name=None):
    x = wrap(x)
    return Tensor._from_value(
        jax.random.bernoulli(rng.next_key(), x.value).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = wrap(x)
    def draw(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            return jax.random.categorical(rng.next_key(), logits,
                                          shape=(num_samples,))
        # Gumbel top-k for sampling without replacement
        g = jax.random.gumbel(rng.next_key(), logits.shape)
        return jax.lax.top_k(logits + g, num_samples)[1]
    v = x.value
    if v.ndim == 1:
        out = draw(v)
    else:
        out = jnp.stack([draw(v[i]) for i in range(v.shape[0])])
    return Tensor._from_value(out.astype(_dt.int64))


def poisson(x, name=None):
    x = wrap(x)
    return Tensor._from_value(
        jax.random.poisson(rng.next_key(), x.value).astype(x.dtype))


def shuffle(x, axis=0):
    x = wrap(x)
    return Tensor._from_value(
        jax.random.permutation(rng.next_key(), x.value, axis=axis,
                               independent=False))


def uniform_(x, min=-1.0, max=1.0):
    x.set_value(jax.random.uniform(rng.next_key(), tuple(x.shape),
                                   x.dtype, minval=min, maxval=max))
    return x


def normal_(x, mean=0.0, std=1.0):
    z = jax.random.normal(rng.next_key(), tuple(x.shape), x.dtype)
    x.set_value(mean + std * z)
    return x


def exponential_(x, lam=1.0):
    z = jax.random.exponential(rng.next_key(), tuple(x.shape), x.dtype)
    x.set_value(z / lam)
    return x


def gaussian(shape, mean=0.0, std=1.0, dtype='float32', name=None):
    """reference: tensor/random.py::gaussian — normal() with the
    (shape, mean, std, dtype) calling convention."""
    dt = convert_dtype(dtype) or get_default_dtype()
    z = jax.random.normal(rng.next_key(), _shape(shape), dt)
    return Tensor._from_value(mean + std * z)


__all__ += ['gaussian']
