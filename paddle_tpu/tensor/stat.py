"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
import jax.numpy as jnp

from ..core.dispatch import apply
from ._helpers import wrap, axis_tuple

__all__ = ['mean', 'std', 'var', 'median', 'quantile', 'nanmean', 'nansum']


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.mean(v, axis=axis_tuple(axis),
                                    keepdims=keepdim), wrap(x),
                 op_name='mean')


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.std(v, axis=axis_tuple(axis),
                                   ddof=1 if unbiased else 0,
                                   keepdims=keepdim), wrap(x), op_name='std')


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda v: jnp.var(v, axis=axis_tuple(axis),
                                   ddof=1 if unbiased else 0,
                                   keepdims=keepdim), wrap(x), op_name='var')


def median(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.median(v, axis=axis_tuple(axis),
                                      keepdims=keepdim), wrap(x),
                 op_name='median')


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.quantile(v, jnp.asarray(q),
                                        axis=axis_tuple(axis),
                                        keepdims=keepdim), wrap(x),
                 op_name='quantile')


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nanmean(v, axis=axis_tuple(axis),
                                       keepdims=keepdim), wrap(x),
                 op_name='nanmean')


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nansum(v, axis=axis_tuple(axis),
                                      keepdims=keepdim), wrap(x),
                 op_name='nansum')
