"""Tensor creation ops.

Reference analogue: /root/reference/python/paddle/tensor/creation.py
(fill_constant / assign C++ kernels).  TPU-native: constants come out of
jnp (constant-folded by XLA under jit).
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.dispatch import apply
from ._helpers import wrap, raw, normalize_shape as _shape

__all__ = [
    'to_tensor', 'zeros', 'ones', 'full', 'empty', 'zeros_like', 'ones_like',
    'full_like', 'empty_like', 'arange', 'linspace', 'logspace', 'eye',
    'diag', 'diagflat', 'tril', 'triu', 'meshgrid', 'assign', 'clone',
    'create_parameter',
]


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor._from_value(jnp.zeros(_shape(shape), d))


def ones(shape, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor._from_value(jnp.ones(_shape(shape), d))


def full(shape, fill_value, dtype=None, name=None):
    # reference defaults to float32 when dtype is None, even for int fills
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor._from_value(jnp.full(_shape(shape), raw(fill_value), d))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = wrap(x)
    return Tensor._from_value(
        jnp.zeros_like(x.value, dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    x = wrap(x)
    return Tensor._from_value(
        jnp.ones_like(x.value, dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    x = wrap(x)
    return Tensor._from_value(
        jnp.full_like(x.value, raw(fill_value), dtype=convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    return Tensor._from_value(
        jnp.arange(raw(start), raw(end), raw(step), convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor._from_value(
        jnp.linspace(raw(start), raw(stop), int(num),
                     dtype=convert_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor._from_value(
        jnp.logspace(raw(start), raw(stop), int(num), base=raw(base),
                     dtype=convert_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor._from_value(jnp.eye(num_rows, num_columns, dtype=d))


def diag(x, offset=0, padding_value=0, name=None):
    x = wrap(x)
    def fn(v):
        if v.ndim == 1 and padding_value != 0:
            n = v.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, v.dtype)
            idx = jnp.arange(v.shape[0])
            r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
            return out.at[r, c].set(v)
        return jnp.diag(v, k=offset)
    return apply(fn, x, op_name='diag')


def diagflat(x, offset=0, name=None):
    x = wrap(x)
    return apply(lambda v: jnp.diagflat(v, k=offset), x, op_name='diagflat')


def tril(x, diagonal=0, name=None):
    return apply(lambda v: jnp.tril(v, k=diagonal), wrap(x), op_name='tril')


def triu(x, diagonal=0, name=None):
    return apply(lambda v: jnp.triu(v, k=diagonal), wrap(x), op_name='triu')


def meshgrid(*args, **kwargs):
    ts = [wrap(a) for a in (args[0] if len(args) == 1 and
                            isinstance(args[0], (list, tuple)) else args)]
    return apply(lambda *vs: jnp.meshgrid(*vs, indexing='ij'), *ts,
                 op_name='meshgrid')


def assign(x, output=None):
    src = wrap(x)
    if output is None:
        return src.clone()
    output.set_value(src.value)
    return output


def clone(x, name=None):
    return wrap(x).clone()


def create_parameter(shape, dtype='float32', name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from ..nn import initializer as I
    init = default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierNormal())
    value = init(_shape(shape), convert_dtype(dtype))
    return Parameter(value, name=name)
