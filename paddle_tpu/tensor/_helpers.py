"""Shared helpers for the tensor op library."""
import numpy as np
import jax.numpy as jnp

from ..core import autograd
from ..core.dispatch import apply
from ..core.tensor import Tensor


def wrap(x, dtype=None):
    """Coerce python scalars / numpy arrays / Tensors into Tensor."""
    if isinstance(x, Tensor):
        return x if dtype is None else x.astype(dtype)
    return Tensor(x, dtype=dtype)


def raw(x):
    return x.value if isinstance(x, Tensor) else x


def napply(fn, *args, op_name=None, **kwargs):
    """apply() for non-differentiable ops (int/bool outputs)."""
    with autograd.no_grad():
        out = apply(fn, *args, op_name=op_name, **kwargs)
    return out


def normalize_shape(shape):
    """Shape argument → tuple of ints; accepts int, list/tuple (possibly
    holding scalar Tensors), or a 1-D int Tensor (paddle allows all)."""
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape.value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def axis_tuple(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)
