"""Tensor op library + method patching.

Mirrors the reference's approach of patching tensor methods onto the
Tensor class at import time
(/root/reference/python/paddle/fluid/dygraph/math_op_patch.py), so the op
library lives in function modules and methods are generated.
"""
import numpy as np

from ..core.tensor import Tensor

from . import (creation, math, manipulation, linalg, logic, random,
               search, stat, array)
from . import to_string as _to_string_mod
from .creation import *      # noqa: F401,F403
from .math import *          # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *        # noqa: F401,F403
from .logic import *         # noqa: F401,F403
from .random import *        # noqa: F401,F403
from .search import *        # noqa: F401,F403
from .stat import *          # noqa: F401,F403
from .array import *         # noqa: F401,F403
from .to_string import *     # noqa: F401,F403

__all__ = (creation.__all__ + math.__all__ + manipulation.__all__ +
           linalg.__all__ + logic.__all__ + random.__all__ +
           search.__all__ + stat.__all__ + array.__all__ +
           _to_string_mod.__all__)

# stat wins over math for `mean` etc. — patch order matters (last wins),
# matching the reference where paddle.mean is the stat reduce_mean.
_METHOD_MODULES = [math, manipulation, linalg, logic, search, stat]

_SKIP_METHODS = {'is_tensor', 'meshgrid', 'einsum', 'multi_dot',
                 'broadcast_shape'}


def _patch_methods():
    for mod in _METHOD_MODULES:
        for name in mod.__all__:
            if name in _SKIP_METHODS:
                continue
            fn = getattr(mod, name)
            if callable(fn) and not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # patched names that collide with core attrs get underscored variants
    Tensor.sum = math.sum
    Tensor.abs = math.abs
    Tensor.mean = stat.mean
    Tensor.reshape = manipulation.reshape
    Tensor.astype_ = Tensor.astype


def _patch_operators():
    Tensor.__add__ = lambda s, o: math.add(s, o)
    Tensor.__radd__ = lambda s, o: math.add(o, s)
    Tensor.__sub__ = lambda s, o: math.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: math.subtract(o, s)
    Tensor.__mul__ = lambda s, o: math.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: math.multiply(o, s)
    Tensor.__truediv__ = lambda s, o: math.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: math.divide(o, s)
    Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    Tensor.__mod__ = lambda s, o: math.mod(s, o)
    Tensor.__pow__ = lambda s, o: math.pow(s, o)
    Tensor.__rpow__ = lambda s, o: math.pow(o, s)
    Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
    Tensor.__neg__ = lambda s: math.scale(s, -1.0)
    Tensor.__abs__ = lambda s: math.abs(s)
    Tensor.__eq__ = lambda s, o: logic.equal(s, o)
    Tensor.__ne__ = lambda s, o: logic.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: logic.less_than(s, o)
    Tensor.__le__ = lambda s, o: logic.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: logic.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: logic.greater_equal(s, o)
    Tensor.__invert__ = lambda s: logic.logical_not(s)
    Tensor.__and__ = lambda s, o: logic.logical_and(s, o)
    Tensor.__or__ = lambda s, o: logic.logical_or(s, o)
    Tensor.__xor__ = lambda s, o: logic.logical_xor(s, o)


_patch_methods()
_patch_operators()
