"""Tensor printing — reference: python/paddle/tensor/to_string.py
(set_printoptions + the Tensor __str__ formatter)."""
import numpy as np

__all__ = ['set_printoptions', 'to_string']

_options = {
    'precision': 8,
    'threshold': 1000,
    'edgeitems': 3,
    'linewidth': 80,
    'sci_mode': None,
}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Global print formatting for Tensors (mirrors numpy's knobs,
    which back the formatter)."""
    if precision is not None:
        _options['precision'] = int(precision)
    if threshold is not None:
        _options['threshold'] = int(threshold)
    if edgeitems is not None:
        _options['edgeitems'] = int(edgeitems)
    if linewidth is not None:
        _options['linewidth'] = int(linewidth)
    if sci_mode is not None:
        _options['sci_mode'] = bool(sci_mode)


def to_string(var, prefix='Tensor'):
    from ..core.tensor import Tensor
    v = var.value if isinstance(var, Tensor) else var
    arr = np.asarray(v)
    kw = dict(precision=_options['precision'],
              threshold=_options['threshold'],
              edgeitems=_options['edgeitems'],
              linewidth=_options['linewidth'])
    if _options['sci_mode']:
        prec = _options['precision']
        kw['formatter'] = {
            'float_kind': lambda v: np.format_float_scientific(
                v, precision=prec)}
    elif _options['sci_mode'] is not None:
        kw['suppress'] = True
    with np.printoptions(**kw):
        body = np.array2string(arr, separator=', ')
    sg = getattr(var, 'stop_gradient', True)
    return (f'{prefix}(shape={list(arr.shape)}, dtype={arr.dtype}, '
            f'stop_gradient={sg},\n       {body})')
