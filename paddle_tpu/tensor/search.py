"""Search/sort/select ops (reference: python/paddle/tensor/search.py)."""
import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as _dt
from ..core.dispatch import apply
from ._helpers import wrap, napply

__all__ = [
    'argmax', 'argmin', 'argsort', 'sort', 'topk', 'where', 'nonzero',
    'index_select', 'index_sample', 'masked_select', 'searchsorted',
    'kthvalue', 'mode',
]


def argmax(x, axis=None, keepdim=False, dtype='int64', name=None):
    def fn(v):
        out = jnp.argmax(v, axis=axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(_dt.int64)
    return napply(fn, wrap(x), op_name='argmax')


def argmin(x, axis=None, keepdim=False, dtype='int64', name=None):
    def fn(v):
        out = jnp.argmin(v, axis=axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(_dt.int64)
    return napply(fn, wrap(x), op_name='argmin')


def argsort(x, axis=-1, descending=False, name=None):
    def fn(v):
        o = jnp.argsort(v, axis=axis)
        return jnp.flip(o, axis=axis) if descending else o
    return napply(fn, wrap(x), op_name='argsort')


def sort(x, axis=-1, descending=False, name=None):
    def fn(v):
        o = jnp.sort(v, axis=axis)
        return jnp.flip(o, axis=axis) if descending else o
    return apply(fn, wrap(x), op_name='sort')


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = wrap(x)
    kk = int(k.item()) if hasattr(k, 'item') else int(k)
    def fn(v):
        ax = axis % v.ndim
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, kk)
        else:
            vals, idx = jax.lax.top_k(-vm, kk)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(_dt.int64), -1, ax))
    return apply(fn, x, op_name='topk')


def where(condition, x=None, y=None, name=None):
    cond = wrap(condition)
    if x is None and y is None:
        return nonzero(cond, as_tuple=False)
    return apply(lambda c, a, b: jnp.where(c.astype(bool), a, b),
                 cond, wrap(x), wrap(y), op_name='where')


def nonzero(x, as_tuple=False):
    # data-dependent output shape → host round-trip (same as reference's
    # CPU sync in where_index); avoid inside jit paths.
    v = np.asarray(wrap(x).value)
    nz = np.nonzero(v)
    from ..core.tensor import Tensor
    if as_tuple:
        return tuple(Tensor(np.asarray(i, dtype=np.int32)) for i in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int32))


def index_select(x, index, axis=0, name=None):
    return apply(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis),
                 wrap(x), wrap(index), op_name='index_select')


def index_sample(x, index):
    return apply(lambda v, i: jnp.take_along_axis(
        v, i.astype(jnp.int32), axis=1), wrap(x), wrap(index),
        op_name='index_sample')


def masked_select(x, mask, name=None):
    # data-dependent shape → host round-trip like nonzero
    from ..core.tensor import Tensor
    v = np.asarray(wrap(x).value)
    m = np.asarray(wrap(mask).value).astype(bool)
    return Tensor(v[np.broadcast_to(m, v.shape)])


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = 'right' if right else 'left'
    return napply(lambda s, v: jnp.searchsorted(s, v, side=side).astype(
        jnp.int32 if out_int32 else _dt.int64),
        wrap(sorted_sequence), wrap(values), op_name='searchsorted')


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = axis % v.ndim
        sv = jnp.sort(v, axis=ax)
        si = jnp.argsort(v, axis=ax)
        vals = jnp.take(sv, k - 1, axis=ax)
        idx = jnp.take(si, k - 1, axis=ax).astype(_dt.int64)
        if keepdim:
            vals, idx = jnp.expand_dims(vals, ax), jnp.expand_dims(idx, ax)
        return vals, idx
    return apply(fn, wrap(x), op_name='kthvalue')


def mode(x, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = axis % v.ndim
        sv = jnp.sort(v, axis=ax)
        n = v.shape[ax]
        sv_m = jnp.moveaxis(sv, ax, -1)
        runs = jnp.concatenate(
            [jnp.ones(sv_m.shape[:-1] + (1,), bool),
             sv_m[..., 1:] != sv_m[..., :-1]], axis=-1)
        run_id = jnp.cumsum(runs, axis=-1) - 1
        counts = jax.nn.one_hot(run_id, n, dtype=jnp.int32).sum(-2)
        best_run = jnp.argmax(counts, axis=-1)
        first_idx = jnp.argmax(run_id == best_run[..., None], axis=-1)
        vals = jnp.take_along_axis(sv_m, first_idx[..., None], -1)[..., 0]
        idx = jnp.argmax(jnp.moveaxis(v, ax, -1) == vals[..., None],
                         axis=-1).astype(_dt.int64)
        if keepdim:
            vals, idx = vals[..., None], idx[..., None]
            return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)
        return vals, idx
    return apply(fn, wrap(x), op_name='mode')
