"""Comparison + logical ops (reference: python/paddle/tensor/logic.py)."""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._helpers import wrap, napply

__all__ = [
    'equal', 'not_equal', 'greater_than', 'greater_equal', 'less_than',
    'less_equal', 'logical_and', 'logical_or', 'logical_not', 'logical_xor',
    'allclose', 'isclose', 'equal_all', 'is_empty', 'is_tensor',
    'bitwise_and', 'bitwise_or', 'bitwise_xor', 'bitwise_not',
]


def _cmp(jfn, name):
    def op(x, y, name=None):
        if np.isscalar(y):
            return napply(lambda v: jfn(v, y), wrap(x), op_name=name)
        if np.isscalar(x):
            return napply(lambda v: jfn(x, v), wrap(y), op_name=name)
        return napply(jfn, wrap(x), wrap(y), op_name=name)
    op.__name__ = name
    return op


equal = _cmp(jnp.equal, 'equal')
not_equal = _cmp(jnp.not_equal, 'not_equal')
greater_than = _cmp(jnp.greater, 'greater_than')
greater_equal = _cmp(jnp.greater_equal, 'greater_equal')
less_than = _cmp(jnp.less, 'less_than')
less_equal = _cmp(jnp.less_equal, 'less_equal')
logical_and = _cmp(jnp.logical_and, 'logical_and')
logical_or = _cmp(jnp.logical_or, 'logical_or')
logical_xor = _cmp(jnp.logical_xor, 'logical_xor')
bitwise_and = _cmp(jnp.bitwise_and, 'bitwise_and')
bitwise_or = _cmp(jnp.bitwise_or, 'bitwise_or')
bitwise_xor = _cmp(jnp.bitwise_xor, 'bitwise_xor')


def logical_not(x, out=None, name=None):
    return napply(jnp.logical_not, wrap(x), op_name='logical_not')


def bitwise_not(x, out=None, name=None):
    return napply(jnp.bitwise_not, wrap(x), op_name='bitwise_not')


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return napply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                            equal_nan=equal_nan),
                  wrap(x), wrap(y), op_name='allclose')


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return napply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                  wrap(x), wrap(y), op_name='isclose')


def equal_all(x, y, name=None):
    return napply(lambda a, b: jnp.array_equal(a, b), wrap(x), wrap(y),
                  op_name='equal_all')


def is_empty(x, name=None):
    return Tensor(np.asarray(wrap(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
