"""Elementwise + reduction math ops.

Reference analogue: /root/reference/python/paddle/tensor/math.py backed by
paddle/fluid/operators/elementwise/* and reduce_ops/*.  TPU-native: thin
jnp lambdas through the dispatch choke point; XLA fuses chains of these
into single HBM-friendly kernels, so there is no need for the reference's
hand-fused kernels.
"""
import numpy as np
import jax.numpy as jnp
import jax

from ..core.dispatch import apply
from ._helpers import wrap, raw, napply, axis_tuple

__all__ = [
    'add', 'subtract', 'multiply', 'divide', 'floor_divide', 'mod',
    'remainder', 'pow', 'float_power', 'maximum', 'minimum', 'fmax', 'fmin',
    'exp', 'expm1', 'log', 'log2', 'log10', 'log1p', 'sqrt', 'rsqrt',
    'square', 'abs', 'sign', 'sin', 'cos', 'tan', 'asin', 'acos', 'atan',
    'atan2', 'sinh', 'cosh', 'tanh', 'asinh', 'acosh', 'atanh', 'reciprocal',
    'floor', 'ceil', 'round', 'trunc', 'clip', 'sum', 'prod', 'max', 'min',
    'amax', 'amin', 'cumsum', 'cumprod', 'logsumexp', 'logit', 'erf',
    'erfinv', 'multiply_', 'addmm', 'inner', 'outer', 'kron', 'isfinite',
    'isinf', 'isnan', 'nan_to_num', 'lerp', 'scale', 'increment', 'all',
    'any', 'heaviside', 'frac', 'rad2deg', 'deg2rad', 'gcd', 'lcm', 'diff',
    'angle', 'count_nonzero', 'sgn', 'take', 'digamma', 'lgamma',
    'floor_mod', 'stanh', 'multiplex',
]


def _binary(jfn, name):
    # python scalars stay in the closure → jnp weak typing applies, so
    # `f32_tensor + 2.5` stays float32 (the reference promotes the same way).
    def op(x, y, name=None):
        xs, ys = np.isscalar(x), np.isscalar(y)
        if ys and not xs:
            return apply(lambda v: jfn(v, y), wrap(x), op_name=name)
        if xs and not ys:
            return apply(lambda v: jfn(x, v), wrap(y), op_name=name)
        return apply(jfn, wrap(x), wrap(y), op_name=name)
    op.__name__ = name
    return op


def _unary(jfn, name):
    def op(x, name=None):
        return apply(jfn, wrap(x), op_name=name)
    op.__name__ = name
    return op


def _nunary(jfn, name):
    def op(x, name=None):
        return napply(jfn, wrap(x), op_name=name)
    op.__name__ = name
    return op


add = _binary(jnp.add, 'add')
subtract = _binary(jnp.subtract, 'subtract')
multiply = _binary(jnp.multiply, 'multiply')
divide = _binary(jnp.divide, 'divide')
floor_divide = _binary(jnp.floor_divide, 'floor_divide')
mod = _binary(jnp.mod, 'mod')
remainder = mod
floor_mod = mod
maximum = _binary(jnp.maximum, 'maximum')
minimum = _binary(jnp.minimum, 'minimum')
fmax = _binary(jnp.fmax, 'fmax')
fmin = _binary(jnp.fmin, 'fmin')
atan2 = _binary(jnp.arctan2, 'atan2')
heaviside = _binary(jnp.heaviside, 'heaviside')
gcd = _binary(jnp.gcd, 'gcd')
lcm = _binary(jnp.lcm, 'lcm')


def pow(x, y, name=None):
    x = wrap(x)
    if np.isscalar(y):
        return apply(lambda v: jnp.power(v, y), x, op_name='pow')
    return apply(jnp.power, x, wrap(y), op_name='pow')


float_power = pow

exp = _unary(jnp.exp, 'exp')
expm1 = _unary(jnp.expm1, 'expm1')
log = _unary(jnp.log, 'log')
log2 = _unary(jnp.log2, 'log2')
log10 = _unary(jnp.log10, 'log10')
log1p = _unary(jnp.log1p, 'log1p')
sqrt = _unary(jnp.sqrt, 'sqrt')
rsqrt = _unary(jax.lax.rsqrt, 'rsqrt')
square = _unary(jnp.square, 'square')
abs = _unary(jnp.abs, 'abs')
sign = _unary(jnp.sign, 'sign')
sgn = sign
sin = _unary(jnp.sin, 'sin')
cos = _unary(jnp.cos, 'cos')
tan = _unary(jnp.tan, 'tan')
asin = _unary(jnp.arcsin, 'asin')
acos = _unary(jnp.arccos, 'acos')
atan = _unary(jnp.arctan, 'atan')
sinh = _unary(jnp.sinh, 'sinh')
cosh = _unary(jnp.cosh, 'cosh')
tanh = _unary(jnp.tanh, 'tanh')
asinh = _unary(jnp.arcsinh, 'asinh')
acosh = _unary(jnp.arccosh, 'acosh')
atanh = _unary(jnp.arctanh, 'atanh')
reciprocal = _unary(jnp.reciprocal, 'reciprocal')
floor = _unary(jnp.floor, 'floor')
ceil = _unary(jnp.ceil, 'ceil')
# paddle rounds half AWAY FROM ZERO; jnp.round is half-to-even
round = _unary(lambda v: jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5), 'round')
trunc = _unary(jnp.trunc, 'trunc')
erf = _unary(jax.scipy.special.erf, 'erf')
erfinv = _unary(jax.scipy.special.erfinv, 'erfinv')
digamma = _unary(jax.scipy.special.digamma, 'digamma')
lgamma = _unary(jax.scipy.special.gammaln, 'lgamma')
frac = _unary(lambda v: v - jnp.trunc(v), 'frac')
rad2deg = _unary(jnp.rad2deg, 'rad2deg')
deg2rad = _unary(jnp.deg2rad, 'deg2rad')
angle = _unary(jnp.angle, 'angle')
isfinite = _nunary(jnp.isfinite, 'isfinite')
isinf = _nunary(jnp.isinf, 'isinf')
isnan = _nunary(jnp.isnan, 'isnan')


def logit(x, eps=None, name=None):
    def fn(v):
        u = jnp.clip(v, eps, 1 - eps) if eps is not None else v
        return jnp.log(u / (1 - u))
    return apply(fn, wrap(x), op_name='logit')


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                          neginf=neginf), wrap(x),
                 op_name='nan_to_num')


def clip(x, min=None, max=None, name=None):
    return apply(lambda v: jnp.clip(v, raw(min), raw(max)), wrap(x),
                 op_name='clip')


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core.dtype import convert_dtype
    return apply(lambda v: jnp.sum(v, axis=axis_tuple(axis),
                                   dtype=convert_dtype(dtype),
                                   keepdims=keepdim), wrap(x), op_name='sum')


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    from ..core.dtype import convert_dtype
    return apply(lambda v: jnp.prod(v, axis=axis_tuple(axis),
                                    dtype=convert_dtype(dtype),
                                    keepdims=keepdim), wrap(x),
                 op_name='prod')


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.max(v, axis=axis_tuple(axis),
                                   keepdims=keepdim), wrap(x), op_name='max')


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.min(v, axis=axis_tuple(axis),
                                   keepdims=keepdim), wrap(x), op_name='min')


amax = max
amin = min


def all(x, axis=None, keepdim=False, name=None):
    return napply(lambda v: jnp.all(v, axis=axis_tuple(axis),
                                    keepdims=keepdim), wrap(x), op_name='all')


def any(x, axis=None, keepdim=False, name=None):
    return napply(lambda v: jnp.any(v, axis=axis_tuple(axis),
                                    keepdims=keepdim), wrap(x), op_name='any')


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return napply(lambda v: jnp.count_nonzero(v, axis=axis_tuple(axis),
                                              keepdims=keepdim), wrap(x),
                  op_name='count_nonzero')


def cumsum(x, axis=None, dtype=None, name=None):
    def fn(v):
        return jnp.cumsum(v.ravel() if axis is None else v,
                          axis=None if axis is None else int(axis))
    return apply(fn, wrap(x), op_name='cumsum')


def cumprod(x, dim=None, dtype=None, name=None):
    return apply(lambda v: jnp.cumprod(v, axis=dim), wrap(x),
                 op_name='cumprod')


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jax.scipy.special.logsumexp(
        v, axis=axis_tuple(axis), keepdims=keepdim), wrap(x),
        op_name='logsumexp')


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * (a @ b),
                 wrap(input), wrap(x), wrap(y), op_name='addmm')


def inner(x, y, name=None):
    return apply(jnp.inner, wrap(x), wrap(y), op_name='inner')


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), wrap(x), wrap(y),
                 op_name='outer')


def kron(x, y, name=None):
    return apply(jnp.kron, wrap(x), wrap(y), op_name='kron')


def lerp(x, y, weight, name=None):
    if np.isscalar(weight):
        return apply(lambda a, b: a + weight * (b - a), wrap(x), wrap(y),
                     op_name='lerp')
    return apply(lambda a, b, w: a + w * (b - a), wrap(x), wrap(y),
                 wrap(weight), op_name='lerp')


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = raw(scale), raw(bias)
    def fn(v):
        return v * s + b if bias_after_scale else (v + b) * s
    return apply(fn, wrap(x), op_name='scale')


def increment(x, value=1.0, name=None):
    if hasattr(x, '_snapshot'):
        x._replace(apply(lambda v: v + value, x._snapshot(),
                         op_name='increment'))
        return x
    return apply(lambda v: v + value, wrap(x), op_name='increment')


def multiply_(x, y):
    x._replace(multiply(x._snapshot(), y))
    return x


def diff(x, n=1, axis=-1, name=None):
    return apply(lambda v: jnp.diff(v, n=n, axis=axis), wrap(x),
                 op_name='diff')


def take(x, index, mode='raise', name=None):
    return apply(lambda v, i: jnp.take(v.ravel(), i.ravel(), mode=mode)
                 .reshape(i.shape), wrap(x), wrap(index), op_name='take')


# -- reference long-tail: in-place variants, complex parts, misc -------------
# (python/paddle/tensor/math.py — the trailing-underscore ops mutate in
# place but keep the tape edge via _snapshot/_replace, like multiply_)

def add_(x, y, name=None):
    x._replace(add(x._snapshot(), y))
    return x


def subtract_(x, y, name=None):
    x._replace(subtract(x._snapshot(), y))
    return x


def clip_(x, min=None, max=None, name=None):
    x._replace(clip(x._snapshot(), min=min, max=max))
    return x


_scale_fn = scale


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
           name=None):
    x._replace(_scale_fn(x._snapshot(), scale=scale, bias=bias,
                         bias_after_scale=bias_after_scale, act=act))
    return x


def tanh_(x, name=None):
    x._replace(tanh(x._snapshot()))
    return x


def exp_(x, name=None):
    x._replace(exp(x._snapshot()))
    return x


def ceil_(x, name=None):
    x._replace(ceil(x._snapshot()))
    return x


def floor_(x, name=None):
    x._replace(floor(x._snapshot()))
    return x


def reciprocal_(x, name=None):
    x._replace(reciprocal(x._snapshot()))
    return x


def round_(x, name=None):
    x._replace(round(x._snapshot()))
    return x


def rsqrt_(x, name=None):
    x._replace(rsqrt(x._snapshot()))
    return x


def sqrt_(x, name=None):
    x._replace(sqrt(x._snapshot()))
    return x


def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (reference: sum op add_n)."""
    if not isinstance(inputs, (list, tuple)):
        return wrap(inputs).clone()
    out = wrap(inputs[0])
    for t in inputs[1:]:
        out = add(out, t)
    return out


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.trace(v, offset=offset, axis1=axis1,
                                     axis2=axis2), wrap(x),
                 op_name='trace')


def conj(x, name=None):
    return apply(jnp.conj, wrap(x), op_name='conj')


def real(x, name=None):
    return apply(jnp.real, wrap(x), op_name='real')


def imag(x, name=None):
    return apply(jnp.imag, wrap(x), op_name='imag')


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    """Scaled tanh: scale_b * tanh(scale_a * x) (reference
    fluid.layers.nn.stanh → paddle.stanh)."""
    return apply(lambda v: scale_b * jnp.tanh(scale_a * v), wrap(x),
                 op_name='stanh')


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors: out[i] = inputs[index[i]][i]
    (reference fluid.layers.nn.multiplex → paddle.multiplex)."""
    def fn(i, *vs):
        stacked = jnp.stack(vs, axis=0)
        sel = i.reshape(-1).astype(jnp.int32)
        return stacked[sel, jnp.arange(stacked.shape[1])]
    return apply(fn, wrap(index), *[wrap(v) for v in inputs],
                 op_name='multiplex')


def broadcast_shape(x_shape, y_shape):
    """Pure shape computation (no tensors)."""
    import numpy as _np
    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


__all__ += ['add_', 'subtract_', 'clip_', 'scale_', 'tanh_', 'add_n',
            'trace', 'conj', 'real', 'imag', 'broadcast_shape',
            'exp_', 'ceil_', 'floor_', 'reciprocal_', 'round_',
            'rsqrt_', 'sqrt_']
# NOTE: reference tensor_method_func also lists 'mul', but its binder
# (fluid/dygraph/math_op_patch.py:331) getattr-skips names missing from
# paddle.tensor — 'mul' is one, so reference Tensor has NO mul method;
# the only real 1.x mul (flatten-matmul) lives in fluid.layers.mul.
