"""Linear algebra ops.

Reference analogue: /root/reference/python/paddle/tensor/linalg.py (matmul
→ cuBLAS in the reference).  TPU-native: jnp.matmul/einsum lower straight
onto the MXU; bf16 inputs with fp32 accumulation is XLA's default contract.
"""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply
from ._helpers import wrap, raw, napply

__all__ = [
    'matmul', 'mm', 'bmm', 'dot', 'mv', 't', 'norm', 'dist', 'cross',
    'cholesky', 'matrix_power', 'histogram', 'einsum', 'inv', 'det',
    'slogdet', 'svd', 'solve', 'qr', 'eigh', 'pinv', 'multi_dot',
    'triangular_solve', 'cond', 'matrix_rank',
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply(fn, wrap(x), wrap(y), op_name='matmul')


mm = matmul


def bmm(x, y, name=None):
    return apply(jnp.matmul, wrap(x), wrap(y), op_name='bmm')


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), wrap(x), wrap(y),
                 op_name='dot')


def mv(x, vec, name=None):
    return apply(jnp.matmul, wrap(x), wrap(vec), op_name='mv')


def t(input, name=None):
    x = wrap(input)
    if x.ndim > 2:
        raise ValueError(
            "paddle.t only supports tensors of rank <= 2; use transpose")
    return apply(lambda v: v.T if v.ndim == 2 else v, x, op_name='t')


def norm(x, p='fro', axis=None, keepdim=False, name=None):
    def fn(v):
        if p == 'fro' and axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(v)))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == 'fro':
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=ax,
                                    keepdims=keepdim))
        if p == np.inf:
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax,
                           keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=ax,
                                 keepdims=keepdim), 1.0 / p)
    return apply(fn, wrap(x), op_name='norm')


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = a - b
        if p == np.inf:
            return jnp.max(jnp.abs(d))
        if p == -np.inf:
            return jnp.min(jnp.abs(d))
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype))
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)
    return apply(fn, wrap(x), wrap(y), op_name='dist')


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None
    def fn(a, b):
        if ax is None:
            # paddle default: first axis with dim 3
            for i, s in enumerate(a.shape):
                if s == 3:
                    return jnp.cross(a, b, axis=i)
            raise ValueError("no axis of size 3 for cross")
        return jnp.cross(a, b, axis=ax)
    return apply(fn, wrap(x), wrap(y), op_name='cross')


def cholesky(x, upper=False, name=None):
    def fn(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply(fn, wrap(x), op_name='cholesky')


def matrix_power(x, n, name=None):
    return apply(lambda v: jnp.linalg.matrix_power(v, n), wrap(x),
                 op_name='matrix_power')


def histogram(input, bins=100, min=0, max=0, name=None):
    def fn(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
        h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
        return h
    return napply(fn, wrap(input), op_name='histogram')


def einsum(equation, *operands):
    ts = [wrap(o) for o in operands]
    return apply(lambda *vs: jnp.einsum(equation, *vs), *ts,
                 op_name='einsum')


def inv(x, name=None):
    return apply(jnp.linalg.inv, wrap(x), op_name='inv')


def det(x, name=None):
    return apply(jnp.linalg.det, wrap(x), op_name='det')


def slogdet(x, name=None):
    return apply(lambda v: tuple(jnp.linalg.slogdet(v)), wrap(x),
                 op_name='slogdet')


def svd(x, full_matrices=False, name=None):
    return apply(lambda v: tuple(jnp.linalg.svd(
        v, full_matrices=full_matrices)), wrap(x), op_name='svd')


def qr(x, mode='reduced', name=None):
    return apply(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), wrap(x),
                 op_name='qr')


def eigh(x, UPLO='L', name=None):
    return apply(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), wrap(x),
                 op_name='eigh')


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda v: jnp.linalg.pinv(v, rcond=rcond,
                                           hermitian=hermitian), wrap(x),
                 op_name='pinv')


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, wrap(x), wrap(y), op_name='solve')


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax.scipy.linalg as jsl
    def fn(a, b):
        return jsl.solve_triangular(a, b, lower=not upper, trans=int(transpose),
                                    unit_diagonal=unitriangular)
    return apply(fn, wrap(x), wrap(y), op_name='triangular_solve')


def multi_dot(x, name=None):
    ts = [wrap(t_) for t_ in x]
    return apply(lambda *vs: jnp.linalg.multi_dot(vs), *ts,
                 op_name='multi_dot')


def cond(x, p=None, name=None):
    return apply(lambda v: jnp.linalg.cond(v, p=p), wrap(x), op_name='cond')


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return napply(lambda v: jnp.linalg.matrix_rank(v, tol=tol), wrap(x),
                  op_name='matrix_rank')


def inverse(x, name=None):
    """Matrix inverse (reference: tensor/math.py::inverse); alias of
    linalg.inv with batched support from jnp."""
    return inv(x)


__all__ += ['inverse']
