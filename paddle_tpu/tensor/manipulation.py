"""Shape / layout manipulation ops.

Reference analogue: /root/reference/python/paddle/tensor/manipulation.py.
TPU-native note: reshape/transpose/slice are free-ish metadata ops under
XLA; gather/scatter lower to lax.gather/scatter which tile onto the VPU.
"""
import builtins

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ._helpers import wrap, raw, napply, normalize_shape as _resolve_shape

__all__ = [
    'reshape', 'flatten', 'transpose', 'concat', 'split', 'chunk', 'stack',
    'unstack', 'squeeze', 'unsqueeze', 'expand', 'expand_as', 'tile',
    'broadcast_to', 'flip', 'roll', 'gather', 'gather_nd', 'scatter',
    'scatter_nd_add', 'unbind', 'unique', 'moveaxis', 'repeat_interleave',
    'take_along_axis', 'put_along_axis', 'numel', 'cast', 'slice',
    'strided_slice', 'rot90', 'as_strided', 'view', 'tolist',
    'tensordot', 'atleast_1d', 'atleast_2d', 'atleast_3d',
    'reverse', 'crop', 'scatter_nd', 'shard_index', 'shape', 'rank',
]





def reshape(x, shape, name=None):
    shape = _resolve_shape(shape)
    return apply(lambda v: jnp.reshape(v, shape), wrap(x), op_name='reshape')


def view(x, shape_or_dtype, name=None):
    return reshape(x, shape_or_dtype)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = wrap(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    so = stop_axis % nd if nd else 0
    def fn(v):
        shp = v.shape[:sa] + (-1,) + v.shape[so + 1:]
        return jnp.reshape(v, shp)
    return apply(fn, x, op_name='flatten')


def transpose(x, perm, name=None):
    perm = [int(p) for p in perm]
    return apply(lambda v: jnp.transpose(v, perm), wrap(x),
                 op_name='transpose')


def moveaxis(x, source, destination, name=None):
    return apply(lambda v: jnp.moveaxis(v, source, destination), wrap(x),
                 op_name='moveaxis')


def concat(x, axis=0, name=None):
    ts = [wrap(t) for t in x]
    axis = int(raw(axis)) if not isinstance(axis, int) else axis
    return apply(lambda *vs: jnp.concatenate(vs, axis=axis), *ts,
                 op_name='concat')


def stack(x, axis=0, name=None):
    ts = [wrap(t) for t in x]
    return apply(lambda *vs: jnp.stack(vs, axis=axis), *ts, op_name='stack')


def split(x, num_or_sections, axis=0, name=None):
    x = wrap(x)
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis {axis} size {dim} is not divisible by "
                f"{num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) if not isinstance(s, Tensor) else int(s.item())
                 for s in num_or_sections]
        n_unknown = sum(1 for s in sizes if s < 0)
        if n_unknown:
            known = sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1])
    def fn2(v):
        outs = []
        for o, s in zip(offsets, sizes):
            idx = [np.s_[:]] * v.ndim
            idx[axis] = np.s_[o:o + s]
            outs.append(v[tuple(idx)])
        return tuple(outs)
    return list(apply(fn2, x, op_name='split'))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unstack(x, axis=0, num=None, name=None):
    x = wrap(x)
    n = num or x.shape[axis]
    def fn(v):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(v, n, axis=axis))
    return list(apply(fn, x, op_name='unstack'))


def unbind(input, axis=0):
    return unstack(input, axis)


def squeeze(x, axis=None, name=None):
    x = wrap(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return apply(lambda v: jnp.squeeze(v, axis=ax), x, op_name='squeeze')


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a) if not isinstance(a, Tensor) else int(a.item())
            for a in axes]
    def fn(v):
        for a in sorted(axes):
            v = jnp.expand_dims(v, a)
        return v
    return apply(fn, wrap(x), op_name='unsqueeze')


def expand(x, shape, name=None):
    shape = _resolve_shape(shape)
    x = wrap(x)
    def fn(v):
        tgt = list(shape)
        off = len(tgt) - v.ndim
        for i in range(v.ndim):
            if tgt[off + i] == -1:
                tgt[off + i] = v.shape[i]
        return jnp.broadcast_to(v, tuple(tgt))
    return apply(fn, x, op_name='expand')


def expand_as(x, y, name=None):
    return expand(x, wrap(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def tile(x, repeat_times, name=None):
    reps = _resolve_shape(repeat_times)
    return apply(lambda v: jnp.tile(v, reps), wrap(x), op_name='tile')


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply(lambda v: jnp.flip(v, axis=tuple(axes)), wrap(x),
                 op_name='flip')


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), wrap(x),
                 op_name='rot90')


def roll(x, shifts, axis=None, name=None):
    return apply(lambda v: jnp.roll(v, shifts, axis=axis), wrap(x),
                 op_name='roll')


def gather(x, index, axis=0, name=None):
    axis = int(raw(axis)) if not isinstance(axis, int) else axis
    return apply(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis),
                 wrap(x), wrap(index), op_name='gather')


def gather_nd(x, index, name=None):
    return apply(
        lambda v, i: v[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))],
        wrap(x), wrap(index), op_name='gather_nd')


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        i = i.astype(jnp.int32)
        if overwrite:
            return v.at[i].set(u)
        return v.at[i].add(u)
    return apply(fn, wrap(x), wrap(index), wrap(updates), op_name='scatter')


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, i, u):
        return v.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))].add(u)
    return apply(fn, wrap(x), wrap(index), wrap(updates),
                 op_name='scatter_nd_add')


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype='int64', name=None):
    x = wrap(x)
    res = napply(
        lambda v: jnp.unique(v, return_index=return_index,
                             return_inverse=return_inverse,
                             return_counts=return_counts, axis=axis),
        x, op_name='unique')
    return res


def repeat_interleave(x, repeats, axis=None, name=None):
    r = raw(repeats)
    return apply(lambda v: jnp.repeat(v, r, axis=axis), wrap(x),
                 op_name='repeat_interleave')


def take_along_axis(arr, indices, axis, broadcast=True):
    return apply(lambda v, i: jnp.take_along_axis(v, i.astype(jnp.int32),
                                                  axis=axis),
                 wrap(arr), wrap(indices), op_name='take_along_axis')


def put_along_axis(arr, indices, values, axis, reduce='assign'):
    def fn(v, i, u):
        i = i.astype(jnp.int32)
        u = jnp.broadcast_to(u, i.shape).astype(v.dtype)
        idx = [jnp.arange(s).reshape([-1 if k == d else 1
                                      for k in range(i.ndim)])
               for d, s in enumerate(i.shape)]
        idx[axis] = i
        if reduce == 'add':
            return v.at[tuple(idx)].add(u)
        return v.at[tuple(idx)].set(u)
    return apply(fn, wrap(arr), wrap(indices), wrap(values),
                 op_name='put_along_axis')


def numel(x, name=None):
    return Tensor(np.int32(wrap(x).size))


def cast(x, dtype):
    return wrap(x).astype(dtype)


def slice(input, axes, starts, ends):
    x = wrap(input)
    def fn(v):
        idx = [np.s_[:]] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            s = int(raw(s)) if not isinstance(s, int) else s
            e = int(raw(e)) if not isinstance(e, int) else e
            idx[a] = np.s_[s:e]
        return v[tuple(idx)]
    return apply(fn, x, op_name='slice')


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = wrap(x)
    def fn(v):
        idx = [np.s_[:]] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = np.s_[s:e:st]
        return v[tuple(idx)]
    return apply(fn, x, op_name='strided_slice')


def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError(
        "as_strided has no XLA analogue; use reshape/slice/gather")


def tolist(x):
    return wrap(x).tolist()


def tensordot(x, y, axes=2, name=None):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes), wrap(x),
                 wrap(y), op_name='tensordot')


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, wrap(t), op_name='atleast_1d')
            for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, wrap(t), op_name='atleast_2d')
            for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, wrap(t), op_name='atleast_3d')
            for t in inputs]
    return outs[0] if len(outs) == 1 else outs


# -- reference long-tail: in-place view variants -----------------------------
# (python/paddle/tensor/manipulation.py — trailing-underscore ops; the
# tape edge survives via _snapshot/_replace)

def reverse(x, axis, name=None):
    """Alias of flip (reference fluid.layers.reverse → paddle.reverse)."""
    return flip(x, axis)


def crop(x, shape=None, offsets=None, name=None):
    """Crop x to `shape` starting at `offsets` (reference
    fluid.layers.crop_tensor, exported as paddle.crop). -1 in shape keeps
    everything from the offset to the end of that dim."""
    x = wrap(x)
    nd = x.ndim
    in_shape = x.shape
    offs = [0] * nd if offsets is None else list(_resolve_shape(offsets))
    out = (list(in_shape) if shape is None else list(_resolve_shape(shape)))
    sizes = [in_shape[d] - offs[d] if out[d] == -1 else out[d]
             for d in range(nd)]
    idx = tuple(builtins.slice(o, o + s) for o, s in zip(offs, sizes))
    return apply(lambda v: v[idx], x, op_name='crop')


def scatter_nd(index, updates, shape, name=None):
    """Zeros of `shape` with `updates` scatter-ADDED at `index` (duplicate
    indices sum — reference fluid.layers.nn.scatter_nd semantics)."""
    shp = _resolve_shape(shape)

    def fn(i, u):
        zeros = jnp.zeros(shp, u.dtype)
        return zeros.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))] \
            .add(u)
    return apply(fn, wrap(index), wrap(updates), op_name='scatter_nd')


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Recompute label indices for the shard_id-th of nshards vocab shards
    (reference fluid.layers.nn.shard_index): ids belonging to this shard
    map to their local offset, others to ignore_value.  Pairs with
    VocabParallelEmbedding / ParallelCrossEntropy on the tp axis."""
    if not 0 <= shard_id < nshards:
        raise ValueError(
            f'shard_id {shard_id} out of range for nshards={nshards}')
    size = (int(index_num) + int(nshards) - 1) // int(nshards)

    def fn(v):
        local = v - shard_id * size
        in_shard = (v // size) == shard_id
        return jnp.where(in_shard, local,
                         jnp.asarray(ignore_value, v.dtype))
    return napply(fn, wrap(input), op_name='shard_index')


def shape(input, name=None):
    """Runtime shape of `input` as a 1-D int32 Tensor (reference
    tensor/attribute.py: paddle.shape).  Recorded as an op so static
    Programs report the RUN-time feed shape, not the build-time
    template (dynamic batch dims would otherwise read as 1)."""
    return napply(lambda v: jnp.asarray(jnp.shape(v), jnp.int32),
                  wrap(input), op_name='shape')


def rank(input, name=None):
    """Number of dimensions as a 0-D int32 Tensor (paddle.rank)."""
    return Tensor(np.asarray(wrap(input).ndim, np.int32))


def reshape_(x, shape, name=None):
    x._replace(reshape(x._snapshot(), shape))
    return x


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    x._replace(flatten(x._snapshot(), start_axis, stop_axis))
    return x


def squeeze_(x, axis=None, name=None):
    x._replace(squeeze(x._snapshot(), axis))
    return x


def unsqueeze_(x, axis, name=None):
    x._replace(unsqueeze(x._snapshot(), axis))
    return x


def scatter_(x, index, updates, overwrite=True, name=None):
    x._replace(scatter(x._snapshot(), index, updates,
                       overwrite=overwrite))
    return x


__all__ += ['reshape_', 'flatten_', 'squeeze_', 'unsqueeze_', 'scatter_']
