"""paddle.save / paddle.load.

Reference analogue: /root/reference/python/paddle/framework/io.py, which
pickles a dict of LoDTensor→numpy.  Same wire idea here: Tensors are
converted to numpy on save (device→host once, async-friendly), and load
returns numpy arrays — `set_state_dict` re-uploads to HBM lazily on
first use.  Checkpoint-at-scale (async, sharded) lives in
paddle_tpu.hapi.checkpoint (orbax-backed).
"""
import os
import pickle

import numpy as np

from ..core.tensor import Tensor

__all__ = ['save', 'load']

_PROTO = 4


def _to_host(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.value)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    try:
        import jax
        if isinstance(obj, jax.Array):
            return np.asarray(obj)
    except ImportError:
        pass
    return obj


def save(obj, path, protocol=_PROTO, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, 'wb') as f:
        pickle.dump(_to_host(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, 'rb') as f:
        return pickle.load(f)
