"""paddle_tpu.framework — save/load and misc framework-level helpers.

Reference analogue: /root/reference/python/paddle/framework/ (io.py,
random.py, framework.py).
"""
from .io import save, load  # noqa: F401
from ..core.rng import seed, get_seed  # noqa: F401
from ..core.rng import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401
from ..core.autograd import grad, set_grad_enabled  # noqa: F401

__all__ = ['save', 'load', 'seed', 'get_seed', 'grad', 'set_grad_enabled',
           'get_cuda_rng_state', 'set_cuda_rng_state', 'ComplexTensor']


def ComplexTensor(real, imag=None):
    """Legacy complex constructor (reference exported paddle.ComplexTensor
    from the fluid C++ core).  Complex dtypes are native to the Tensor
    here, so this just pairs real/imag into one complex64 Tensor; read
    parts back via .real() / .imag()."""
    import numpy as np
    from ..core.tensor import Tensor
    r = np.asarray(real, dtype=np.float32)
    i = np.zeros_like(r) if imag is None else np.asarray(imag,
                                                         dtype=np.float32)
    if i.shape != r.shape:
        raise ValueError(f'real/imag shape mismatch: {r.shape} vs {i.shape}')
    return Tensor((r + 1j * i).astype(np.complex64))
