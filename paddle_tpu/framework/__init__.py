"""paddle_tpu.framework — save/load and misc framework-level helpers.

Reference analogue: /root/reference/python/paddle/framework/ (io.py,
random.py, framework.py).
"""
from .io import save, load  # noqa: F401
from ..core.rng import seed, get_seed  # noqa: F401

__all__ = ['save', 'load', 'seed', 'get_seed']
