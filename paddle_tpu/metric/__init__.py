"""Metrics: Accuracy / Precision / Recall / Auc.

Reference analogue: python/paddle/metric/metrics.py (Metric, Accuracy,
Precision, Recall, Auc, paddle.metric.accuracy).

Jit-safe state discipline (SURVEY §2#21): `compute` runs INSIDE the
compiled eval step and reduces the batch to a tiny statistic array
(correct-counts, tp/fp, AUC histogram buckets); `update` adds that
statistic into a device-resident jnp state with NO host readback —
lazy device ops only, so `hapi.Model.evaluate` performs zero
device→host syncs per batch (each one is a ~100 ms round trip through
the TPU tunnel).  The only host sync is `accumulate()` at the end of
evaluation.  The legacy eager signatures (`update(preds, labels)`
with raw predictions) still work and route through the same compute.
"""
import abc

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ['Metric', 'Accuracy', 'Precision', 'Recall', 'Auc', 'accuracy']


def _to_np(x):
    if isinstance(x, Tensor):
        return np.asarray(x.value)
    return np.asarray(x)


def _to_jnp(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(x)


class _LongCounter:
    """Device-resident EXACT integer accumulator for streaming metric
    states: two int32 limbs (`hi` in units of 2^16), with the carry
    fold every `_FOLD_EVERY` adds done ON DEVICE — `add` is always a
    lazy jnp op, never a host sync, and the representable total
    (~1.4e14 per element) outlives any eval stream.  (A single f32
    state saturates at 2^24 and a single int32 wraps at 2^31; the one
    host sync is `read()` at accumulate time.)"""

    _FOLD_EVERY = 1024

    def __init__(self, shape):
        self.lo = jnp.zeros(shape, jnp.int32)
        self.hi = jnp.zeros(shape, jnp.int32)
        self._adds = 0

    def add(self, x):
        self.lo = self.lo + x.astype(jnp.int32)
        self._adds += 1
        if self._adds >= self._FOLD_EVERY:
            carry = self.lo >> 16          # still lazy device math
            self.hi = self.hi + carry
            self.lo = self.lo - (carry << 16)
            self._adds = 0

    def read(self):
        """Host int64 totals — the single device→host sync."""
        return ((np.asarray(self.hi).astype(np.int64) << 16)
                + np.asarray(self.lo).astype(np.int64))


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Device-side pre-computation; runs inside the compiled step."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or 'acc'
        self.reset()

    def compute(self, pred, label, *args):
        """Return correctness matrix [N, maxk] (jit-safe)."""
        pred = _to_jnp(pred)
        label = _to_jnp(label)
        # lax.top_k, not a full argsort: O(C log k) and no [.., C]
        # sorted-index tensor on the eval step's critical path.
        # k clamps to the class count (top_k raises where the old
        # argsort slice silently clamped, e.g. topk=(1,5) on a
        # 2-class head)
        _, pred_idx = jax.lax.top_k(
            pred, min(self.maxk, pred.shape[-1]))
        if label.ndim == pred.ndim:  # one-hot or column labels
            if label.shape[-1] == 1:
                label = label[..., 0]
            else:
                label = jnp.argmax(label, axis=-1)
        return (pred_idx == label[..., None]).astype(jnp.float32)

    def update(self, correct, *args):
        """Accumulate per-topk correct counts as LAZY device adds (no
        float() readback); returns the batch accuracies as jnp scalars
        (callers that print force the sync, not the update)."""
        correct = _to_jnp(correct)
        n = correct.shape[0]
        nums = jnp.stack([jnp.sum(correct[..., :k]) for k in self.topk])
        self.total.add(jnp.round(nums))
        self.count += n
        accs = [nums[i] / max(1, n) for i in range(len(self.topk))]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = _LongCounter(len(self.topk))
        self.count = 0

    def accumulate(self):
        tot = self.total.read()   # the single host sync
        res = [float(t) / max(1, self.count) for t in tot]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return ['{}_top{}'.format(self._name, k) for k in self.topk]


class Precision(Metric):
    """Binary precision over thresholded predictions."""

    _STAT_LEN = 2   # (tp, fp)

    def __init__(self, name='precision', *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def compute(self, preds, labels, *args):
        """[tp, fp] of the batch as a jnp stat (jit-safe)."""
        p = _to_jnp(preds).reshape(-1)
        y = _to_jnp(labels).reshape(-1)
        pred_pos = p > 0.5
        tp = jnp.sum(pred_pos & (y == 1))
        fp = jnp.sum(pred_pos & (y != 1))
        return jnp.stack([tp, fp]).astype(jnp.int32)

    def update(self, stat, labels=None):
        """`stat` is compute()'s [tp, fp]; the legacy eager call
        update(preds, labels) routes through compute first."""
        if labels is not None:
            stat = self.compute(stat, labels)
        self._stat.add(_to_jnp(stat))

    def reset(self):
        self._stat = _LongCounter(2)

    def accumulate(self):
        tp, fp = self._stat.read()
        denom = tp + fp
        return float(tp / denom) if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall over thresholded predictions."""

    def __init__(self, name='recall', *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def compute(self, preds, labels, *args):
        """[tp, fn] of the batch as a jnp stat (jit-safe)."""
        p = _to_jnp(preds).reshape(-1)
        y = _to_jnp(labels).reshape(-1)
        pred_pos = p > 0.5
        tp = jnp.sum(pred_pos & (y == 1))
        fn = jnp.sum(~pred_pos & (y == 1))
        return jnp.stack([tp, fn]).astype(jnp.int32)

    def update(self, stat, labels=None):
        if labels is not None:
            stat = self.compute(stat, labels)
        self._stat.add(_to_jnp(stat))

    def reset(self):
        self._stat = _LongCounter(2)

    def accumulate(self):
        tp, fn = self._stat.read()
        denom = tp + fn
        return float(tp / denom) if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via histogram buckets (streaming-friendly).  The bucket
    histograms are jnp state summed in-place per batch; the trapezoid
    walk happens once, at accumulate()."""

    def __init__(self, curve='ROC', num_thresholds=4095, name='auc',
                 *args, **kwargs):
        super().__init__()
        self.curve = curve
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def compute(self, preds, labels, *args):
        """Batch bucket histograms stacked [2, T+1] (pos, neg) — a
        scatter-add inside the compiled step."""
        p = _to_jnp(preds)
        y = _to_jnp(labels).reshape(-1)
        if p.ndim == 2 and p.shape[1] == 2:
            scores = p[:, 1]
        else:
            scores = p.reshape(-1)
        n = self.num_thresholds + 1
        buckets = jnp.clip(
            (scores * self.num_thresholds).astype(jnp.int32),
            0, self.num_thresholds)
        pos = (y != 0).astype(jnp.int32)
        pos_hist = jnp.zeros(n, jnp.int32).at[buckets].add(pos)
        neg_hist = jnp.zeros(n, jnp.int32).at[buckets].add(1 - pos)
        return jnp.stack([pos_hist, neg_hist])

    def update(self, stat, labels=None):
        """`stat` is compute()'s [2, T+1] histogram pair; the legacy
        eager call update(preds, labels) routes through compute.  The
        state is a _LongCounter: exact int64-range totals with every
        add (and the periodic carry fold) staying ON device."""
        if labels is not None:
            stat = self.compute(stat, labels)
        self._stat.add(_to_jnp(stat))

    def reset(self):
        self._stat = _LongCounter((2, self.num_thresholds + 1))

    @property
    def _stat_pos(self):
        """Host view of the positive buckets (fleet.metrics.auc and
        legacy consumers read these)."""
        return self._stat.read()[0]

    @property
    def _stat_neg(self):
        return self._stat.read()[1]

    def accumulate(self):
        # walk thresholds high->low accumulating TP/FP; trapezoid rule
        stat = self._stat.read()   # the single host sync
        stat_pos, stat_neg = stat[0], stat[1]
        tot_pos = float(stat_pos.sum())
        tot_neg = float(stat_neg.sum())
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp = fp = 0.0
        auc = 0.0
        prev_tpr = prev_fpr = 0.0
        for b in range(self.num_thresholds, -1, -1):
            tp += float(stat_pos[b])
            fp += float(stat_neg[b])
            tpr, fpr = tp / tot_pos, fp / tot_neg
            auc += (fpr - prev_fpr) * (tpr + prev_tpr) / 2.0
            prev_tpr, prev_fpr = tpr, fpr
        return auc

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: paddle.metric.accuracy)."""
    x = input.value if isinstance(input, Tensor) else jnp.asarray(input)
    y = label.value if isinstance(label, Tensor) else jnp.asarray(label)
    _, pred_idx = jax.lax.top_k(x, min(k, x.shape[-1]))
    if y.ndim == x.ndim:
        if y.shape[-1] == 1:
            y = y[..., 0]
        else:
            y = jnp.argmax(y, axis=-1)
    correct_mat = (pred_idx == y[..., None]).any(axis=-1)
    return Tensor(jnp.mean(correct_mat.astype(jnp.float32), keepdims=True))
