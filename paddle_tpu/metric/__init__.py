"""Metrics: Accuracy / Precision / Recall / Auc.

Reference analogue: python/paddle/metric/metrics.py (Metric, Accuracy,
Precision, Recall, Auc, paddle.metric.accuracy).  `compute` is jit-safe
(pure jnp on device); `update` accumulates small host-side scalars so
the compiled train step never materialises metric state on device.
"""
import abc

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ['Metric', 'Accuracy', 'Precision', 'Recall', 'Auc', 'accuracy']


def _to_np(x):
    if isinstance(x, Tensor):
        return np.asarray(x.value)
    return np.asarray(x)


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Device-side pre-computation; runs inside the compiled step."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or 'acc'
        self.reset()

    def compute(self, pred, label, *args):
        """Return correctness matrix [N, maxk] (jit-safe)."""
        pred = pred.value if isinstance(pred, Tensor) else jnp.asarray(pred)
        label = label.value if isinstance(label, Tensor) \
            else jnp.asarray(label)
        pred_idx = jnp.argsort(pred, axis=-1)[..., ::-1][..., :self.maxk]
        if label.ndim == pred.ndim:  # one-hot or column labels
            if label.shape[-1] == 1:
                label = label[..., 0]
            else:
                label = jnp.argmax(label, axis=-1)
        return (pred_idx == label[..., None]).astype(jnp.float32)

    def update(self, correct, *args):
        correct = _to_np(correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].sum()
            accs.append(float(num) / max(1, correct.shape[0]))
            self.total[self.topk.index(k)] += float(num)
        self.count += correct.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(1, self.count) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return ['{}_top{}'.format(self._name, k) for k in self.topk]


class Precision(Metric):
    """Binary precision over thresholded predictions."""

    def __init__(self, name='precision', *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).reshape(-1)
        labels = _to_np(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels != 1)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall over thresholded predictions."""

    def __init__(self, name='recall', *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).reshape(-1)
        labels = _to_np(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via histogram buckets (streaming-friendly)."""

    def __init__(self, curve='ROC', num_thresholds=4095, name='auc',
                 *args, **kwargs):
        super().__init__()
        self.curve = curve
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            scores = preds[:, 1]
        else:
            scores = preds.reshape(-1)
        buckets = np.clip((scores * self.num_thresholds).astype(int),
                          0, self.num_thresholds)
        pos = labels.astype(bool)
        n = self.num_thresholds + 1
        self._stat_pos += np.bincount(buckets[pos], minlength=n)
        self._stat_neg += np.bincount(buckets[~pos], minlength=n)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def accumulate(self):
        # walk thresholds high->low accumulating TP/FP; trapezoid rule
        tot_pos = float(self._stat_pos.sum())
        tot_neg = float(self._stat_neg.sum())
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp = fp = 0.0
        auc = 0.0
        prev_tpr = prev_fpr = 0.0
        for b in range(self.num_thresholds, -1, -1):
            tp += float(self._stat_pos[b])
            fp += float(self._stat_neg[b])
            tpr, fpr = tp / tot_pos, fp / tot_neg
            auc += (fpr - prev_fpr) * (tpr + prev_tpr) / 2.0
            prev_tpr, prev_fpr = tpr, fpr
        return auc

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: paddle.metric.accuracy)."""
    x = input.value if isinstance(input, Tensor) else jnp.asarray(input)
    y = label.value if isinstance(label, Tensor) else jnp.asarray(label)
    pred_idx = jnp.argsort(x, axis=-1)[..., ::-1][..., :k]
    if y.ndim == x.ndim:
        if y.shape[-1] == 1:
            y = y[..., 0]
        else:
            y = jnp.argmax(y, axis=-1)
    correct_mat = (pred_idx == y[..., None]).any(axis=-1)
    return Tensor(jnp.mean(correct_mat.astype(jnp.float32), keepdims=True))
