"""ERNIE model family (SURVEY §3 config 3: "ERNIE/BERT-base pretrain").

ERNIE 1.0 (Baidu) shares the BERT encoder architecture; what differs is
the pretraining DATA strategy (phrase/entity-level masking, which lives
in the input pipeline, not the network) and the Chinese-corpus config:
vocab 18000, type_vocab 4.  This module therefore configures the BERT
backbone (models/bert.py) with ERNIE's dimensions rather than
duplicating the encoder — any masking strategy can be applied by the
data pipeline feeding it.  (The backbone's GELU MLP and NSP-style
sentence head are shared with BERT; this is the bench config for
SURVEY §3 item 3, not a weight-compatible ERNIE 1.0 port.)
"""
from .bert import BertConfig, BertModel, BertForPretraining

__all__ = ['ErnieConfig', 'ErnieModel', 'ErnieForPretraining',
           'ernie_base', 'ernie_tiny']


class ErnieConfig(BertConfig):
    def __init__(self, vocab_size=18000, hidden_size=768, num_layers=12,
                 num_heads=12, max_seq_len=513, type_vocab_size=4,
                 **kw):
        super().__init__(vocab_size=vocab_size, hidden_size=hidden_size,
                         num_layers=num_layers, num_heads=num_heads,
                         max_seq_len=max_seq_len,
                         type_vocab_size=type_vocab_size, **kw)


class ErnieModel(BertModel):
    """ERNIE 1.0 encoder (BERT backbone with ERNIE dims)."""


class ErnieForPretraining(BertForPretraining):
    """MLM + next-sentence head over the ERNIE encoder; phrase/entity
    masking is the caller's labeling strategy."""


def ernie_base(**kw):
    return ErnieForPretraining(ErnieConfig(**kw))


def ernie_tiny(**kw):
    kw.setdefault('vocab_size', 128)
    kw.setdefault('hidden_size', 32)
    kw.setdefault('num_layers', 2)
    kw.setdefault('num_heads', 2)
    kw.setdefault('max_seq_len', 64)
    return ErnieForPretraining(ErnieConfig(**kw))
