"""Flagship NLP model zoo (the reference keeps these in fleet examples;
here they are first-class because they drive the distributed benches)."""
from .gpt import (  # noqa: F401
    GPTConfig, GPT, GPTForCausalLM, gpt_tiny, gpt_small, gpt_1p3b,
    gpt_moe_tiny)
from .widedeep import WideDeep, DeepFM  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForPretraining, bert_tiny, bert_base,
    bert_large)
from .ernie import (  # noqa: F401
    ErnieConfig, ErnieModel, ErnieForPretraining, ernie_base,
    ernie_tiny)

__all__ = ['GPTConfig', 'GPT', 'GPTForCausalLM', 'gpt_tiny', 'gpt_small',
           'gpt_1p3b', 'gpt_moe_tiny', 'WideDeep', 'DeepFM', 'BertConfig', 'BertModel',
           'BertForPretraining', 'bert_tiny', 'bert_base', 'bert_large',
           'ErnieConfig', 'ErnieModel', 'ErnieForPretraining',
           'ernie_base', 'ernie_tiny']
