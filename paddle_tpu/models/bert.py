"""BERT/ERNIE-style bidirectional encoder + pretraining heads.

Reference analogue: the ERNIE/BERT fleet pretrain benchmarks the
reference runs over NCCL DP (SURVEY.md §3 item 3).  Same TP-layer
construction as GPT (Megatron qkv/proj split on `tp`), but bidirectional
attention (non-causal flash kernel single-chip) plus MLM + NSP heads.
"""
import math

from .. import nn
from ..nn import functional as F
from ..distributed.fleet.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from ..parallel.api import maybe_shard
from ..tensor import linalg, manipulation

__all__ = ['BertConfig', 'BertModel', 'BertForPretraining', 'bert_tiny',
           'bert_base', 'bert_large']


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, max_seq_len=512, type_vocab_size=2,
                 intermediate_size=None, dropout=0.1,
                 layer_norm_epsilon=1e-12, fused_head=False,
                 fused_head_chunks=8):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_seq_len = max_seq_len
        self.type_vocab_size = type_vocab_size
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.dropout = dropout
        self.layer_norm_epsilon = layer_norm_epsilon
        # fused MLM head (ops/fused_ce.py): training forward returns
        # the transformed hidden states and loss() fuses the tied
        # decoder matmul + softmax + CE over vocab chunks — the
        # [B·T, V] logits never materialize (single-chip / dp paths;
        # keep off under tp)
        self.fused_head = fused_head
        self.fused_head_chunks = fused_head_chunks


class BertSelfAttention(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        assert cfg.hidden_size % cfg.num_heads == 0
        self.n_head = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.qkv = ColumnParallelLinear(cfg.hidden_size,
                                        3 * cfg.hidden_size,
                                        gather_output=False)
        self.proj = RowParallelLinear(cfg.hidden_size, cfg.hidden_size,
                                      input_is_parallel=True)
        self.drop = nn.Dropout(cfg.dropout)

    def _use_flash(self, T):
        from ..ops.flash_attention import can_use_pallas
        dropout_active = self.training and self.drop.p > 0.0
        return not dropout_active and can_use_pallas(T, T, self.head_dim)

    def forward(self, x, attn_mask=None):
        B, T, H = x.shape
        qkv = self.qkv(x)
        qkv = maybe_shard(qkv, ('dp', None, 'tp'))
        qkv = manipulation.reshape(qkv, [B, T, 3, self.n_head,
                                         self.head_dim])
        q = manipulation.transpose(qkv[:, :, 0], [0, 2, 1, 3])
        k = manipulation.transpose(qkv[:, :, 1], [0, 2, 1, 3])
        v = manipulation.transpose(qkv[:, :, 2], [0, 2, 1, 3])
        if attn_mask is None and self._use_flash(T):
            from ..ops import flash_attention
            from ..core.dispatch import apply
            nh, hd = self.n_head, self.head_dim
            q = manipulation.reshape(q, [B * nh, T, hd])
            k = manipulation.reshape(k, [B * nh, T, hd])
            v = manipulation.reshape(v, [B * nh, T, hd])
            y = apply(lambda qv, kv, vv: flash_attention(
                qv, kv, vv, causal=False), q, k, v,
                op_name='flash_attention')
            y = manipulation.reshape(y, [B, nh, T, hd])
        else:
            q = maybe_shard(q, ('dp', 'tp', None, None))
            k = maybe_shard(k, ('dp', 'tp', None, None))
            v = maybe_shard(v, ('dp', 'tp', None, None))
            att = linalg.matmul(q, k, transpose_y=True)
            att = att * (1.0 / math.sqrt(self.head_dim))
            if attn_mask is not None:
                att = att + attn_mask
            att = F.softmax(att, axis=-1)
            att = self.drop(att)
            y = linalg.matmul(att, v)
        y = manipulation.transpose(y, [0, 2, 1, 3])
        y = manipulation.reshape(y, [B, T, H])
        y = maybe_shard(y, ('dp', None, 'tp'))
        return self.proj(y)


class BertLayer(nn.Layer):
    """post-LN encoder block (original BERT ordering)."""

    def __init__(self, cfg):
        super().__init__()
        self.attn = BertSelfAttention(cfg)
        self.ln1 = nn.LayerNorm(cfg.hidden_size,
                                epsilon=cfg.layer_norm_epsilon)
        self.fc = ColumnParallelLinear(cfg.hidden_size,
                                       cfg.intermediate_size,
                                       gather_output=False)
        self.proj = RowParallelLinear(cfg.intermediate_size,
                                      cfg.hidden_size,
                                      input_is_parallel=True)
        self.ln2 = nn.LayerNorm(cfg.hidden_size,
                                epsilon=cfg.layer_norm_epsilon)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.drop(self.attn(x, attn_mask)))
        # single chip: fused matmul+GELU epilogue kernel whose backward
        # recomputes the pre-activation instead of saving the [B,T,4H]
        # tensor (ops/fused_gelu_linear.py); mesh: tp-sharded path
        from ..ops.fused_gelu_linear import mlp_gelu
        h = mlp_gelu(x, self.fc, shard_spec=('dp', None, 'tp'))
        h = self.proj(h)
        return self.ln2(x + self.drop(h))


class BertModel(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.word_emb = VocabParallelEmbedding(config.vocab_size,
                                               config.hidden_size)
        self.pos_emb = nn.Embedding(config.max_seq_len,
                                    config.hidden_size)
        self.type_emb = nn.Embedding(config.type_vocab_size,
                                     config.hidden_size)
        self.ln = nn.LayerNorm(config.hidden_size,
                               epsilon=config.layer_norm_epsilon)
        self.drop = nn.Dropout(config.dropout)
        self.layers = nn.LayerList([BertLayer(config)
                                    for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        B, T = input_ids.shape
        x = self.word_emb(input_ids) + F.embedding_prefix(
            self.pos_emb.weight, T)
        if token_type_ids is not None:
            x = x + self.type_emb(token_type_ids)
        x = self.drop(self.ln(x))
        x = maybe_shard(x, ('dp', None, None))
        for layer in self.layers:
            x = layer(x, attn_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM (tied decoder) + NSP heads; loss() = mlm_ce + nsp_ce."""

    def __init__(self, config):
        super().__init__()
        self.bert = BertModel(config)
        self.config = config
        self.mlm_transform = nn.Linear(config.hidden_size,
                                       config.hidden_size)
        self.mlm_ln = nn.LayerNorm(config.hidden_size,
                                   epsilon=config.layer_norm_epsilon)
        self.nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None):
        seq, pooled = self.bert(input_ids, token_type_ids)
        h = self.mlm_ln(F.gelu(self.mlm_transform(seq),
                               approximate=True))
        nsp_logits = self.nsp(pooled)
        if self.config.fused_head and self.training:
            # fused MLM head: the tied-decoder matmul happens inside
            # loss() (ops/fused_ce.py) — return the hidden states
            return h, nsp_logits
        logits = linalg.matmul(h, self.bert.word_emb.weight,
                               transpose_y=True)
        logits = maybe_shard(logits, ('dp', None, 'tp'))
        return logits, nsp_logits

    def loss(self, outputs, mlm_labels, nsp_labels=None):
        logits, nsp_logits = outputs
        B, T, D = logits.shape
        # keyed off the SHAPE the forward actually produced, not
        # self.training — a train-forward/eval-loss toggle must not
        # feed hidden states into the unfused CE branch
        if self.config.fused_head and D == self.config.hidden_size \
                and D != self.config.vocab_size:
            from ..core.dispatch import apply as _apply
            from ..ops.fused_ce import fused_linear_cross_entropy
            import jax.numpy as jnp

            def _fce(h, w, lb):
                hh = h.reshape(B * T, D)
                yy = lb.reshape(B * T)
                losses = fused_linear_cross_entropy(
                    hh, w.T, yy,
                    num_chunks=self.config.fused_head_chunks)
                # ignore_index=-100: those labels land in no vocab
                # chunk, so masking the loss zeroes both the value
                # and (through the where) the gradient
                valid = yy != -100
                n = jnp.maximum(jnp.sum(valid), 1)
                return jnp.sum(jnp.where(valid, losses, 0.0)) / n

            mlm = _apply(_fce, logits, self.bert.word_emb.weight,
                         mlm_labels, op_name='fused_mlm_head_ce')
        else:
            lg = manipulation.reshape(logits, [B * T, D])
            lb = manipulation.reshape(mlm_labels, [B * T])
            mlm = F.cross_entropy(lg, lb, ignore_index=-100)
        if nsp_labels is None:
            return mlm
        return mlm + F.cross_entropy(nsp_logits, nsp_labels)


def bert_tiny(**kw):
    kw.setdefault('vocab_size', 128)
    kw.setdefault('hidden_size', 64)
    kw.setdefault('num_layers', 4)
    kw.setdefault('num_heads', 4)
    kw.setdefault('max_seq_len', 128)
    kw.setdefault('dropout', 0.0)
    return BertForPretraining(BertConfig(**kw))


def bert_base(**kw):
    return BertForPretraining(BertConfig(**kw))


def bert_large(**kw):
    kw.setdefault('hidden_size', 1024)
    kw.setdefault('num_layers', 24)
    kw.setdefault('num_heads', 16)
    return BertForPretraining(BertConfig(**kw))
