"""GPT-family causal-transformer — the flagship distributed model.

Reference analogue: the fleet GPT examples driving
python/paddle/distributed/fleet/meta_parallel (mp_layers/pp_layers); the
reference scales it with NCCL TP/PP process groups.  TPU-native design:

- built on fleet.meta_parallel TP layers (ColumnParallelLinear /
  RowParallelLinear / VocabParallelEmbedding) whose PartitionSpecs put
  matmul shards on the `tp` mesh axis — XLA inserts the psum/all-gather
  collectives over ICI;
- sequence-parallel hook: activations between blocks carry a
  P(dp, sp, None) sharding constraint, so long sequences split over the
  `sp` axis (ring attention upgrades this path later);
- eager single-chip: the same code runs unsharded (maybe_shard is the
  identity outside a mesh trace).

Everything under one `jax.jit` train step: no Python control flow
depends on data; dropout threads PRNG keys via the functional-key scope.
"""
import math

import numpy as np

from .. import nn
from ..nn import functional as F
from ..core.tensor import Tensor
from ..distributed.fleet.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from ..parallel.api import maybe_shard
from ..tensor import creation, linalg, manipulation, math as pmath

__all__ = ['GPTConfig', 'GPT', 'GPTForCausalLM', 'gpt_tiny', 'gpt_small',
           'gpt_1p3b', 'gpt_moe_tiny']


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, max_seq_len=1024, intermediate_size=None,
                 dropout=0.1, layer_norm_epsilon=1e-5,
                 sequence_parallel=False, initializer_range=0.02,
                 moe_num_experts=0, moe_every=2, moe_top_k=1,
                 moe_capacity_factor=1.25, moe_aux_weight=0.01,
                 fused_head=False, fused_head_chunks=8,
                 striped_sp=False, scan_decode_blocks=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_seq_len = max_seq_len
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.dropout = dropout
        self.layer_norm_epsilon = layer_norm_epsilon
        self.sequence_parallel = sequence_parallel
        self.initializer_range = initializer_range
        # MoE (expert parallelism over the 'ep' mesh axis): when
        # moe_num_experts > 0, every moe_every-th block's MLP becomes a
        # SwitchMoE (incubate/moe.py) and loss() adds the load-balance
        # auxiliary term
        self.moe_num_experts = moe_num_experts
        self.moe_every = moe_every
        self.moe_top_k = moe_top_k
        self.moe_capacity_factor = moe_capacity_factor
        self.moe_aux_weight = moe_aux_weight
        # fused LM head (ops/fused_ce.py): training forward returns
        # the final HIDDEN states and loss() computes linear+softmax+CE
        # chunked over the vocab — the f32 [B·T, V] logits are never
        # materialized.  Single-chip / dp paths; keep off under tp
        # (the head matmul then wants the V-sharded parallel CE).
        self.fused_head = fused_head
        self.fused_head_chunks = fused_head_chunks
        # striped (load-balanced) sequence parallelism: hidden states
        # live in the Striped Attention token order END-TO-END during
        # training (ids/positions striped at embedding, labels
        # shift-then-stripe in the fused loss — the per-token CE mean
        # is permutation-invariant, so loss parity is exact).  Requires
        # sequence_parallel + fused_head; eval/decode stay natural.
        self.striped_sp = striped_sp
        # decode compile-time lever: scan ONE block body over stacked
        # per-layer params inside generate() instead of inlining
        # num_layers copies into the token scan — ~L-times less HLO
        # in the decode module (the 900 s remote compile that twice
        # wedged the round-4 tunnel was the unrolled form).  CPU
        # measurement (stacks hoisted out of the token body): compile
        # -33%, runtime +70% — CPU materializes each layer's param
        # slice as a copy per token, which TPU's while-loop HBM reads
        # do not; OPT-IN until the chip A/B
        # (tools/bench_scan_decode.py) shows the compile shrink is
        # worth the TPU runtime delta.  Token-exact parity with the
        # unrolled path is locked in tests/test_kv_cache.py.  Ignored
        # for heterogeneous stacks (MoE every-k blocks).
        self.scan_decode_blocks = scan_decode_blocks


def _act_spec(cfg):
    """Sharding of [B, T, H] activations between blocks."""
    return ('dp', 'sp' if cfg.sequence_parallel else None, None)


def _striped_sp_now(cfg, training):
    """sp degree iff a forward traced RIGHT NOW should run in the
    striped layout.  ONE gate shared by GPT.forward (which stripes the
    ids/positions) and CausalSelfAttention (which picks the striped
    ring) so the two can never disagree: config opted in, training
    with the fused head (striped hidden states are consumed only by
    the permutation-invariant fused CE loss — eval logits must stay
    natural), dropout inactive (mirrors _ring_mesh: the ring itself is
    gated off under attention dropout), and an sp>1 mesh installed."""
    if not (cfg.striped_sp and cfg.sequence_parallel and cfg.fused_head
            and training):
        return None
    if cfg.dropout > 0.0:
        return None
    from ..distributed import env as _env
    mesh = _env.get_mesh()
    if mesh is None:
        return None
    sp = dict(mesh.shape).get('sp', 1)
    return sp if sp > 1 else None


class CausalSelfAttention(nn.Layer):
    """Multi-head causal attention; qkv column-parallel, output
    row-parallel (Megatron split — one psum per block on TPU ICI)."""

    def __init__(self, cfg):
        super().__init__()
        assert cfg.hidden_size % cfg.num_heads == 0
        self.n_head = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.qkv = ColumnParallelLinear(cfg.hidden_size,
                                        3 * cfg.hidden_size,
                                        gather_output=False)
        self.proj = RowParallelLinear(cfg.hidden_size, cfg.hidden_size,
                                      input_is_parallel=True)
        self.attn_drop = nn.Dropout(cfg.dropout)
        self.resid_drop = nn.Dropout(cfg.dropout)
        self.cfg = cfg

    def _ring_mesh(self):
        """The active mesh, iff sequence-parallel ring attention should
        run: sp axis > 1, config opted in, and dropout inactive."""
        if not self.cfg.sequence_parallel:
            return None
        if self.training and self.attn_drop.p > 0.0:
            return None
        from ..distributed import env as _env
        mesh = _env.get_mesh()
        if mesh is not None and dict(mesh.shape).get('sp', 1) > 1:
            return mesh
        return None

    def _use_flash(self, T):
        """Pallas flash attention on the single chip.  Dropout only
        blocks it while actually active (training mode)."""
        from ..ops.flash_attention import can_use_pallas
        dropout_active = self.training and self.attn_drop.p > 0.0
        return not dropout_active and can_use_pallas(T, T, self.head_dim)

    def _flash_mesh(self, B, T):
        """The active mesh iff flash should run UNDER it (shard_map over
        dp/tp — ops.flash_attention.flash_attention_spmd)."""
        dropout_active = self.training and self.attn_drop.p > 0.0
        if dropout_active:
            return None
        from ..distributed import env as _env
        from ..ops.flash_attention import can_use_pallas_spmd
        mesh = _env.get_mesh()
        if mesh is not None and can_use_pallas_spmd(
                B, self.n_head, T, self.head_dim, mesh):
            return mesh
        return None

    def forward(self, x, cache=None, pos=None):
        B, T, H = x.shape
        # attention needs the full sequence: un-shard T, shard heads on tp
        qkv = self.qkv(x)                       # [B, T, 3H/tp]
        qkv = maybe_shard(qkv, ('dp', None, 'tp'))
        qkv = manipulation.reshape(qkv, [B, T, 3, self.n_head,
                                         self.head_dim])
        q = manipulation.transpose(qkv[:, :, 0], [0, 2, 1, 3])
        k = manipulation.transpose(qkv[:, :, 1], [0, 2, 1, 3])
        v = manipulation.transpose(qkv[:, :, 2], [0, 2, 1, 3])
        if cache is not None and getattr(cache, 'paged', False):
            # paged serving decode (serving/kv_cache.PagedCacheView):
            # ONE query token per sequence, k/v scattered into the
            # sequence's pool blocks through its block table, ragged
            # per-sequence length masking — bit-exact vs the dense
            # buffer below on shared prefixes (ops/paged_attention).
            if T != 1:
                raise ValueError(
                    'paged cache views decode one token per step; '
                    f'prefill goes through the dense path (got T={T})')
            from ..core.dispatch import apply as _apply
            from ..ops.paged_attention import (paged_attention,
                                               write_kv)

            def paged(kp, vp, tbl, slots, lens, qv, kv, vv):
                kp, vp = write_kv(kp, vp, kv[:, :, 0], vv[:, :, 0],
                                  tbl, slots)
                y = paged_attention(qv[:, :, 0], kp, vp, tbl, lens)
                return y[:, :, None], kp, vp

            y, new_k, new_v = _apply(
                paged, cache.k_pool, cache.v_pool, cache.block_table,
                cache.slots, cache.lens, q, k, v,
                op_name='paged_attention')
            y = manipulation.transpose(y, [0, 2, 1, 3])
            y = manipulation.reshape(y, [B, T, H])
            y = self.proj(y)
            return self.resid_drop(y), cache.updated(
                new_k.value if hasattr(new_k, 'value') else new_k,
                new_v.value if hasattr(new_v, 'value') else new_v)
        if cache is not None:
            # jit-friendly incremental decode: k/v land in a
            # PREALLOCATED [B, nh, Tmax, hd] buffer at traced offset
            # `pos` (lax.dynamic_update_slice) — static shapes, so the
            # whole generate loop compiles to ONE XLA while/scan.  The
            # eager concat-cache equivalent lives in
            # nn.layer.transformer.MultiHeadAttention.Cache.
            from ..core.dispatch import apply as _apply

            def cached(kb, vb, qv, kv, vv, posv):
                import jax
                import jax.numpy as jnp
                p = posv.reshape(()).astype(jnp.int32)
                kb = jax.lax.dynamic_update_slice(
                    kb, kv.astype(kb.dtype), (0, 0, p, 0))
                vb = jax.lax.dynamic_update_slice(
                    vb, vv.astype(vb.dtype), (0, 0, p, 0))
                scores = jnp.einsum('bhqd,bhkd->bhqk', qv, kb) \
                    * (1.0 / math.sqrt(self.head_dim))
                Tmax = kb.shape[2]
                row = p + jnp.arange(T)                  # absolute q pos
                col = jnp.arange(Tmax)
                mask = col[None, :] <= row[:, None]      # causal, static
                scores = jnp.where(mask[None, None], scores, -1e9)
                att = jax.nn.softmax(scores, axis=-1)
                y = jnp.einsum('bhqk,bhkd->bhqd', att, vb)
                return y, kb, vb

            y, new_k, new_v = _apply(cached, cache[0], cache[1], q, k, v,
                                     pos, op_name='cached_attention')
            y = manipulation.transpose(y, [0, 2, 1, 3])
            y = manipulation.reshape(y, [B, T, H])
            y = self.proj(y)
            return self.resid_drop(y), (new_k, new_v)
        ring_mesh = self._ring_mesh()
        if ring_mesh is not None:
            # sequence parallel: K/V rotate around the sp ICI ring, each
            # chip holds T/sp of the sequence (SURVEY.md §2 item 35)
            from ..ops.ring_attention import ring_attention_spmd
            from ..core.dispatch import apply
            nh, hd = self.n_head, self.head_dim
            q = manipulation.reshape(q, [B * nh, T, hd])
            k = manipulation.reshape(k, [B * nh, T, hd])
            v = manipulation.reshape(v, [B * nh, T, hd])
            # same gate as GPT.forward: striped traces get the
            # load-balanced ring over already-striped hidden states
            striped = _striped_sp_now(self.cfg, self.training) is not None
            y = apply(lambda qv, kv, vv: ring_attention_spmd(
                qv, kv, vv, ring_mesh, causal=True, striped=striped,
                pre_striped=striped), q, k, v,
                op_name='ring_attention')
            y = manipulation.reshape(y, [B, nh, T, hd])
        elif self._use_flash(T):
            from ..ops import flash_attention
            from ..core.dispatch import apply
            nh, hd = self.n_head, self.head_dim
            q = manipulation.reshape(q, [B * nh, T, hd])
            k = manipulation.reshape(k, [B * nh, T, hd])
            v = manipulation.reshape(v, [B * nh, T, hd])
            y = apply(lambda qv, kv, vv: flash_attention(
                qv, kv, vv, causal=True), q, k, v,
                op_name='flash_attention')
            y = manipulation.reshape(y, [B, nh, T, hd])
        elif (fmesh := self._flash_mesh(B, T)) is not None:
            # hybrid mesh: the Pallas kernel rides dp/tp via shard_map
            # (batch and heads shard; attention is head-independent)
            from ..ops.flash_attention import flash_attention_spmd
            from ..core.dispatch import apply
            y = apply(lambda qv, kv, vv: flash_attention_spmd(
                qv, kv, vv, fmesh, causal=True), q, k, v,
                op_name='flash_attention_spmd')
        else:
            q = maybe_shard(q, ('dp', 'tp', None, None))
            k = maybe_shard(k, ('dp', 'tp', None, None))
            v = maybe_shard(v, ('dp', 'tp', None, None))
            att = linalg.matmul(q, k, transpose_y=True)  # [B, nh, T, T]
            att = att * (1.0 / math.sqrt(self.head_dim))
            mask = creation.tril(creation.ones([T, T], dtype=att.dtype))
            att = att - (1.0 - mask) * 1e9
            att = F.softmax(att, axis=-1)
            att = self.attn_drop(att)
            y = linalg.matmul(att, v)                    # [B, nh, T, hd]
        y = manipulation.transpose(y, [0, 2, 1, 3])
        y = manipulation.reshape(y, [B, T, H])
        y = maybe_shard(y, ('dp', None, 'tp'))
        y = self.proj(y)                                 # psum over tp
        y = self.resid_drop(y)
        return maybe_shard(y, _act_spec(self.cfg))


class GPTMLP(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.fc = ColumnParallelLinear(cfg.hidden_size,
                                       cfg.intermediate_size,
                                       gather_output=False)
        self.proj = RowParallelLinear(cfg.intermediate_size,
                                      cfg.hidden_size,
                                      input_is_parallel=True)
        self.drop = nn.Dropout(cfg.dropout)
        self.cfg = cfg

    def forward(self, x):
        # fused matmul+GELU on single chip, tp-sharded path on a mesh
        from ..ops.fused_gelu_linear import mlp_gelu
        h = mlp_gelu(x, self.fc, shard_spec=('dp', None, 'tp'))
        h = self.proj(h)
        h = self.drop(h)
        return maybe_shard(h, _act_spec(self.cfg))


class GPTBlock(nn.Layer):
    def __init__(self, cfg, use_moe=False):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size,
                                epsilon=cfg.layer_norm_epsilon)
        self.attn = CausalSelfAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size,
                                epsilon=cfg.layer_norm_epsilon)
        if use_moe:
            from ..incubate.moe import SwitchMoE
            self.mlp = SwitchMoE(cfg.hidden_size, cfg.intermediate_size,
                                 cfg.moe_num_experts,
                                 top_k=cfg.moe_top_k,
                                 capacity_factor=cfg.moe_capacity_factor)
        else:
            self.mlp = GPTMLP(cfg)
        self.cfg = cfg

    def forward(self, x, cache=None, pos=None):
        if cache is not None:
            a, new_cache = self.attn(self.ln1(x), cache=cache, pos=pos)
            x = x + a
            x = x + self.mlp(self.ln2(x))
            return x, new_cache
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return maybe_shard(x, _act_spec(self.cfg))


class GPT(nn.Layer):
    """Backbone: embeddings + blocks + final LN → hidden states."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size,
                                          config.hidden_size)
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList([
            GPTBlock(config, use_moe=(
                config.moe_num_experts > 0
                and i % config.moe_every == config.moe_every - 1))
            for i in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, caches=None, pos=None):
        B, T = input_ids.shape
        if caches is not None:
            # incremental: absolute positions start at traced offset —
            # a scalar for the lock-step generate() batch, a [B] vector
            # for the serving engine's ragged live set (every sequence
            # at its own depth)
            from ..core.dispatch import apply as _apply
            import jax.numpy as jnp

            def _posv(p):
                if getattr(p, 'ndim', 0) == 0 or p.size == 1:
                    return p.reshape(()).astype(jnp.int64) \
                        + jnp.arange(T, dtype=jnp.int64)
                return p.reshape(-1).astype(jnp.int64)[:, None] \
                    + jnp.arange(T, dtype=jnp.int64)[None, :]

            posv = _apply(_posv, pos, op_name='pos_offset')
            x = self.wte(input_ids) + self.wpe(posv)
            x = self.drop(x)
            new_caches = []
            for blk, c in zip(self.blocks, caches):
                x, nc = blk(x, cache=c, pos=pos)
                new_caches.append(nc)
            return self.ln_f(x), new_caches
        sp = _striped_sp_now(self.config, self.training)
        # what THIS forward actually produced — loss() consults the
        # record rather than re-deriving from live mode/mesh state,
        # so a train-forward/eval-loss split cannot mispair layouts
        self._last_striped = sp
        if sp is not None:
            # end-to-end striped layout: ids and the position rows
            # enter in stripe order; every block then runs the
            # load-balanced striped ring with NO per-layer relayout
            from ..core.dispatch import apply as _apply
            from ..ops.ring_attention import stripe_tokens
            input_ids = _apply(
                lambda v: stripe_tokens(v, sp, axis=1), input_ids,
                op_name='stripe_ids')
            pos_rows = F.embedding_prefix(self.wpe.weight, T)
            pos_rows = _apply(
                lambda v: stripe_tokens(v, sp, axis=0), pos_rows,
                op_name='stripe_pos')
            x = self.wte(input_ids) + pos_rows
        else:
            x = self.wte(input_ids) + F.embedding_prefix(
                self.wpe.weight, T)
        x = self.drop(x)
        x = maybe_shard(x, _act_spec(self.config))
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    """GPT + tied LM head; forward returns logits, loss() the LM loss."""

    def __init__(self, config):
        super().__init__()
        self.gpt = GPT(config)
        self.config = config

    def forward(self, input_ids, caches=None, pos=None):
        if caches is not None:
            h, new_caches = self.gpt(input_ids, caches=caches, pos=pos)
            logits = linalg.matmul(h, self.gpt.wte.weight,
                                   transpose_y=True)
            return logits, new_caches
        h = self.gpt(input_ids)
        if self.config.fused_head and self.training:
            # fused-head training: the head matmul happens inside
            # loss() (ops/fused_ce.py) — return the hidden states
            return h
        # tied head: h @ wte.T — logits [B, T, V/tp-sharded]
        logits = linalg.matmul(h, self.gpt.wte.weight, transpose_y=True)
        return maybe_shard(logits, ('dp', None, 'tp'))

    def loss(self, logits, labels, aux_losses=None):
        """Causal LM loss: shift-by-one cross entropy (+ the MoE
        load-balance auxiliary term when experts are routed).

        `aux_losses`: explicit list of per-block MoE aux losses (from
        `SwitchMoE.forward(..., return_aux=True)`).  REQUIRED when
        this loss is compiled in a different trace than the forward —
        the fallback reads each block's `.aux_loss` attribute, which
        is only valid within the same trace (it raises a clear error
        otherwise instead of leaking a tracer).

        With `config.fused_head` the training forward returns HIDDEN
        states [B, T, H] and the linear+softmax+CE fuse here via
        ops/fused_ce.py — no [B·T, V] logits tensor exists."""
        B, T, D = logits.shape
        # keyed off the SHAPE the forward actually produced, not
        # self.training — a train-forward/eval-loss toggle must not
        # feed hidden states into the unfused CE branch
        if self.config.fused_head and D == self.config.hidden_size \
                and D != self.config.vocab_size:
            from ..core.dispatch import apply as _apply
            from ..ops.fused_ce import fused_linear_cross_entropy
            # layout the forward ACTUALLY produced (recorded at trace
            # time), not a re-derivation from live mode/mesh state
            sp = getattr(self.gpt, '_last_striped', None)

            if sp is not None:
                from ..ops.ring_attention import stripe_tokens

                def _fce(h, w, lb):
                    # hidden states arrive STRIPED; labels are natural
                    # ids: shift in natural order, mark the last
                    # position invalid, then stripe — the masked mean
                    # over B*(T-1) tokens equals the natural-order loss
                    # exactly (the CE mean is permutation-invariant)
                    import jax.numpy as jnp
                    nxt = jnp.concatenate(
                        [lb[:, 1:], jnp.zeros((B, 1), lb.dtype)], 1)
                    valid = jnp.concatenate(
                        [jnp.ones((B, T - 1), bool),
                         jnp.zeros((B, 1), bool)], 1)
                    nxt = stripe_tokens(nxt, sp, axis=1)
                    valid = stripe_tokens(valid, sp, axis=1)
                    hh = h.reshape(B * T, D)
                    losses = fused_linear_cross_entropy(
                        hh, w.T, nxt.reshape(B * T),
                        num_chunks=self.config.fused_head_chunks)
                    vv = valid.reshape(B * T).astype(losses.dtype)
                    return jnp.sum(losses * vv) / jnp.sum(vv)
            else:
                def _fce(h, w, lb):
                    hh = h[:, :-1, :].reshape(B * (T - 1), D)
                    yy = lb[:, 1:].reshape(B * (T - 1))
                    losses = fused_linear_cross_entropy(
                        hh, w.T, yy,
                        num_chunks=self.config.fused_head_chunks)
                    return losses.mean()

            out = _apply(_fce, logits, self.gpt.wte.weight,
                         labels, op_name='fused_lm_head_ce')
        else:
            lg = manipulation.reshape(logits[:, :-1, :],
                                      [B * (T - 1), D])
            lb = manipulation.reshape(labels[:, 1:], [B * (T - 1)])
            out = F.cross_entropy(lg, lb)
        if self.config.moe_num_experts > 0:
            if aux_losses is not None:
                aux = list(aux_losses)
            else:
                aux = [blk.mlp.aux_loss for blk in self.gpt.blocks
                       if getattr(blk.mlp, 'aux_loss', None)
                       is not None]
            if aux:
                total = aux[0]
                for a in aux[1:]:
                    total = total + a
                out = out + self.config.moe_aux_weight * \
                    (total / float(len(aux)))
        return out

    def init_decode_caches(self, batch_size, max_len, dtype=None):
        """Per-layer dense KV buffers ``[B, nh, max_len, hd]`` for the
        cached forward — what ``generate`` preallocates internally.
        The serving engine allocates prefill-sized ones (rounded up to
        its KV block size) and scatters them into the paged pool."""
        import jax.numpy as jnp
        cfg = self.config
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        dtype = dtype or jnp.float32
        shape = (int(batch_size), nh, int(max_len), hd)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(cfg.num_layers)]

    def prefill(self, params, buffers, ids, pos, caches):
        """Pure cached forward over a (padded) prompt: every position's
        k/v lands in ``caches`` starting at ``pos``; returns
        ``(logits, new_caches)``.  Safe inside jit — ``generate`` and
        the serving engine (``serving/engine.py``) both run their
        prefill through here, so the two can never drift.

        ``caches`` is a list of per-layer dense ``(k, v)`` buffers
        (``init_decode_caches``) or paged views
        (``serving.kv_cache.PagedCacheView``, decode only);
        ``pos`` is a traced scalar (lock-step batch) or a ``[B]``
        vector (ragged serving batch) of absolute start positions."""
        from ..jit import functional_call
        (logits, new_caches), _ = functional_call(
            self, params, buffers, (ids,),
            kwargs={'caches': caches, 'pos': pos}, training=False)
        return logits, new_caches

    def decode_step(self, params, buffers, tok, pos, caches):
        """One incremental decode step: ``tok`` is ``[B, 1]`` (the
        previous step's sampled token), ``pos`` its absolute
        position(s).  Same pure cached forward as :meth:`prefill` —
        factored apart so callers (generate's token scan, the serving
        engine's continuous-batching step) name what they mean."""
        return self.prefill(params, buffers, tok, pos, caches)

    def generate(self, input_ids, max_new_tokens, temperature=1.0,
                 top_k=None, seed=0):
        """Autoregressive decode, ONE compiled XLA module.

        Prefill runs the prompt through the cached forward (writing every
        prompt position's k/v into the preallocated buffers), then a
        `lax.scan` emits max_new_tokens tokens with O(1) attention work
        per step — no per-step retracing, no growing shapes.  temperature
        0 = greedy argmax; otherwise softmax sampling (optionally top-k
        truncated).  Returns [B, T0 + max_new_tokens] token ids.

        Prompt lengths are BUCKETED to the next power of two: the
        prompt is right-padded to the bucket, the true length rides as
        a traced scalar (prefill samples at row T0-1; decode overwrites
        the padded k/v slots before the causal mask can expose them),
        so the compiled-module set stays finite across arbitrary
        prompt lengths — the serving-bucket precursor.  Token streams
        are bit-identical to the unbucketed decode (the padded tail is
        masked to exact zeros).  Modules are keyed through the shared
        ``core.compile_cache`` fingerprint and persisted as
        ``jax.export`` artifacts, so a fresh process (restart, serving
        cold-start) deserializes instead of re-tracing; see
        ``precompile_decode`` for the export-time AOT path.

        The reference decodes through fluid's BeamSearchDecoder host loop
        (fluid/layers/rnn.py:1581); this is the TPU-native equivalent of
        its cache mechanism (nn/layer/transformer.py:151).
        """
        import jax
        import jax.numpy as jnp
        from ..core import compile_cache as _cc

        cfg = self.config
        ids = input_ids.value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int64)
        B, T0 = ids.shape
        if int(max_new_tokens) < 1:
            return Tensor(ids)
        if T0 + int(max_new_tokens) > cfg.max_seq_len:
            raise ValueError(
                f'prompt+new tokens {T0 + int(max_new_tokens)} exceeds '
                f'max_seq_len {cfg.max_seq_len}')
        if not hasattr(self, '_gen_cache'):
            self._gen_cache = {}
        # the serving hot path keys on the CHEAP signature (bucketed
        # prompt, not exact length); the fingerprint/closure build in
        # _decode_program runs only on a module-cache miss
        P = self._decode_bucket(T0, int(max_new_tokens))
        greedy = temperature == 0 or temperature is None
        sig = (B, P, int(max_new_tokens), greedy,
               float(temperature or 0.0), top_k)
        params, buffers = self.functional_state()
        ids_p = jnp.pad(ids, ((0, 0), (0, P - T0)))
        t0v = jnp.asarray(T0, jnp.int32)
        key = jax.random.PRNGKey(seed)
        jitted = self._gen_cache.get(sig)
        if jitted is None:
            gen_fn, fp, _ck, _P = self._decode_program(
                B, T0, int(max_new_tokens), temperature, top_k,
                params=params)
            if fp is not None:
                jitted = _cc.lookup_executable(fp, name='GPT.generate')
                if jitted is not None:
                    # aval drift (x64 flip etc.) degrades to a fresh
                    # jit instead of crashing the serve path
                    jitted = _cc._with_fallback(
                        jitted, jax.jit(gen_fn), name='GPT.generate')
            if jitted is None:
                # export-primary: ONE trace serves both the persistent
                # artifact and this process's executable (plain jax.jit
                # when the cache is off or the trace is unexportable)
                jitted = _cc.export_jit(
                    gen_fn, (params, buffers, ids_p, t0v, key), fp=fp,
                    name='GPT.generate')
            self._gen_cache[sig] = jitted
        new = jitted(params, buffers, ids_p, t0v, key)
        return Tensor(jnp.concatenate([ids, new], axis=1))

    def precompile_decode(self, batch_size, prompt_len, max_new_tokens,
                          temperature=1.0, top_k=None):
        """AOT warm start for one decode bucket: build, export and
        persist the decode module `generate` would compile for this
        (batch, bucketed prompt, new tokens, sampling) signature —
        without running it.  Returns (fingerprint, prompt_bucket).
        ``tools/precompile.py`` drives this over the declared serving
        bucket set at export time; a later worker's ``generate``
        deserializes the artifact instead of re-tracing."""
        import jax
        import jax.numpy as jnp
        from ..core import compile_cache as _cc
        if prompt_len + int(max_new_tokens) > self.config.max_seq_len:
            raise ValueError(
                f'prompt+new tokens {prompt_len + int(max_new_tokens)} '
                f'exceeds max_seq_len {self.config.max_seq_len}')
        gen_fn, fp, _ck, P = self._decode_program(
            int(batch_size), int(prompt_len), int(max_new_tokens),
            temperature, top_k)
        if fp is None or not _cc.enabled():
            return fp, P
        if _cc.get('exec', fp, name='precompile_decode') is None:
            params, buffers = self.functional_state()
            example = (params, buffers,
                       jnp.zeros((int(batch_size), P), jnp.int64),
                       jnp.asarray(P, jnp.int32), jax.random.PRNGKey(0))
            _cc.store_executable(fp, jax.jit(gen_fn), example,
                                 name='GPT.generate', aot_compile=True)
        return fp, P

    def _decode_bucket(self, T0, max_new_tokens):
        """Prompt bucket for one decode signature: next power of two
        (capped so bucket + new tokens fit max_seq_len).  MoE configs
        are exempt — padded garbage tokens would compete with real
        ones for expert capacity in prefill."""
        from ..core import compile_cache as _cc
        cfg = self.config
        if cfg.moe_num_experts > 0:
            return T0
        return _cc.bucket_pow2(T0, cap=cfg.max_seq_len - max_new_tokens)

    def _decode_program(self, B, T0, max_new_tokens, temperature,
                        top_k, params=None):
        """Build the decode function + its shared cache fingerprint for
        one signature.  Returns (gen_fn, fingerprint, module_key,
        prompt_bucket); gen_fn(params, buffers, ids[B, bucket],
        t0_scalar, key) -> new tokens [B, max_new_tokens].  `params`
        (shapes only are read) saves callers that already hold the
        functional state a second full tree walk."""
        import jax
        import jax.numpy as jnp
        from ..core import compile_cache as _cc
        from ..jit import functional_call

        cfg = self.config
        P = self._decode_bucket(T0, max_new_tokens)
        Tmax = P + max_new_tokens
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        L = cfg.num_layers
        model = self
        greedy = temperature == 0 or temperature is None

        # shared key discipline (ops/sampling): the token at absolute
        # position `pos` of row `r` is drawn with
        # fold_in(fold_in(base, pos), r) — a pure function of (seed,
        # position, row), NOT of the split-chain history.  The paged
        # serving engine derives per-request keys under the same rule
        # (row 0), which is what makes sampled engine-vs-generate
        # parity and mid-stream retry replay bit-exact.
        from ..ops.sampling import sample_rows as _sample_rows

        def sample(logits, base, pos):
            return _sample_rows(logits, base, pos, temperature, top_k)

        # scan-over-layers decode: ONE block body over stacked
        # per-layer params — ~L-times less HLO in the decode module
        # than inlining every block into the token scan (the unrolled
        # form's ~900 s remote compile is what wedged the round-4
        # tunnel).  Needs a homogeneous stack (no MoE blocks).
        use_scan = (cfg.scan_decode_blocks and L > 1
                    and cfg.moe_num_experts == 0)
        blocks_prefix = 'gpt.blocks.'
        block0 = self.gpt.blocks[0]

        def _sub(tree, prefix):
            return {k[len(prefix):]: v for k, v in tree.items()
                    if k.startswith(prefix)}

        def _stacked(tree):
            """{'0.attn.qkv.weight': v, ...} → {'attn.qkv.weight':
            [L, ...]} — per-layer leaves stacked for lax.scan."""
            per = {}
            for k, v in _sub(tree, blocks_prefix).items():
                i, sub = k.split('.', 1)
                per.setdefault(sub, [None] * L)[int(i)] = v
            return {k: jnp.stack(vs) for k, vs in per.items()}

        def _scan_blocks(x, stacked_p, stacked_b, k_all, v_all, p):
            """Run the homogeneous block stack as one lax.scan; caches
            ride as [L, B, nh, Tmax, hd] xs/ys."""
            def layer_body(xc, per_layer):
                lp, lb, kc, vc = per_layer
                (xc, (nk, nv)), _ = functional_call(
                    block0, lp, lb, (xc,),
                    kwargs={'cache': (kc, vc), 'pos': p},
                    training=False)
                return xc, (nk, nv)
            x, (nk_all, nv_all) = jax.lax.scan(
                layer_body, x, (stacked_p, stacked_b, k_all, v_all))
            return x, nk_all, nv_all

        def _scan_step(state, ids_t, p, cache):
            """Embeddings → scanned blocks → ln_f → tied head, built
            from the same sublayers the unrolled path runs (dropout is
            identity in eval).  `state` carries the per-layer stacks
            computed ONCE per generate call (stacking in here would
            re-emit L-way stacks into every token-scan body) plus only
            the NON-block subtrees — threading the full params dict
            through would keep a second unused copy of every block
            weight live in the module.  (The stacks themselves still
            double block-weight HBM versus the unrolled form for the
            duration of the call — the price of the smaller module.)"""
            params, buffers, stacked_p, stacked_b = state
            k_all, v_all = cache
            T = ids_t.shape[1]
            posv = p.reshape(()).astype(jnp.int64) \
                + jnp.arange(T, dtype=jnp.int64)
            emb, _ = functional_call(
                model.gpt.wte, _sub(params, 'gpt.wte.'),
                _sub(buffers, 'gpt.wte.'), (ids_t,), training=False)
            pe, _ = functional_call(
                model.gpt.wpe, _sub(params, 'gpt.wpe.'),
                _sub(buffers, 'gpt.wpe.'), (posv,), training=False)
            x, nk_all, nv_all = _scan_blocks(
                emb + pe, stacked_p, stacked_b, k_all, v_all, p)
            h, _ = functional_call(
                model.gpt.ln_f, _sub(params, 'gpt.ln_f.'),
                _sub(buffers, 'gpt.ln_f.'), (x,), training=False)
            logits = jnp.einsum('bth,vh->btv', h,
                                params['gpt.wte.weight'])
            return logits, (nk_all, nv_all)

        def _unrolled_prefill(state, ids_t, p, caches):
            # the factored serving-shared entry points: generate's
            # prefill and token steps run the SAME pure cached forward
            # the serving engine calls (prefill()/decode_step()), so
            # batch-1 generate and the continuous-batching engine can
            # never drift apart numerically
            return model.prefill(*state, ids_t, p, caches)

        def _unrolled_decode(state, tok_t, p, caches):
            return model.decode_step(*state, tok_t, p, caches)

        def _make_gen(prepare, step, init_cache, decode=None):
            """One decode loop for both block forms: prefill (padded to
            the bucket, true prompt length `t0` traced), sample at row
            t0-1, then a token lax.scan over `step` starting at
            position t0.  Bucketing stays bit-exact: rows < t0 only
            attend real columns, the garbage k/v the padded prefill
            rows wrote at t0..P-1 is overwritten by each decoded
            token's slot BEFORE the causal mask (col <= row) can ever
            expose it, and the masked softmax tail underflows to exact
            zeros."""
            decode = decode or step

            def gen(params, buffers, ids, t0, key):
                state = prepare(params, buffers)
                logits, cache = step(state, ids,
                                     jnp.zeros((), jnp.int32),
                                     init_cache())
                # key is the per-call BASE; each sampled token derives
                # its own key from its absolute position (t0-1 for the
                # prefill sample, p for each scan step)
                tok = sample(jnp.take(logits, t0 - 1, axis=1),
                             key, t0 - 1)  # [B]

                def body(carry, _):
                    tok, p, cache = carry
                    logits, cache = decode(state, tok[:, None], p,
                                           cache)
                    ntok = sample(logits[:, -1], key, p)
                    return (ntok, p + 1, cache), tok

                (last, _, _), toks = jax.lax.scan(
                    body, (tok, t0, cache),
                    None, length=max_new_tokens - 1)
                return jnp.concatenate(
                    [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
            return gen

        def _nonblock(tree):
            return {k: v for k, v in tree.items()
                    if not k.startswith(blocks_prefix)}

        if use_scan:
            gen_fn = _make_gen(
                lambda p, b: (_nonblock(p), _nonblock(b),
                              _stacked(p), _stacked(b)),
                _scan_step,
                lambda: (jnp.zeros((L, B, nh, Tmax, hd), jnp.float32),
                         jnp.zeros((L, B, nh, Tmax, hd), jnp.float32)))
        else:
            gen_fn = _make_gen(
                lambda p, b: (p, b),
                _unrolled_prefill,
                lambda: model.init_decode_caches(B, Tmax),
                decode=_unrolled_decode)

        # the decode signature keys the module: bucketed prompt P (not
        # T0), so every prompt length in a bucket reuses ONE compiled
        # module, in-process and across processes
        if params is None:
            params, _ = self.functional_state()
        pspec = tuple(sorted((n, tuple(v.shape), str(v.dtype))
                             for n, v in params.items()))
        fp = _cc.fingerprint(
            'gpt-decode', config=tuple(sorted(vars(cfg).items())),
            params=pspec, batch=B, prompt_bucket=P, new=max_new_tokens,
            sampling=(greedy, float(temperature or 0.0), top_k),
            scan=use_scan,
            # sampled modules draw keys per absolute position (the
            # ops/sampling discipline) — a pre-discipline artifact
            # would replay the old split-chain stream, so the marker
            # bumps SAMPLED fingerprints only (greedy HLO never reads
            # the key; those artifacts stay valid and cache-hit)
            **({} if greedy else {'key_discipline': 'per-pos-row'}),
            # prompt-ids aval dtype follows the x64 setting — a module
            # exported under one setting must not be handed the other
            ids_dtype=str(jnp.asarray(0, jnp.int64).dtype))
        ck = fp or ('gen', B, P, max_new_tokens, greedy,
                    float(temperature or 0.0), top_k, use_scan)
        return gen_fn, fp, ck, P

    def as_pipeline_module(self, num_stages, mesh):
        """Adapter for the 1F1B pipeline engine (parallel.pipeline_1f1b):
        repacks parameters into shared/stage-stacked pytrees and exposes
        pure stage functions.  See models/gpt_pipe.py."""
        from .gpt_pipe import GPTPipeModule
        return GPTPipeModule(self, num_stages, mesh)


def gpt_tiny(**kw):
    """4-layer toy config for tests/dryruns."""
    kw.setdefault('vocab_size', 128)
    kw.setdefault('hidden_size', 64)
    kw.setdefault('num_layers', 4)
    kw.setdefault('num_heads', 4)
    kw.setdefault('max_seq_len', 128)
    kw.setdefault('dropout', 0.0)
    return GPTForCausalLM(GPTConfig(**kw))


def gpt_moe_tiny(**kw):
    """gpt_tiny with routed experts on alternating blocks — the ep-axis
    dryrun/test config."""
    kw.setdefault('moe_num_experts', 4)
    kw.setdefault('moe_top_k', 1)
    return gpt_tiny(**kw)


def gpt_small(**kw):
    """GPT-2 small (117M)."""
    kw.setdefault('hidden_size', 768)
    kw.setdefault('num_layers', 12)
    kw.setdefault('num_heads', 12)
    return GPTForCausalLM(GPTConfig(**kw))


def gpt_1p3b(**kw):
    """GPT-3 XL-ish 1.3B — the hybrid-parallel benchmark config
    (SURVEY.md §3 item 4)."""
    kw.setdefault('hidden_size', 2048)
    kw.setdefault('num_layers', 24)
    kw.setdefault('num_heads', 16)
    kw.setdefault('max_seq_len', 2048)
    return GPTForCausalLM(GPTConfig(**kw))
