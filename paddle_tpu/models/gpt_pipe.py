"""Pipeline-functional GPT: the flagship model on the 1F1B engine.

Reference analogue: GPTForCausalLMPipe-style models built on
/root/reference/python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py
(PipelineLayer + LayerDesc segmenting the layer list onto pp ranks) with
mp_layers.py inside each stage.  TPU-native: the Layer tree's parameters
are repacked ONCE into a pipeline pytree —

  shared : {wte, wpe, lnf_w, lnf_b}        replicated over pp
           (wte is tied: embedding on stage 0, LM head on stage S-1;
           its gradient totals both via the engine's pp-psum)
  stages : per-block leaves stacked [S, L/S, ...], leading dim sharded
           on 'pp' so every stage holds ONLY its blocks' weights

— and the stage forward is pure jnp with hand-written tensor-parallel
collectives: qkv/fc are column-split over 'tp' (no comm), proj/fc2 are
row-split (one lax.psum each), matching the Megatron split the GSPMD
path (models/gpt.py) expresses via PartitionSpecs.  The qkv weight is
repacked [H, 3, nh, hd] with heads on the tp dim so a contiguous shard
is exactly `nh/tp` complete heads.

Dropout must be 0 in pipeline mode (the engine recomputes forwards in
the backward tick; stochastic layers would need per-(mb, tick) key
threading — not wired yet, and the reference disables dropout variance
across recompute the same way).
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['GPTPipeModule']


def _ln(x, w, b, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * w + b


class GPTPipeModule:
    """Adapter: GPTForCausalLM -> (params, specs, stage fns) for
    parallel.pipeline_1f1b.pipeline_value_and_grad."""

    def __init__(self, model, num_stages, mesh, tp_axis='tp',
                 ep_axis='ep'):
        cfg = model.config
        assert cfg.num_layers % num_stages == 0, (
            f'num_layers {cfg.num_layers} % pp {num_stages} != 0')
        assert cfg.dropout == 0.0, (
            'pipeline engine requires dropout=0 (recompute-backward)')
        self.model = model
        self.cfg = cfg
        self.S = num_stages
        self.mesh = mesh
        self.tp = dict(mesh.shape).get(tp_axis, 1)
        self.tp_axis = tp_axis
        self.ep = dict(mesh.shape).get(ep_axis, 1)
        self.ep_axis = ep_axis
        assert cfg.num_heads % self.tp == 0
        assert cfg.intermediate_size % self.tp == 0
        # MoE in the pipeline: every block routed (homogeneous lax.scan
        # over layers), experts sharded on 'ep'.  The load-balance aux
        # loss is NOT emitted on this path — the 1F1B engine
        # differentiates the last stage's loss only; capacity dropping
        # still bounds expert load.  (The GSPMD path carries aux.)
        self.moe = cfg.moe_num_experts > 0
        if self.moe:
            assert cfg.moe_every == 1, (
                'pipeline MoE needs moe_every=1 (homogeneous stages for '
                'the scan-over-layers); got moe_every='
                f'{cfg.moe_every}')
            assert cfg.moe_top_k == 1, 'pipeline MoE is top-1 (Switch)'
            assert cfg.moe_num_experts % self.ep == 0, (
                f'experts {cfg.moe_num_experts} % ep {self.ep} != 0')
        self.params = self._extract()
        self.stage_specs = self._specs()

    # -- param repacking -----------------------------------------------------
    def _extract(self):
        m, cfg = self.model, self.cfg
        g = m.gpt
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        H, I = cfg.hidden_size, cfg.intermediate_size

        def stack(getter):
            return jnp.stack([jnp.asarray(getter(blk).value)
                              for blk in g.blocks])

        blocks = {
            'ln1_w': stack(lambda b: b.ln1.weight),
            'ln1_b': stack(lambda b: b.ln1.bias),
            # [L, H, 3H] -> [L, H, 3, nh, hd]: heads contiguous on dim 3
            'qkv_w': stack(lambda b: b.attn.qkv.weight).reshape(
                (-1, H, 3, nh, hd)),
            'qkv_b': stack(lambda b: b.attn.qkv.bias).reshape(
                (-1, 3, nh, hd)),
            # [L, H, H] rows are (nh, hd)-ordered input features
            'proj_w': stack(lambda b: b.attn.proj.weight).reshape(
                (-1, nh, hd, H)),
            'proj_b': stack(lambda b: b.attn.proj.bias),
            'ln2_w': stack(lambda b: b.ln2.weight),
            'ln2_b': stack(lambda b: b.ln2.bias),
        }
        if self.moe:
            blocks.update({
                'gate_w': stack(lambda b: b.mlp.gate_w),
                'moe_w1': stack(lambda b: b.mlp.w1),
                'moe_b1': stack(lambda b: b.mlp.b1),
                'moe_w2': stack(lambda b: b.mlp.w2),
                'moe_b2': stack(lambda b: b.mlp.b2),
            })
        else:
            blocks.update({
                'fc_w': stack(lambda b: b.mlp.fc.weight),
                'fc_b': stack(lambda b: b.mlp.fc.bias),
                'fc2_w': stack(lambda b: b.mlp.proj.weight),
                'fc2_b': stack(lambda b: b.mlp.proj.bias),
            })
        S = self.S
        stages = {k: v.reshape((S, v.shape[0] // S) + v.shape[1:])
                  for k, v in blocks.items()}
        shared = {
            'wte': jnp.asarray(g.wte.weight.value),
            'wpe': jnp.asarray(g.wpe.weight.value),
            'lnf_w': jnp.asarray(g.ln_f.weight.value),
            'lnf_b': jnp.asarray(g.ln_f.bias.value),
        }
        return {'shared': shared, 'stages': stages}

    def restore(self, params):
        """Write a (trained) pipeline pytree back into the live Layer."""
        m, cfg = self.model, self.cfg
        g = m.gpt
        H = cfg.hidden_size
        sh, st = params['shared'], params['stages']
        g.wte.weight.value = jnp.asarray(sh['wte'])
        g.wpe.weight.value = jnp.asarray(sh['wpe'])
        g.ln_f.weight.value = jnp.asarray(sh['lnf_w'])
        g.ln_f.bias.value = jnp.asarray(sh['lnf_b'])
        flat = {k: np.asarray(v).reshape((-1,) + v.shape[2:])
                for k, v in st.items()}
        for i, blk in enumerate(g.blocks):
            blk.ln1.weight.value = jnp.asarray(flat['ln1_w'][i])
            blk.ln1.bias.value = jnp.asarray(flat['ln1_b'][i])
            blk.attn.qkv.weight.value = jnp.asarray(
                flat['qkv_w'][i].reshape(H, -1))
            blk.attn.qkv.bias.value = jnp.asarray(
                flat['qkv_b'][i].reshape(-1))
            blk.attn.proj.weight.value = jnp.asarray(
                flat['proj_w'][i].reshape(H, H))
            blk.attn.proj.bias.value = jnp.asarray(flat['proj_b'][i])
            blk.ln2.weight.value = jnp.asarray(flat['ln2_w'][i])
            blk.ln2.bias.value = jnp.asarray(flat['ln2_b'][i])
            if self.moe:
                blk.mlp.gate_w.value = jnp.asarray(flat['gate_w'][i])
                blk.mlp.w1.value = jnp.asarray(flat['moe_w1'][i])
                blk.mlp.b1.value = jnp.asarray(flat['moe_b1'][i])
                blk.mlp.w2.value = jnp.asarray(flat['moe_w2'][i])
                blk.mlp.b2.value = jnp.asarray(flat['moe_b2'][i])
            else:
                blk.mlp.fc.weight.value = jnp.asarray(flat['fc_w'][i])
                blk.mlp.fc.bias.value = jnp.asarray(flat['fc_b'][i])
                blk.mlp.proj.weight.value = jnp.asarray(flat['fc2_w'][i])
                blk.mlp.proj.bias.value = jnp.asarray(flat['fc2_b'][i])

    def _specs(self):
        """GLOBAL PartitionSpecs for the stage leaves: [S, L/S, ...] with
        'pp' leading; 'tp' on the head dim (qkv/proj) or the
        intermediate dim (fc/fc2) — the Megatron column/row split."""
        t = self.tp_axis
        specs = {
            'ln1_w': P('pp'), 'ln1_b': P('pp'),
            'qkv_w': P('pp', None, None, None, t, None),
            'qkv_b': P('pp', None, None, t, None),
            'proj_w': P('pp', None, t, None, None),
            'proj_b': P('pp'),
            'ln2_w': P('pp'), 'ln2_b': P('pp'),
        }
        if self.moe:
            e = self.ep_axis
            specs.update({
                'gate_w': P('pp'),                       # replicated gate
                'moe_w1': P('pp', None, e, None, None),  # [S,L/S,E,H,F]
                'moe_b1': P('pp', None, e, None, None),
                'moe_w2': P('pp', None, e, None, None),
                'moe_b2': P('pp', None, e, None, None),
            })
        else:
            specs.update({
                'fc_w': P('pp', None, None, t),
                'fc_b': P('pp', None, t),
                'fc2_w': P('pp', None, t, None),
                'fc2_b': P('pp'),
            })
        return specs

    # -- stage functions (pure jnp, run inside shard_map) --------------------
    def first_fn(self, shared, ids_1mb):
        """Token + position embedding (stage 0 only)."""
        T = ids_1mb.shape[-1]
        x = jnp.take(shared['wte'], ids_1mb, axis=0)
        return x + shared['wpe'][:T]

    def _block(self, bp, x):
        """One transformer block on the local tp shard of heads/ffn.
        bp leaves have NO layer dim (scanned out)."""
        cfg = self.cfg
        eps = cfg.layer_norm_epsilon
        hd = cfg.hidden_size // cfg.num_heads
        tp_on = self.tp > 1

        h = _ln(x, bp['ln1_w'], bp['ln1_b'], eps)
        y = jnp.einsum('bth,hcnd->btcnd', h, bp['qkv_w']) + bp['qkv_b']
        q, k, v = y[:, :, 0], y[:, :, 1], y[:, :, 2]  # [mb,T,nh_l,hd]
        att = jnp.einsum('btnd,bsnd->bnts', q, k) / math.sqrt(hd)
        T = x.shape[1]
        mask = jnp.tril(jnp.ones((T, T), att.dtype))
        att = att - (1.0 - mask) * 1e9
        att = jax.nn.softmax(att, axis=-1)
        o = jnp.einsum('bnts,bsnd->btnd', att, v)
        o = jnp.einsum('btnd,ndh->bth', o, bp['proj_w'])
        if tp_on:
            o = jax.lax.psum(o, self.tp_axis)  # row-parallel reduce
        x = x + o + bp['proj_b']

        h = _ln(x, bp['ln2_w'], bp['ln2_b'], eps)
        if self.moe:
            return x + self._moe_mlp(bp, h)
        u = jax.nn.gelu(jnp.einsum('bth,hi->bti', h, bp['fc_w'])
                        + bp['fc_b'], approximate=True)
        u = jnp.einsum('bti,ih->bth', u, bp['fc2_w'])
        if tp_on:
            u = jax.lax.psum(u, self.tp_axis)
        return x + u + bp['fc2_b']

    def _moe_mlp(self, bp, h):
        """Switch (top-1) expert MLP on the LOCAL ep shard of experts.

        Same routing math as incubate.moe.SwitchMoE (dense dispatch/
        combine, capacity drop), but with HAND-WRITTEN sharding: the
        tokens are replicated over 'ep' inside the pipeline's shard_map,
        each shard computes only its E/ep experts' slice of the dispatch
        einsum, and ONE psum('ep') combines — the manual form of the
        all-to-all XLA infers on the GSPMD path."""
        cfg = self.cfg
        E = cfg.moe_num_experts
        E_l = E // self.ep
        act = jax.nn.gelu      # SwitchMoE's default (incubate/moe.py)

        mb, T, H = h.shape
        S = mb * T
        import math as _math
        C = max(1, int(_math.ceil(S / E * cfg.moe_capacity_factor)))
        xs = h.reshape(S, H)
        logits = xs.astype(jnp.float32) @ bp['gate_w'].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)          # [S, E]
        idx = jnp.argmax(probs, axis=-1)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        gate = jnp.sum(probs * onehot, axis=-1)          # [S]
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
        keep = (pos < C) & (onehot > 0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)
        sel = slot * keep.astype(jnp.float32)[..., None]  # [S, E, C]
        dispatch = sel.astype(xs.dtype)
        combine = sel * gate[:, None, None]

        if self.ep > 1:
            e0 = jax.lax.axis_index(self.ep_axis) * E_l
            dispatch_l = jax.lax.dynamic_slice_in_dim(dispatch, e0, E_l, 1)
            combine_l = jax.lax.dynamic_slice_in_dim(combine, e0, E_l, 1)
        else:
            dispatch_l, combine_l = dispatch, combine

        ein = jnp.einsum('sec,sh->ech', dispatch_l, xs)  # [E_l, C, H]
        u = act(jnp.einsum('ech,ehf->ecf', ein, bp['moe_w1'])
                + bp['moe_b1'].astype(ein.dtype))
        out = jnp.einsum('ecf,efh->ech', u, bp['moe_w2']) \
            + bp['moe_b2'].astype(u.dtype)
        y = jnp.einsum('ech,sec->sh', out, combine_l.astype(out.dtype))
        if self.ep > 1:
            y = jax.lax.psum(y, self.ep_axis)
        return y.reshape(mb, T, H)

    def stage_fn(self, shared, stage_p, x, rank):
        """Apply this stage's L/S blocks via lax.scan over the stacked
        layer dim (one traced block, the scan-over-layers idiom).
        `shared`/`rank` unused: GPT stages are homogeneous."""
        del shared, rank
        def body(x, layer_p):
            return self._block(layer_p, x), None
        x, _ = jax.lax.scan(body, x, stage_p)
        return x

    def last_fn(self, shared, y, labels_1mb):
        """Final LN + tied LM head + shifted causal-LM loss (stage S-1)."""
        cfg = self.cfg
        h = _ln(y, shared['lnf_w'], shared['lnf_b'],
                cfg.layer_norm_epsilon)
        logits = jnp.einsum('bth,vh->btv', h, shared['wte'])
        lg = logits[:, :-1, :]
        lb = labels_1mb[:, 1:]
        logp = jax.nn.log_softmax(lg, axis=-1)
        nll = -jnp.take_along_axis(logp, lb[..., None].astype(jnp.int32),
                                   axis=-1)[..., 0]
        return nll.mean()
