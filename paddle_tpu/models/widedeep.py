"""Wide&Deep and DeepFM — the sparse-embedding recommender models.

Reference analogue: the reference serves these via the brpc parameter
server (fleet/runtime, distributed lookup_table ops): sparse rows live
on PS shards and workers pull/push.  TPU-native substitute (SURVEY.md
§2 item 34): ALL fields share one fused embedding table addressed by
per-field offsets — a single large `gather` the MXU-adjacent memory
system handles natively — and the table shards over the `tp` mesh axis
via VocabParallelEmbedding, so "parameter server" becomes "table rows
spread over chips + XLA-partitioned gather", with the fleet PS API
(init_server/init_worker/...) kept as no-op-compatible surface.
"""
import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.meta_parallel import VocabParallelEmbedding
from ..tensor import creation, manipulation, math as pmath

__all__ = ['WideDeep', 'DeepFM']


class _FusedSparseEmbedding(nn.Layer):
    """One table for all sparse fields; ids are per-field local and get
    offset into the fused vocab.  shard=True puts rows on the tp axis."""

    def __init__(self, field_dims, embed_dim, shard=False):
        super().__init__()
        total = int(sum(field_dims))
        self.offsets = np.array(
            [0] + list(np.cumsum(field_dims)[:-1]), dtype='int64')
        if shard:
            self.table = VocabParallelEmbedding(total, embed_dim)
        else:
            self.table = nn.Embedding(total, embed_dim)

    def forward(self, ids):
        """ids [B, F] (field-local) → embeddings [B, F, E]."""
        off = Tensor(self.offsets)
        return self.table(ids + off)


class _PerFieldSparseEmbedding(nn.Layer):
    """Reference-style per-field tables — F separate gathers + stack
    (the shape of the reference's per-slot lookup_table calls,
    fleet/runtime/the_one_ps.py:417).  Kept as the baseline arm of the
    fused-vs-per-field gather A/B (tools/bench_widedeep_gather.py,
    PERF round-3 lead 3); the fused single-table gather is the
    default."""

    def __init__(self, field_dims, embed_dim):
        super().__init__()
        self.tables = nn.LayerList(
            [nn.Embedding(int(d), embed_dim) for d in field_dims])

    def forward(self, ids):
        """ids [B, F] (field-local) → embeddings [B, F, E]."""
        cols = [t(ids[:, i]) for i, t in enumerate(self.tables)]
        return manipulation.stack(cols, axis=1)


class WideDeep(nn.Layer):
    """wide (1st-order sparse + dense linear) + deep (embeddings→MLP).

    Args:
        sparse_field_dims: vocab size per sparse field.
        dense_dim: number of dense float features (0 to disable).
        embed_dim: deep embedding width.
        hidden: deep MLP widths.
        shard_vocab: shard the fused tables over the tp mesh axis.
        fused_gather: one offset-addressed table per role (default) vs
            reference-style per-field tables (A/B baseline; not
            shardable over tp).
    """

    def __init__(self, sparse_field_dims, dense_dim=0, embed_dim=16,
                 hidden=(64, 32), shard_vocab=False, fused_gather=True):
        super().__init__()
        self.dense_dim = dense_dim
        f = len(sparse_field_dims)
        if not fused_gather and shard_vocab:
            raise ValueError('per-field tables (fused_gather=False) '
                             'do not shard over tp; use the fused '
                             'table for shard_vocab=True')
        if fused_gather:
            self.wide = _FusedSparseEmbedding(sparse_field_dims, 1,
                                              shard=shard_vocab)
            self.deep_emb = _FusedSparseEmbedding(sparse_field_dims,
                                                  embed_dim,
                                                  shard=shard_vocab)
        else:
            self.wide = _PerFieldSparseEmbedding(sparse_field_dims, 1)
            self.deep_emb = _PerFieldSparseEmbedding(sparse_field_dims,
                                                     embed_dim)
        layers = []
        in_dim = f * embed_dim + dense_dim
        for h in hidden:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.deep = nn.Sequential(*layers)
        self.dense_linear = nn.Linear(dense_dim, 1) if dense_dim else None
        self.bias = self.create_parameter([1], is_bias=True)

    def forward(self, sparse_ids, dense=None):
        B = sparse_ids.shape[0]
        wide = pmath.sum(self.wide(sparse_ids), axis=[1, 2],
                         keepdim=True)[:, :, 0]        # [B, 1]
        emb = self.deep_emb(sparse_ids)                 # [B, F, E]
        deep_in = manipulation.reshape(emb, [B, -1])
        if self.dense_linear is not None and dense is not None:
            wide = wide + self.dense_linear(dense)
            deep_in = manipulation.concat([deep_in, dense], axis=1)
        deep = self.deep(deep_in)                       # [B, 1]
        return wide + deep + self.bias


class DeepFM(nn.Layer):
    """Factorization-machine second-order interactions + deep MLP over
    the same fused embeddings (one gather feeds both)."""

    def __init__(self, sparse_field_dims, dense_dim=0, embed_dim=16,
                 hidden=(64, 32), shard_vocab=False):
        super().__init__()
        self.dense_dim = dense_dim
        f = len(sparse_field_dims)
        self.first_order = _FusedSparseEmbedding(sparse_field_dims, 1,
                                                 shard=shard_vocab)
        self.emb = _FusedSparseEmbedding(sparse_field_dims, embed_dim,
                                         shard=shard_vocab)
        layers = []
        in_dim = f * embed_dim + dense_dim
        for h in hidden:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.deep = nn.Sequential(*layers)
        self.dense_linear = nn.Linear(dense_dim, 1) if dense_dim else None
        self.bias = self.create_parameter([1], is_bias=True)

    def forward(self, sparse_ids, dense=None):
        B = sparse_ids.shape[0]
        first = pmath.sum(self.first_order(sparse_ids), axis=[1, 2],
                          keepdim=True)[:, :, 0]        # [B, 1]
        e = self.emb(sparse_ids)                        # [B, F, E]
        # FM: 0.5 * ((sum_f e)^2 - sum_f e^2), summed over E
        s = pmath.sum(e, axis=1)                        # [B, E]
        fm = 0.5 * pmath.sum(s * s - pmath.sum(e * e, axis=1),
                             axis=1, keepdim=True)      # [B, 1]
        deep_in = manipulation.reshape(e, [B, -1])
        if self.dense_linear is not None and dense is not None:
            first = first + self.dense_linear(dense)
            deep_in = manipulation.concat([deep_in, dense], axis=1)
        deep = self.deep(deep_in)
        return first + fm + deep + self.bias
