"""paddle.incubate parity namespace.

The reference uses incubate/ for pre-stable features; here the
TPU-native experimental pieces live in stable modules already
(ops.flash_attention, ops.ring_attention, parallel.pipeline), so
incubate re-exports them under the familiar names.
"""
from ..ops.flash_attention import flash_attention  # noqa: F401
from ..ops.ring_attention import ring_attention, ring_attention_spmd  # noqa: F401
from ..parallel.pipeline import gpipe_spmd  # noqa: F401
from .host_embedding import HostOffloadEmbedding  # noqa: F401
from .moe import SwitchMoE  # noqa: F401
from . import optimizer  # noqa: F401

__all__ = ['flash_attention', 'ring_attention', 'ring_attention_spmd',
           'gpipe_spmd', 'HostOffloadEmbedding', 'SwitchMoE',
           'optimizer']
from . import checkpoint  # noqa: F401
