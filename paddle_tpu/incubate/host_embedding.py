"""Host-offloaded large-vocab embedding — the TPU-native
parameter-server substitute.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/runtime/the_one_ps.py:417
and parameter_server_runtime.py:32: sparse tables live on parameter
servers (host DRAM), workers pull rows for the current batch and push
gradients back asynchronously (`strategy.a_sync`).  TPU-native mapping:

  * the table is a HOST numpy array — vocab size is bounded by host
    DRAM, not the chip's HBM (the reason PS mode exists);
  * the "pull" is a `jax.pure_callback` gather of exactly the batch's
    rows — the only thing that ever enters HBM is a [B*S, D] slab;
  * the "push" is an ordered `jax.experimental.io_callback` in the
    custom VJP: the row gradients leave the device and the HOST applies
    the optimizer rule (SGD or Adagrad) immediately — the device-side
    optimizer never sees the table, exactly like a PS worker whose
    dense step is separate from the server's sparse update;
  * `a_sync` semantics: the host update is fire-and-forget from the
    device's point of view (the next lookup may or may not observe it,
    matching the reference's asynchronous SGD staleness contract).

Works eagerly and inside jit/ParallelTrainer (callbacks ride the
compiled module). Duplicate ids within a batch accumulate their
gradients before the update (scatter-add), like the reference's sparse
gradient merge.  Out-of-range ids raise (like nn.Embedding).

SINGLE-HOST ONLY for now: each process would hold an independent table
copy with no cross-host aggregation (the reference solves this with a
central server); the constructor rejects jax.process_count() > 1.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..core.dispatch import apply
from ..tensor._helpers import wrap

__all__ = ['HostOffloadEmbedding']


class HostOffloadEmbedding(Layer):
    """Embedding with a host-resident table and host-side sparse update.

    Args:
        num_embeddings: vocab size (host-DRAM bounded).
        embedding_dim:  row width.
        learning_rate:  host-side update step size.
        optimizer:      'sgd' or 'adagrad' (the reference PS's sparse
                        optimizers; adagrad keeps a host accumulator).
        trainable:      False freezes the table (pull-only).
    """

    def __init__(self, num_embeddings, embedding_dim, learning_rate=0.01,
                 optimizer='sgd', trainable=True, dtype='float32',
                 seed=None, entry=None):
        super().__init__()
        if optimizer not in ('sgd', 'adagrad'):
            raise ValueError(f'unsupported host optimizer {optimizer!r}')
        if jax.process_count() > 1:
            raise NotImplementedError(
                'HostOffloadEmbedding is single-host: each process '
                'would hold a divergent table copy (no cross-host '
                'aggregation server); use fleet VocabParallelEmbedding '
                'for multi-host sparse tables')
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.learning_rate = float(learning_rate)
        self.optimizer = optimizer
        self.trainable = trainable
        self._np_dtype = np.dtype(dtype)
        if seed is None:
            from ..core import rng as rng_mod
            seed = rng_mod.get_seed()
        rs = np.random.RandomState(seed)
        bound = 1.0 / np.sqrt(self.embedding_dim)
        self.table = rs.uniform(
            -bound, bound,
            (self.num_embeddings, self.embedding_dim)).astype(self._np_dtype)
        self._accum = (np.zeros_like(self.table)
                       if optimizer == 'adagrad' else None)
        # entry admission (reference distributed/entry_attr.py): gate the
        # sparse update per row — see _admitted()
        from ..distributed.entry_attr import (EntryAttr, ProbabilityEntry,
                                              CountFilterEntry)
        if entry is not None and not isinstance(entry, EntryAttr):
            raise TypeError('entry must be a ProbabilityEntry or '
                            'CountFilterEntry')
        self.entry = entry
        self._entry_rng = np.random.RandomState(
            (seed if seed is not None else 0) ^ 0x5eed)
        if isinstance(entry, CountFilterEntry):
            self._counts = np.zeros((self.num_embeddings,), np.int64)
        elif isinstance(entry, ProbabilityEntry):
            # -1 undecided, 0 rejected, 1 admitted
            self._admit_flag = np.full((self.num_embeddings,), -1, np.int8)
        # a zero scalar device parameter that rides through the lookup:
        # ids are integers, so without a float input on the op the
        # autograd tape would mark the output stop_gradient and the
        # backward push would never fire (it also keeps the op inside
        # the compiled step's differentiated region under jit)
        from ..nn import initializer as I
        self._anchor = self.create_parameter(
            [1], attr=None, dtype='float32',
            default_initializer=I.Constant(0.0))
        self._lookup = self._build_lookup()

    # -- host side -----------------------------------------------------------
    def _check_ids(self, ids):
        ids = np.asarray(ids).astype(np.int64)
        if ids.size and (ids.min() < 0
                         or ids.max() >= self.num_embeddings):
            raise ValueError(
                f'HostOffloadEmbedding: id out of range [0, '
                f'{self.num_embeddings}) — got '
                f'[{ids.min()}, {ids.max()}]')
        return ids

    def _host_gather(self, ids):
        return self.table[self._check_ids(ids)]

    def _admitted(self, uniq, counts_in_batch):
        """Entry-admission mask over the batch's unique rows (reference
        PS admits features probabilistically or after a show count)."""
        from ..distributed.entry_attr import (ProbabilityEntry,
                                              CountFilterEntry)
        if isinstance(self.entry, CountFilterEntry):
            self._counts[uniq] += counts_in_batch
            return self._counts[uniq] >= self.entry._count_filter
        if isinstance(self.entry, ProbabilityEntry):
            undecided = self._admit_flag[uniq] == -1
            if undecided.any():
                draws = (self._entry_rng.rand(int(undecided.sum()))
                         < self.entry._probability).astype(np.int8)
                self._admit_flag[uniq[undecided]] = draws
            return self._admit_flag[uniq] == 1
        return np.ones(uniq.shape[0], bool)

    def _host_push(self, ids, grad):
        """Sparse update: accumulate duplicate ids, apply the rule."""
        ids = self._check_ids(ids).reshape(-1)
        g = np.asarray(grad, self._np_dtype).reshape(
            -1, self.embedding_dim)
        uniq, inv, cnt = np.unique(ids, return_inverse=True,
                                   return_counts=True)
        merged = np.zeros((uniq.shape[0], self.embedding_dim),
                          self._np_dtype)
        np.add.at(merged, inv, g)
        if self.entry is not None:
            keep = self._admitted(uniq, cnt)
            if not keep.all():
                uniq, merged = uniq[keep], merged[keep]
            if uniq.size == 0:
                return np.zeros((), np.int32)
        if self.optimizer == 'adagrad':
            self._accum[uniq] += merged * merged
            merged = merged / np.sqrt(self._accum[uniq] + 1e-10)
        self.table[uniq] -= self.learning_rate * merged
        return np.zeros((), np.int32)  # io_callback wants a result

    # -- device side ---------------------------------------------------------
    def _build_lookup(self):
        D = self.embedding_dim
        dt = jnp.dtype(self._np_dtype)

        @jax.custom_vjp
        def lookup(ids, anchor):
            out = jax.ShapeDtypeStruct(ids.shape + (D,), dt)
            # io_callback, NOT pure_callback: the table mutates between
            # calls (pushes), so the read must not be CSE'd/cached or
            # re-executed out of order (e.g. by jax.remat re-running the
            # forward after later pushes landed)
            from jax.experimental import io_callback
            rows = io_callback(self._host_gather, out, ids,
                               ordered=False)
            # anchor is 0.0: keeps the op differentiable without
            # perturbing the rows
            return rows + anchor.astype(dt)

        def fwd(ids, anchor):
            return lookup(ids, anchor), ids

        def bwd(ids, g):
            if self.trainable:
                from jax.experimental import io_callback
                io_callback(self._host_push,
                            jax.ShapeDtypeStruct((), jnp.int32),
                            ids, g, ordered=True)
            # integer primal -> float0 cotangent; zero for the anchor
            ct = np.zeros(np.shape(ids), jax.dtypes.float0)
            return (ct, jnp.zeros((1,), jnp.float32))

        lookup.defvjp(fwd, bwd)
        return lookup

    def forward(self, ids):
        ids = wrap(ids)
        return apply(self._lookup, ids, self._anchor,
                     op_name='host_offload_embedding')

    # -- checkpointing (the table is host state, not a device param).
    # get/set_extra_state is the Layer-system hook: the table travels in
    # every PARENT model's state_dict under '<path>._extra_state', so
    # whole-model save/restore keeps the embeddings.
    def get_extra_state(self):
        state = {'table': self.table.copy()}  # snapshot: pushes mutate
        if self._accum is not None:
            state['accum'] = self._accum.copy()
        if getattr(self, '_counts', None) is not None:
            state['counts'] = self._counts.copy()
        if getattr(self, '_admit_flag', None) is not None:
            state['admit_flag'] = self._admit_flag.copy()
        return state

    def set_extra_state(self, state):
        table = np.asarray(state['table'], self._np_dtype)
        if table.shape != self.table.shape:
            raise ValueError(
                f'HostOffloadEmbedding table shape mismatch: checkpoint '
                f'{table.shape} vs layer {self.table.shape}')
        self.table = table.copy()
        if self._accum is not None and 'accum' in state:
            accum = np.asarray(state['accum'], self._np_dtype)
            if accum.shape != self._accum.shape:
                raise ValueError(
                    f'HostOffloadEmbedding accum shape mismatch: '
                    f'{accum.shape} vs {self._accum.shape}')
            self._accum = accum.copy()
        if 'counts' in state and getattr(self, '_counts', None) is not None:
            self._counts = np.asarray(state['counts'], np.int64).copy()
        if 'admit_flag' in state and \
                getattr(self, '_admit_flag', None) is not None:
            self._admit_flag = np.asarray(state['admit_flag'],
                                          np.int8).copy()

    def extra_repr(self):
        return (f'{self.num_embeddings}, {self.embedding_dim}, '
                f'host-offloaded, opt={self.optimizer}')
