"""Host-offloaded large-vocab embedding — the TPU-native
parameter-server substitute.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/runtime/the_one_ps.py:417
and parameter_server_runtime.py:32: sparse tables live on parameter
servers (host DRAM), workers pull rows for the current batch and push
gradients back asynchronously (`strategy.a_sync`).  TPU-native mapping:

  * the table is a HOST numpy array — vocab size is bounded by host
    DRAM, not the chip's HBM (the reason PS mode exists);
  * the "pull" is a `jax.pure_callback` gather of exactly the batch's
    rows — the only thing that ever enters HBM is a [B*S, D] slab;
  * the "push" is an ordered `jax.experimental.io_callback` in the
    custom VJP: the row gradients leave the device and the HOST applies
    the optimizer rule (SGD or Adagrad) immediately — the device-side
    optimizer never sees the table, exactly like a PS worker whose
    dense step is separate from the server's sparse update;
  * `a_sync` semantics: the host update is fire-and-forget from the
    device's point of view (the next lookup may or may not observe it,
    matching the reference's asynchronous SGD staleness contract).

Works eagerly and inside jit/ParallelTrainer (callbacks ride the
compiled module). Duplicate ids within a batch accumulate their
gradients before the update (scatter-add), like the reference's sparse
gradient merge.  Out-of-range ids raise (like nn.Embedding).

MULTI-HOST (r3): the table is PROCESS-SHARDED — process p owns vocab
rows [p*V/P, (p+1)*V/P), exactly the reference PS's table distribution
over server instances (the_one_ps.py:417 _get_tables splits by id mod/
range).  Routing is TPU-native instead of brpc RPC:

  * under `shard_map` over a mesh axis spanning the processes, each
    shard `all_gather`s the batch ids over the axis;
  * every process's host callback contributes rows IT OWNS (zeros for
    the rest), and one `psum` over the axis fills every row — the id
    exchange and row return ride the same ICI/DCN collectives as the
    rest of the step, no separate server RPC fabric;
  * the backward all_gathers row grads the same way and each host
    applies its owned updates (dup-id merge + SGD/Adagrad + entry
    admission) locally.

Single-process (including the 8-virtual-device CPU mesh) runs the same
sharded code path when the axis is bound — partitions then share one
host table and partition 0 does the contribution, so psum semantics
match the multi-process case bit for bit.  Without a bound axis the
original single-host fast path runs unchanged.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..core.dispatch import apply
from ..tensor._helpers import wrap

__all__ = ['HostOffloadEmbedding']


def first_flags_from_procs(procs):
    """Given the owning process index of every shard along an axis
    (`procs`: int32 [P]), return bool [P]: True where that position is
    the FIRST along the axis owned by its process.  Pure jnp so the
    dedup flags can be derived in-graph from the actual runtime layout
    (any device order, per-psum-group) instead of assuming contiguous
    process blocks."""
    eq = procs[:, None] == procs[None, :]            # [P, P]
    return ~jnp.any(jnp.tril(eq, -1), axis=1)


class HostOffloadEmbedding(Layer):
    """Embedding with a host-resident table and host-side sparse update.

    Args:
        num_embeddings: vocab size (host-DRAM bounded).
        embedding_dim:  row width.
        learning_rate:  host-side update step size.
        optimizer:      'sgd' or 'adagrad' (the reference PS's sparse
                        optimizers; adagrad keeps a host accumulator).
        trainable:      False freezes the table (pull-only).
    """

    def __init__(self, num_embeddings, embedding_dim, learning_rate=0.01,
                 optimizer='sgd', trainable=True, dtype='float32',
                 seed=None, entry=None, shard_axis='dp',
                 replicated_axes=('tp', 'ep', 'pp')):
        super().__init__()
        if optimizer not in ('sgd', 'adagrad'):
            raise ValueError(f'unsupported host optimizer {optimizer!r}')
        for ax in ('dp', 'sp'):
            if ax in replicated_axes:
                raise ValueError(
                    f'{ax!r} cannot be a replicated axis: its shards '
                    'hold different data (dp: different batches, sp: '
                    'different sequence chunks), so their embedding '
                    'gradients are distinct updates, not replicas')
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.learning_rate = float(learning_rate)
        self.optimizer = optimizer
        self.trainable = trainable
        self.shard_axis = shard_axis
        # axes whose shards compute IDENTICAL embedding grads (the
        # push dedups over them).  Default: tp (Megatron activations
        # are tp-replicated at the embedding), ep (experts shard, the
        # surrounding activations are replicated), pp (stage-gated
        # replicas).  dp and sp are NEVER replicated — their shards
        # see different batches / sequence chunks.
        self.replicated_axes = tuple(replicated_axes)
        self._np_dtype = np.dtype(dtype)
        if seed is None:
            from ..core import rng as rng_mod
            seed = rng_mod.get_seed()
        rs = np.random.RandomState(seed)
        bound = 1.0 / np.sqrt(self.embedding_dim)
        # process sharding: every process generates the SAME full table
        # (shared seed) and keeps only its own row range — cheap at init
        # and guarantees cross-host agreement on the initial values
        self._nproc = jax.process_count()
        self._pid = jax.process_index()
        full = rs.uniform(
            -bound, bound,
            (self.num_embeddings, self.embedding_dim)).astype(self._np_dtype)
        if self._nproc > 1:
            rpp = -(-self.num_embeddings // self._nproc)  # ceil
            self._row0 = self._pid * rpp
            row1 = min(self._row0 + rpp, self.num_embeddings)
            self._rows_per_proc = rpp
            self.table = full[self._row0:max(row1, self._row0)].copy()
        else:
            self._row0 = 0
            self._rows_per_proc = self.num_embeddings
            self.table = full
        self._accum = (np.zeros_like(self.table)
                       if optimizer == 'adagrad' else None)
        # entry admission (reference distributed/entry_attr.py): gate the
        # sparse update per row — see _admitted()
        from ..distributed.entry_attr import (EntryAttr, ProbabilityEntry,
                                              CountFilterEntry)
        if entry is not None and not isinstance(entry, EntryAttr):
            raise TypeError('entry must be a ProbabilityEntry or '
                            'CountFilterEntry')
        self.entry = entry
        self._entry_rng = np.random.RandomState(
            (seed if seed is not None else 0) ^ 0x5eed)
        # admission state is per OWNED row (storage-local indexing)
        if isinstance(entry, CountFilterEntry):
            self._counts = np.zeros((len(self.table),), np.int64)
        elif isinstance(entry, ProbabilityEntry):
            # -1 undecided, 0 rejected, 1 admitted
            self._admit_flag = np.full((len(self.table),), -1, np.int8)
        # a zero scalar device parameter that rides through the lookup:
        # ids are integers, so without a float input on the op the
        # autograd tape would mark the output stop_gradient and the
        # backward push would never fire (it also keeps the op inside
        # the compiled step's differentiated region under jit)
        from ..nn import initializer as I
        self._anchor = self.create_parameter(
            [1], attr=None, dtype='float32',
            default_initializer=I.Constant(0.0))
        self._lookup = self._build_lookup()
        self._lookup_mp = self._build_lookup_mp()

    # -- host side -----------------------------------------------------------
    def _check_ids(self, ids):
        ids = np.asarray(ids).astype(np.int64)
        if ids.size and (ids.min() < 0
                         or ids.max() >= self.num_embeddings):
            raise ValueError(
                f'HostOffloadEmbedding: id out of range [0, '
                f'{self.num_embeddings}) — got '
                f'[{ids.min()}, {ids.max()}]')
        return ids

    def _host_gather(self, ids):
        return self.table[self._check_ids(ids)]

    def _admitted(self, uniq, counts_in_batch):
        """Entry-admission mask over the batch's unique rows (reference
        PS admits features probabilistically or after a show count)."""
        from ..distributed.entry_attr import (ProbabilityEntry,
                                              CountFilterEntry)
        if isinstance(self.entry, CountFilterEntry):
            self._counts[uniq] += counts_in_batch
            return self._counts[uniq] >= self.entry._count_filter
        if isinstance(self.entry, ProbabilityEntry):
            undecided = self._admit_flag[uniq] == -1
            if undecided.any():
                draws = (self._entry_rng.rand(int(undecided.sum()))
                         < self.entry._probability).astype(np.int8)
                self._admit_flag[uniq[undecided]] = draws
            return self._admit_flag[uniq] == 1
        return np.ones(uniq.shape[0], bool)

    def _apply_update(self, local_rows, g):
        """Shared sparse-update core over STORAGE-LOCAL row indices:
        merge duplicate rows, gate by entry admission, apply the rule.
        Without admission gates the whole merge+rule runs in the native
        C++ pass (io/native/sparse_update.cpp — the host-PS analogue of
        the reference's C++ sparse-table optimizers); the numpy path
        remains for entry-gated tables and odd dtypes."""
        if self.entry is None:
            from ..io.native import sparse_update as _native
            if _native.apply_update(self.table, self._accum, local_rows,
                                    g, self.learning_rate,
                                    self.optimizer):
                return
        uniq, inv, cnt = np.unique(local_rows, return_inverse=True,
                                   return_counts=True)
        merged = np.zeros((uniq.shape[0], self.embedding_dim),
                          self._np_dtype)
        np.add.at(merged, inv, g)
        if self.entry is not None:
            keep = self._admitted(uniq, cnt)
            if not keep.all():
                uniq, merged = uniq[keep], merged[keep]
            if uniq.size == 0:
                return
        if self.optimizer == 'adagrad':
            self._accum[uniq] += merged * merged
            merged = merged / np.sqrt(self._accum[uniq] + 1e-10)
        self.table[uniq] -= self.learning_rate * merged

    def _host_push(self, ids, grad):
        """Single-host sparse update (storage holds the full table)."""
        ids = self._check_ids(ids).reshape(-1)
        g = np.asarray(grad, self._np_dtype).reshape(
            -1, self.embedding_dim)
        self._apply_update(ids, g)
        return np.zeros((), np.int32)  # io_callback wants a result

    # -- process-sharded host side (multi-host PS semantics) ------------
    def _owned_mask(self, ids):
        """Bool mask of global ids whose rows live in THIS storage."""
        return (ids >= self._row0) & (ids < self._row0 + len(self.table))

    def _mp_gather(self, first_local, nseen, all_ids):
        """Contribution of this host to the axis-wide psum: rows it
        owns, zeros elsewhere.  `first_local` is 1 on exactly one
        partition per process (see _build_lookup_mp) so multi-device
        hosts don't contribute the same row L times.  `nseen` is the
        number of distinct processes visible along the shard axis in
        this psum group — every process owns table rows, so fewer than
        `_nproc` means some rows are unreachable from this group."""
        if int(nseen) != max(1, self._nproc):
            raise RuntimeError(
                f'HostOffloadEmbedding: only {int(nseen)} of '
                f'{self._nproc} table-owning processes have a device '
                f'on mesh axis {self.shard_axis!r} in this psum '
                'group; their rows would be missing from the lookup. '
                'Lay out the mesh so every process appears along the '
                'shard axis in every slice of the other axes.')
        all_ids = self._check_ids(all_ids)         # [P, B]
        P, B = all_ids.shape
        out = np.zeros((P, B, self.embedding_dim), self._np_dtype)
        if int(first_local):
            flat = all_ids.reshape(-1)
            mask = self._owned_mask(flat)
            if mask.any():
                rows = np.zeros((flat.shape[0], self.embedding_dim),
                                self._np_dtype)
                rows[mask] = self.table[flat[mask] - self._row0]
                out = rows.reshape(P, B, self.embedding_dim)
        return out

    def _mp_push(self, first_local, all_ids, all_g):
        """Apply this host's owned slice of the axis-wide grads."""
        if not int(first_local):
            return np.zeros((), np.int32)
        all_ids = np.asarray(all_ids)
        all_g = np.asarray(all_g)
        if self.shard_axis in self.replicated_axes:
            # shards along a REPLICATED axis computed identical grads,
            # so the axis-gather holds P copies of one update — apply
            # a single slice (distinct-data axes like 'dp' keep every
            # slice: each is a different batch's gradient)
            all_ids, all_g = all_ids[:1], all_g[:1]
        flat = self._check_ids(all_ids).reshape(-1)
        g = np.asarray(all_g, self._np_dtype).reshape(
            -1, self.embedding_dim)
        mask = self._owned_mask(flat)
        if mask.any():
            self._apply_update(flat[mask] - self._row0, g[mask])
        return np.zeros((), np.int32)

    # -- device side ---------------------------------------------------------
    def _build_lookup(self):
        D = self.embedding_dim
        dt = jnp.dtype(self._np_dtype)

        @jax.custom_vjp
        def lookup(ids, anchor):
            out = jax.ShapeDtypeStruct(ids.shape + (D,), dt)
            # io_callback, NOT pure_callback: the table mutates between
            # calls (pushes), so the read must not be CSE'd/cached or
            # re-executed out of order (e.g. by jax.remat re-running the
            # forward after later pushes landed)
            from jax.experimental import io_callback
            rows = io_callback(self._host_gather, out, ids,
                               ordered=False)
            # anchor is 0.0: keeps the op differentiable without
            # perturbing the rows
            return rows + anchor.astype(dt)

        def fwd(ids, anchor):
            return lookup(ids, anchor), ids

        def bwd(ids, g):
            if self.trainable:
                from jax.experimental import io_callback
                io_callback(self._host_push,
                            jax.ShapeDtypeStruct((), jnp.int32),
                            ids, g, ordered=True)
            # integer primal -> float0 cotangent; zero for the anchor
            ct = np.zeros(np.shape(ids), jax.dtypes.float0)
            return (ct, jnp.zeros((1,), jnp.float32))

        lookup.defvjp(fwd, bwd)
        return lookup

    def _build_lookup_mp(self):
        """Sharded lookup for use INSIDE shard_map over `shard_axis`:
        all_gather ids → per-host owned-row contributions → psum."""
        D = self.embedding_dim
        dt = jnp.dtype(self._np_dtype)
        axis = self.shard_axis

        def axis_first_flags():
            """(my_flag, nseen): GATHER dedup — exactly one partition
            per PROCESS on the shard axis contributes to the psum
            (reads are idempotent, so replicas on OTHER mesh axes may
            all gather their own copy — their psum is over `axis`
            only).  The flags are derived at RUNTIME from the shards'
            actual owning processes (io_callback → all_gather), so any
            device→process layout is handled — including orders that
            interleave processes or differ between psum groups, where
            a contiguous-block assumption would silently double- or
            zero-count rows.  `nseen` (distinct processes visible on
            the axis in this group) lets the host validate that no
            table shard is unreachable."""
            from jax.experimental import io_callback
            pid = io_callback(
                lambda: np.int32(jax.process_index()),
                jax.ShapeDtypeStruct((), jnp.int32), ordered=False)
            procs = jax.lax.all_gather(pid, axis)        # [P]
            firsts = first_flags_from_procs(procs)
            nseen = jnp.sum(firsts).astype(jnp.int32)
            return firsts[jax.lax.axis_index(axis)], nseen

        def first_push_flag():
            # PUSH dedup is stricter: the host table must update ONCE
            # per DISTINCT gradient, but every device shard runs the
            # io_callback — so also require index 0 on mesh axes the
            # computation is REPLICATED over (self.replicated_axes,
            # default tp/sp/ep/pp), else the sparse update applies once
            # per replica (lr x tp, adagrad accumulators
            # double-counted).  'dp' is never in the set: data-parallel
            # ranks hold DIFFERENT batches, so each rank's grads are a
            # distinct update that must land (gating on dp==0 would
            # silently train on 1/dp of the data)
            flag, _ = axis_first_flags()
            for other in self.replicated_axes:
                if other == axis:
                    continue
                try:
                    flag = flag & (jax.lax.axis_index(other) == 0)
                except Exception:
                    pass  # axis not bound in this trace
            return flag

        def pull(ids):
            from jax.experimental import io_callback
            flat = ids.reshape(-1)
            all_ids = jax.lax.all_gather(flat, axis)        # [P, B]
            P = all_ids.shape[0]
            flag, nseen = axis_first_flags()
            contrib = io_callback(
                self._mp_gather,
                jax.ShapeDtypeStruct((P, flat.shape[0], D), dt),
                flag, nseen, all_ids, ordered=False)
            rows = jax.lax.psum(contrib, axis)
            mine = rows[jax.lax.axis_index(axis)]
            return mine.reshape(ids.shape + (D,))

        @jax.custom_vjp
        def lookup_mp(ids, anchor):
            return pull(ids) + anchor.astype(dt)

        def fwd(ids, anchor):
            return lookup_mp(ids, anchor), ids

        def bwd(ids, g):
            if self.trainable:
                from jax.experimental import io_callback
                flat = ids.reshape(-1)
                gf = g.reshape(-1, D)
                all_ids = jax.lax.all_gather(flat, axis)
                all_g = jax.lax.all_gather(gf, axis)
                io_callback(self._mp_push,
                            jax.ShapeDtypeStruct((), jnp.int32),
                            first_push_flag(), all_ids, all_g,
                            ordered=True)
            ct = np.zeros(np.shape(ids), jax.dtypes.float0)
            return (ct, jnp.zeros((1,), jnp.float32))

        lookup_mp.defvjp(fwd, bwd)
        return lookup_mp

    def _axis_bound(self):
        """True iff shard_axis is a mapped axis in the current trace."""
        try:
            jax.lax.axis_index(self.shard_axis)
            return True
        except Exception:
            return False

    def forward(self, ids):
        ids = wrap(ids)

        def op(idv, anchor):
            if self._axis_bound():
                return self._lookup_mp(idv, anchor)
            if self._nproc > 1:
                raise RuntimeError(
                    'HostOffloadEmbedding with process-sharded table '
                    f'must run inside shard_map over axis '
                    f'{self.shard_axis!r} (multi-host PS routing needs '
                    'the axis collectives)')
            return self._lookup(idv, anchor)

        return apply(op, ids, self._anchor,
                     op_name='host_offload_embedding')

    # -- checkpointing (the table is host state, not a device param).
    # get/set_extra_state is the Layer-system hook: the table travels in
    # every PARENT model's state_dict under '<path>._extra_state', so
    # whole-model save/restore keeps the embeddings.
    def get_extra_state(self):
        state = {'table': self.table.copy()}  # snapshot: pushes mutate
        if self._accum is not None:
            state['accum'] = self._accum.copy()
        if getattr(self, '_counts', None) is not None:
            state['counts'] = self._counts.copy()
        if getattr(self, '_admit_flag', None) is not None:
            state['admit_flag'] = self._admit_flag.copy()
        return state

    def set_extra_state(self, state):
        table = np.asarray(state['table'], self._np_dtype)
        if table.shape != self.table.shape:
            raise ValueError(
                f'HostOffloadEmbedding table shape mismatch: checkpoint '
                f'{table.shape} vs layer {self.table.shape}')
        self.table = table.copy()
        if self._accum is not None and 'accum' in state:
            accum = np.asarray(state['accum'], self._np_dtype)
            if accum.shape != self._accum.shape:
                raise ValueError(
                    f'HostOffloadEmbedding accum shape mismatch: '
                    f'{accum.shape} vs {self._accum.shape}')
            self._accum = accum.copy()
        if 'counts' in state and getattr(self, '_counts', None) is not None:
            self._counts = np.asarray(state['counts'], np.int64).copy()
        if 'admit_flag' in state and \
                getattr(self, '_admit_flag', None) is not None:
            self._admit_flag = np.asarray(state['admit_flag'],
                                          np.int8).copy()

    def extra_repr(self):
        return (f'{self.num_embeddings}, {self.embedding_dim}, '
                f'host-offloaded, opt={self.optimizer}')
