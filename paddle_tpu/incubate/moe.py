"""Mixture-of-Experts with expert parallelism (the 'ep' mesh axis).

Reference analogue: the reference tree predates MoE (its incubate
gained distributed/models/moe later, built on per-rank experts +
NCCL all-to-all); the brief makes expert parallelism first-class here.

TPU-native design (Switch Transformer routing, arXiv:2101.03961 —
public algorithm, fresh implementation):

  * expert weights live STACKED: w1[E, H, F], w2[E, F, H] with
    PartitionSpec ('ep', None, None) — each ep shard holds E/ep
    experts;
  * routing builds dense dispatch/combine tensors [S, E, C]
    (capacity C = ceil(S/E)*capacity_factor) — compiler-friendly
    static shapes, no scatter;
  * the token shuffle to experts is one einsum producing
    [E, C, H] sharded on 'ep' — XLA lowers the resharding from
    ('dp' tokens) to ('ep' experts) into the same all-to-all the
    reference's MoE issues through NCCL, but scheduled on ICI;
  * expert FFNs run as ONE batched einsum over the expert dim (MXU
    sees E GEMMs batched, not a Python loop);
  * the load-balance auxiliary loss (E * sum_e f_e * p_e) is stored
    on the layer after forward (`.aux_loss`) for the model to add.

Capacity overflow drops tokens (their combine weight is zero and the
residual path carries them) — the standard Switch behavior.
"""
import math

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..nn import initializer as I
from ..core.dispatch import apply
from ..tensor._helpers import wrap

__all__ = ['SwitchMoE']


class SwitchMoE(Layer):
    """Top-1 (or top-2) routed expert FFN: y = combine(expert_ffn(
    dispatch(x))) + aux load-balance loss.

    Args:
        hidden_size:    H of the incoming activations [..., H].
        ffn_size:       expert MLP inner width F.
        num_experts:    E (shard over 'ep' when the mesh has it).
        top_k:          1 (Switch) or 2 (GShard-style second choice).
        capacity_factor: per-expert slots = ceil(S/E * factor).
        activation:     'gelu' or 'relu'.
    """

    def __init__(self, hidden_size, ffn_size, num_experts, top_k=1,
                 capacity_factor=1.25, activation='gelu', name=None):
        super().__init__()
        if top_k not in (1, 2):
            raise ValueError('top_k must be 1 or 2')
        self.hidden_size = int(hidden_size)
        self.ffn_size = int(ffn_size)
        self.num_experts = int(num_experts)
        self.top_k = top_k
        self.capacity_factor = float(capacity_factor)
        self.activation = activation
        E, H, F = self.num_experts, self.hidden_size, self.ffn_size
        self.gate_w = self.create_parameter(
            [H, E], default_initializer=I.XavierUniform())
        self.w1 = self.create_parameter(
            [E, H, F], default_initializer=I.KaimingUniform())
        self.b1 = self.create_parameter([E, 1, F], is_bias=True)
        self.w2 = self.create_parameter(
            [E, F, H], default_initializer=I.KaimingUniform())
        self.b2 = self.create_parameter([E, 1, H], is_bias=True)
        # experts shard over 'ep'; the gate is replicated
        self._param_shardings = {'w1': ('ep',), 'b1': ('ep',),
                                 'w2': ('ep',), 'b2': ('ep',),
                                 'gate_w': None}
        self._aux_loss = None
        self._aux_trace = None

    @property
    def aux_loss(self):
        """Load-balance loss of the LAST forward — valid only inside
        the same trace that ran the forward.  A read from another
        trace (e.g. a separately-compiled eval step) raises here with
        a clear fix instead of leaking a dead tracer into JAX
        internals; pass ``return_aux=True`` to forward and thread the
        value explicitly instead."""
        if self._aux_loss is None:
            return None
        import jax
        val = getattr(self._aux_loss, 'value', self._aux_loss)
        if isinstance(val, jax.core.Tracer) \
                and self._aux_trace is not None \
                and self._aux_trace != jax.core.get_opaque_trace_state():
            raise RuntimeError(
                'SwitchMoE.aux_loss was computed in a different jit '
                'trace than the one reading it (e.g. forward and loss '
                'compiled separately). Reading it here would leak a '
                'JAX tracer. Call forward(x, return_aux=True) and '
                'pass the aux loss to the loss computation '
                'explicitly.')
        return self._aux_loss

    @aux_loss.setter
    def aux_loss(self, value):
        import jax
        self._aux_loss = value
        self._aux_trace = (None if value is None
                           else jax.core.get_opaque_trace_state())

    def _capacity(self, S):
        return max(1, int(math.ceil(
            S / self.num_experts * self.capacity_factor)))

    def forward(self, x, return_aux=False):
        """Route x through the experts.

        With ``return_aux=True`` returns ``(y, aux_loss)`` — the safe
        way to consume the load-balance loss when the loss is computed
        in a different jit trace than the forward (the cached
        ``.aux_loss`` attribute is only valid within the SAME trace;
        a tracer read from another trace is a leak error in JAX).
        """
        # drop any value from a previous trace before computing, so a
        # stale tracer can never be read after this forward
        self.aux_loss = None
        lead = x.shape[:-1]
        S = 1
        for d in lead:
            S *= d
        C = self._capacity(S * self.top_k)
        E = self.num_experts
        act = jax.nn.gelu if self.activation == 'gelu' else jax.nn.relu

        def fn(xv, gw, w1, b1, w2, b2):
            xs = xv.reshape(S, self.hidden_size)
            logits = (xs.astype(jnp.float32)
                      @ gw.astype(jnp.float32))          # [S, E]
            probs = jax.nn.softmax(logits, axis=-1)

            dispatch = jnp.zeros((S, E, C), xs.dtype)
            combine = jnp.zeros((S, E, C), jnp.float32)
            masked = probs
            fracs = []
            # occupancy carries each expert's filled-slot count across
            # routing iterations: a 2nd-choice token must queue BEHIND
            # the 1st-choice tokens of the same expert, or their slots
            # collide and the FFN silently processes summed tokens
            occ = jnp.zeros((E,), jnp.float32)
            for _ in range(self.top_k):
                idx = jnp.argmax(masked, axis=-1)          # [S]
                onehot = jax.nn.one_hot(idx, E,
                                        dtype=jnp.float32)  # [S, E]
                gate = jnp.sum(masked * onehot, axis=-1)    # [S]
                # position of each token in its expert's queue
                pos = (jnp.cumsum(onehot, axis=0) - 1.0 + occ[None, :]) \
                    * onehot
                keep = (pos < C) & (onehot > 0)
                slot = jax.nn.one_hot(pos.astype(jnp.int32), C,
                                      dtype=jnp.float32)    # [S, E, C]
                sel = slot * keep.astype(jnp.float32)[..., None]
                dispatch = dispatch + sel.astype(xs.dtype)
                combine = combine + sel * gate[:, None, None]
                fracs.append(onehot)
                occ = occ + jnp.sum(keep.astype(jnp.float32), axis=0)
                masked = masked * (1.0 - onehot)            # mask chosen

            # aux: E * sum_e (token fraction)_e * (mean prob)_e
            f_e = jnp.mean(fracs[0], axis=0)
            p_e = jnp.mean(probs, axis=0)
            aux = E * jnp.sum(f_e * p_e)

            ein = jnp.einsum('sec,sh->ech', dispatch, xs)   # all-to-all
            h = act(jnp.einsum('ech,ehf->ecf', ein, w1)
                    + b1.astype(ein.dtype))
            out = jnp.einsum('ecf,efh->ech', h, w2) \
                + b2.astype(h.dtype)
            y = jnp.einsum('ech,sec->sh', out,
                           combine.astype(out.dtype))       # back
            return y.reshape(xv.shape), aux.astype(jnp.float32)

        y, aux = apply(fn, wrap(x), self.gate_w, self.w1, self.b1,
                       self.w2, self.b2, op_name='switch_moe')
        self.aux_loss = aux
        if return_aux:
            return y, aux
        return y

    def extra_repr(self):
        return (f'experts={self.num_experts}, top_k={self.top_k}, '
                f'{self.hidden_size}->{self.ffn_size}')
