from . import auto_checkpoint  # noqa: F401

__all__ = ['auto_checkpoint']
