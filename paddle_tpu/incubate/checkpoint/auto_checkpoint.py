"""Auto checkpoint: train-loop-integrated save + crash recovery.

Reference analogue:
/root/reference/python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py:45 (AutoCheckpointChecker reads the EDL env,
TrainEpochRange:265 snapshots exe scope per epoch and `train_epoch_
range`:598 yields only the epochs not yet completed after a restart)
and checkpoint_saver.py (versioned save dirs, max_num_checkpoints).

TPU-native redesign: no ProgramDesc scope — the checkpoint is the
functional state (layer state_dict + optimizer state_dict + RNG seed)
written atomically with `framework.io.save`.  `train_epoch_range`
keeps the reference's contract: the SAME training script, run again
after a crash (e.g. restarted by `distributed.launch --elastic`),
skips the completed epochs and the model/optimizer resume from the
last snapshot — together they make a SIGKILLed job finish with the
same final state as an uninterrupted one.

Configuration is explicit (`configure(...)`) or by env like the
reference's PaddleCloud path: PADDLE_TPU_AUTO_CHECKPOINT_DIR enables
it, PADDLE_TPU_SAVE_CHECKPOINT_INTER (seconds) throttles saves.
Multi-host: only process 0 writes; every process reads the same dir
(shared filesystem, the reference's HDFS role).
"""
import os
import time
import warnings

from ...resilience import (
    install_shutdown, shutdown_requested, retry, PREEMPTED_EXIT_CODE,
    handler_installed, uninstall_shutdown)

__all__ = ['configure', 'train_epoch_range', 'train_step_range',
           'AutoCheckpointChecker']

_CKPT_NAME = 'acp_snapshot'

_state = {
    'dir': None,
    'model': None,
    'optimizer': None,
    'inter': None,
    'heartbeat': None,
    'last_save': 0.0,
    'graceful': True,
}


class AutoCheckpointChecker:
    """Env gate (reference auto_checkpoint.py:45): valid() iff an
    auto-checkpoint dir is configured explicitly or via env."""

    def __init__(self):
        self.env_dir = os.environ.get('PADDLE_TPU_AUTO_CHECKPOINT_DIR')
        self.save_checkpoint_inter = float(os.environ.get(
            'PADDLE_TPU_SAVE_CHECKPOINT_INTER', '0'))

    def valid(self):
        return (_state['dir'] or self.env_dir) is not None


def configure(checkpoint_dir=None, model=None, optimizer=None,
              save_checkpoint_inter=None, heartbeat_file=None,
              graceful_shutdown=True):
    """Register what a snapshot contains.  `model`/`optimizer` may be
    single objects or lists; both expose state_dict/set_state_dict.
    `heartbeat_file` is touched at every save so an elastic supervisor
    can detect a wedged trainer.  With `graceful_shutdown` (default) a
    SIGTERM/SIGINT during a train range saves one final synchronous
    snapshot at the next step boundary and exits with
    resilience.PREEMPTED_EXIT_CODE — which distributed.elastic
    recognizes as a clean preemption (no restart budget consumed)."""
    _state['dir'] = checkpoint_dir
    _state['model'] = model
    _state['optimizer'] = optimizer
    _state['inter'] = save_checkpoint_inter
    _state['heartbeat'] = heartbeat_file
    _state['last_save'] = 0.0
    _state['graceful'] = graceful_shutdown


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _ckpt_path():
    d = _state['dir'] or os.environ.get(
        'PADDLE_TPU_AUTO_CHECKPOINT_DIR')
    return None if d is None else os.path.join(d, _CKPT_NAME)


def _save_snapshot(progress):
    """Atomic snapshot: write to a temp file in the same dir, fsync,
    rename — a crash mid-save leaves the previous snapshot intact
    (the reference's checkpoint_saver versioned-dir equivalent)."""
    path = _ckpt_path()
    if path is None:
        return
    _touch_heartbeat()   # EVERY host heartbeats, even non-writers —
    #                      each host's supervisor watches its own file
    import jax
    try:
        if jax.process_index() != 0:
            return
    except RuntimeError:
        pass
    import pickle
    import numpy as np

    def _host(o):
        """Recursively pull state to host numpy (device arrays and
        Tensor wrappers don't pickle portably)."""
        if isinstance(o, dict):
            return {k: _host(v) for k, v in o.items()}
        v = getattr(o, 'value', o)
        if isinstance(v, (int, float, str, bool, type(None))):
            return v
        return np.asarray(v)

    try:
        nprocs = jax.process_count()
    except RuntimeError:
        nprocs = 1
    payload = {
        'progress': progress,
        # snapshot state is host numpy — layout-free by construction —
        # but the WRITING topology is recorded so a restore onto a
        # different pool size is visible (elastic reshape), not silent
        'process_count': nprocs,
        'models': [_host(m.state_dict())
                   for m in _as_list(_state['model'])],
        'optimizers': [_host(o.state_dict())
                       for o in _as_list(_state['optimizer'])],
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    from ...resilience import atomic_write
    retry(retries=2, backoff=0.05)(   # shared-fs writes flake; the
        lambda: atomic_write(         # tmp+replace makes retries safe
            path, lambda f: pickle.dump(payload, f), mode='wb',
            prefix='.acp_tmp'))()
    _state['last_save'] = time.time()


def _touch_heartbeat():
    """Mark this trainer live for the elastic supervisor.  The path
    comes from configure(heartbeat_file=...) or the
    PADDLE_TPU_HEARTBEAT_FILE env the launcher's --elastic mode
    exports to the worker."""
    hb = _state['heartbeat'] or os.environ.get(
        'PADDLE_TPU_HEARTBEAT_FILE')
    if hb:
        with open(hb, 'a'):
            os.utime(hb, None)


def _load_snapshot():
    path = _ckpt_path()
    if path is None or not os.path.exists(path):
        return None
    try:
        # AOT warm start: when tools/precompile.py left a sidecar
        # manifest next to the snapshot, pre-load the exported compile
        # artifacts so the restarted worker's first step deserializes
        # instead of re-paying trace+lower+compile
        from ...core import compile_cache
        compile_cache.warm_start(os.path.dirname(path),
                                 name='auto_checkpoint')
    except Exception:
        pass
    import pickle
    try:
        with open(path, 'rb') as f:
            payload = pickle.load(f)
    except (EOFError, pickle.UnpicklingError, OSError, ValueError) as e:
        # the write is atomic (tmp+replace), so a torn snapshot means
        # external damage; a restarted worker must start over, not
        # crash-loop on the same corrupt file
        warnings.warn(
            f'auto-checkpoint snapshot {path} is unreadable ({e}); '
            'starting from scratch', RuntimeWarning)
        return None
    saved_procs = payload.get('process_count')
    if saved_procs is not None:
        import jax
        try:
            nprocs = jax.process_count()
        except RuntimeError:
            nprocs = 1
        if nprocs != saved_procs:
            # elastic reshape: the snapshot is host numpy, so a
            # preempted pool resuming with fewer (or more) hosts
            # restores exactly — log it so the topology change is
            # auditable in the run report
            try:
                from ... import telemetry
                telemetry.event('reshape_restore',
                                saved_process_count=saved_procs,
                                process_count=nprocs, path=path)
            except Exception:
                pass
    for m, sd in zip(_as_list(_state['model']), payload['models']):
        m.set_state_dict(sd)
    for o, sd in zip(_as_list(_state['optimizer']),
                     payload['optimizers']):
        o.set_state_dict(sd)
    return payload['progress']


def _should_save():
    inter = _state['inter']
    if inter is None:
        inter = AutoCheckpointChecker().save_checkpoint_inter
    return (not inter) or (time.time() - _state['last_save'] >= inter)


def _range(kind, max_num):
    """Shared epoch/step generator: restore once, then yield only the
    remaining indices, snapshotting after each completed one.  Under
    graceful shutdown (configure default), a SIGTERM mid-range saves a
    final synchronous snapshot at the next boundary and exits
    PREEMPTED_EXIT_CODE — the elastic supervisor restarts without
    burning its failure budget and the resumed range loses zero
    completed work."""
    if not AutoCheckpointChecker().valid():
        # reference behaviour: without the env/config the range is a
        # plain range and nothing is saved
        yield from range(max_num)
        return
    # like Model.fit, the range only BORROWS the signal handlers: if
    # nothing else installed them, restore on exit so a later
    # Ctrl-C/SIGTERM behaves normally once the range is done
    owned = _state['graceful'] and not handler_installed()
    if _state['graceful']:
        install_shutdown()   # idempotent; no-op off the main thread
    try:
        progress = _load_snapshot()
        start = 0
        if progress is not None and progress.get('kind') == kind:
            start = int(progress.get('next', 0))
        for i in range(start, max_num):
            yield i
            if _state['graceful'] and shutdown_requested():
                # the completed index is durable BEFORE we bow out
                _save_snapshot({'kind': kind, 'next': i + 1})
                import signal
                import sys
                from ...resilience import (
                    preemption_signal, clear_shutdown)
                if preemption_signal() == signal.SIGINT:
                    # user interrupt, not fleet preemption: snapshot
                    # is saved, hand control back as Ctrl-C always has
                    clear_shutdown()
                    raise KeyboardInterrupt
                # preemption: durable flight-recorder dump next to the
                # snapshot so the restarted worker's post-mortem holds
                # the final grace-window timeline
                path = _ckpt_path()
                if path is not None:
                    from ... import telemetry
                    telemetry.dump_flight(os.path.join(
                        os.path.dirname(path),
                        f'flightrec-{kind}{i + 1}.json'))
                sys.exit(PREEMPTED_EXIT_CODE)
            if _should_save() or i == max_num - 1:
                _save_snapshot({'kind': kind, 'next': i + 1})
    finally:
        if owned:
            uninstall_shutdown()


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None):
    """Reference auto_checkpoint.py:598: `for epoch in
    train_epoch_range(N):` — after a restart, completed epochs are
    skipped and model/optimizer state is restored."""
    if save_checkpoint_inter is not None:
        _state['inter'] = save_checkpoint_inter
    return _range('epoch', max_epoch_num)


def train_step_range(max_step_num, save_checkpoint_inter=None):
    """Step-granular variant (the TPU trainer's natural unit): same
    contract at per-step resolution, for jobs whose epochs are long
    enough that epoch snapshots lose too much work on a crash."""
    if save_checkpoint_inter is not None:
        _state['inter'] = save_checkpoint_inter
    return _range('step', max_step_num)
