"""paddle.incubate.optimizer — LookAhead and ModelAverage.

Reference analogue:
/root/reference/python/paddle/incubate/optimizer/lookahead.py:26 and
modelaverage.py:27 (the C++ average_accumulates op).

TPU-native: both are pure array recurrences over the parameter pytree —
no per-op kernels.  They run eagerly here AND compose with the compiled
paths: LookAhead exposes the same functional init()/apply_gradients()
contract as core optimizers (the slow-weight interpolation folds into
the one jitted train step); ModelAverage keeps its three-slot
accumulator sums exactly like the reference kernel so the averaged
window matches bit-for-bit semantics.
"""
import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer

__all__ = ['LookAhead', 'ModelAverage']


class LookAhead(Optimizer):
    r"""Lookahead (arXiv:1907.08610): keep slow weights; every k inner
    steps, slow += alpha * (fast - slow) and fast <- slow (reference
    lookahead.py:26).
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError('inner optimizer must be an Optimizer')
        if not 0.0 <= alpha <= 1.0:
            raise ValueError('alpha should be in [0, 1]')
        if not (isinstance(k, int) and k > 0):
            raise ValueError('k should be a positive integer')
        super().__init__(
            learning_rate=alpha,
            parameters=inner_optimizer._ctor_parameter_list)
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = {}          # id(param) -> slow weight array

    # -- eager ----------------------------------------------------------
    def step(self):
        if not self._slow:
            # seed slow copies from the weights BEFORE any inner update
            # (reference lookahead.py seeds the slow var from the
            # initial param; keeps eager == functional init(params)).
            # ALL params seed — a frozen one may unfreeze later.
            for p in self.inner_optimizer._params:
                self._slow[id(p)] = p.value
        self.inner_optimizer.step()
        self._global_step += 1
        if self._global_step % self.k:
            return
        for p in self.inner_optimizer._params:
            if p.stop_gradient:
                continue
            slow = self._slow.get(id(p))
            if slow is None:
                # param added after training started: seed now, first
                # interpolation happens at the NEXT window
                self._slow[id(p)] = p.value
                continue
            slow = slow + self.alpha * (p.value - slow)
            p.value = slow
            self._slow[id(p)] = slow

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self.inner_optimizer._params]

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    # -- functional (compiled path) --------------------------------------
    def init(self, params):
        return {'inner': self.inner_optimizer.init(params),
                'slow': jax.tree_util.tree_map(lambda v: v, params)}

    def apply_gradients(self, params, grads, state, step, lr=None):
        new_params, new_inner = self.inner_optimizer.apply_gradients(
            params, grads, state['inner'], step, lr=lr)
        sync = (step % self.k) == 0

        def blend(fast, slow):
            merged = slow + self.alpha * (fast - slow)
            return jnp.where(sync, merged, fast), \
                jnp.where(sync, merged, slow)

        pairs = jax.tree_util.tree_map(blend, new_params, state['slow'])
        new_p = jax.tree_util.tree_map(
            lambda pr: pr[0], pairs,
            is_leaf=lambda x: isinstance(x, tuple))
        new_slow = jax.tree_util.tree_map(
            lambda pr: pr[1], pairs,
            is_leaf=lambda x: isinstance(x, tuple))
        return new_p, {'inner': new_inner, 'slow': new_slow}


class ModelAverage(Optimizer):
    r"""Maintain a running average of parameters over a trailing window
    (reference modelaverage.py:27 / the average_accumulates kernel):

        sum_1 += p each step; every 16384 updates sum_2 += sum_1,
        sum_1 = 0; when num_accumulates >= max(min_average_window,
        min(max_average_window, num_updates * average_window_rate)):
        sum_3 = sum_1 + sum_2, sums reset, old_num = num, num = 0.

    apply() swaps the averaged weights in (optionally restoring after),
    restore() puts the trained weights back.
    """

    _SHIFT = 16384               # kMaxNumAccumulates in the reference op

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._acc = {}           # id(p) -> dict of slots
        self._saved = {}         # id(p) -> live weights during apply()

    def _slots(self, p):
        st = self._acc.get(id(p))
        if st is None:
            z = jnp.zeros_like(p.value)
            st = {'sum_1': z, 'sum_2': z, 'sum_3': z,
                  'num_accumulates': 0, 'old_num_accumulates': 0,
                  'num_updates': 0}
            self._acc[id(p)] = st
        return st

    def step(self):
        """Accumulate the CURRENT weights (call after the inner
        optimizer's step, like the reference's minimize pairing)."""
        for p in self._params:
            if p.stop_gradient:
                continue
            st = self._slots(p)
            st['sum_1'] = st['sum_1'] + p.value
            st['num_updates'] += 1
            st['num_accumulates'] += 1
            if st['num_updates'] % self._SHIFT == 0:
                st['sum_2'] = st['sum_2'] + st['sum_1']
                st['sum_1'] = jnp.zeros_like(st['sum_1'])
            window = min(self.max_average_window,
                         st['num_updates'] * self.average_window)
            if st['num_accumulates'] >= self.min_average_window \
                    and st['num_accumulates'] >= window:
                st['sum_3'] = st['sum_1'] + st['sum_2']
                st['sum_1'] = jnp.zeros_like(st['sum_1'])
                st['sum_2'] = jnp.zeros_like(st['sum_2'])
                st['old_num_accumulates'] = st['num_accumulates']
                st['num_accumulates'] = 0

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, []

    def _average(self, p):
        st = self._slots(p)
        total = st['num_accumulates'] + st['old_num_accumulates']
        if total == 0:
            return p.value
        s = st['sum_1'] + st['sum_2'] + st['sum_3']
        return (s / total).astype(p.value.dtype)

    def apply(self, executor=None, need_restore=True):
        """Context manager: parameters hold the averaged weights inside
        the block (reference modelaverage.py apply)."""
        outer = self

        class _Ctx:
            def __enter__(ctx):
                for p in outer._params:
                    outer._saved[id(p)] = p.value
                    p.value = outer._average(p)
                return ctx

            def __exit__(ctx, *exc):
                if need_restore:
                    outer.restore()
                return False

        return _Ctx()

    def restore(self, executor=None):
        for p in self._params:
            saved = self._saved.pop(id(p), None)
            if saved is not None:
                p.value = saved
