"""paddle_tpu.quantization — QAT + post-training quantization.

Reference analogue:
/root/reference/python/paddle/fluid/contrib/slim/quantization/
(imperative/qat.py:40 ImperativeQuantAware,
post_training_quantization.py PostTrainingQuantization,
quantization_pass.py's fake_quantize_* ops).

TPU-native redesign: no graph passes, no per-op CUDA fake-quant
kernels.  Fake quantization is a pure function with a straight-through
estimator (custom_vjp identity gradient) inserted by WRAPPING layers —
the wrapped model stays an ordinary Layer tree that jit/hapi/
ParallelTrainer compile as usual, and XLA folds the quant-dequant
chains into the surrounding matmuls.  The int8 artifact for inference
is a state_dict of int8 weights + scales.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..core.dispatch import apply
from ..tensor._helpers import wrap

__all__ = ['fake_quant', 'FakeQuantAbsMax',
           'FakeQuantMovingAverageAbsMax', 'QuantedLayer',
           'ImperativeQuantAware', 'PostTrainingQuantization',
           'quant_post_dynamic', 'load_quantized_model',
           'Int8DynamicLinear', 'Int4DynamicLinear',
           'quantize_dynamic_int8', 'quantize_dynamic_int4',
           'quantize_for_serving']


def _make_fake_quant():
    """quantize-dequantize with a straight-through gradient."""

    @jax.custom_vjp
    def fq(x, scale, qmax):
        s = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
        return q * s / qmax

    def fwd(x, scale, qmax):
        return fq(x, scale, qmax), (x, scale, qmax)

    def bwd(res, g):
        x, scale, qmax = res
        # STE: pass gradients through inside the clip range, zero outside
        s = jnp.maximum(scale, 1e-8)
        inside = (jnp.abs(x) <= s).astype(g.dtype)
        return (g * inside, jnp.zeros_like(scale),
                jnp.zeros_like(qmax))

    fq.defvjp(fwd, bwd)
    return fq


_fq = _make_fake_quant()


def _channel_scale(w, axis, xp=jnp):
    """Per-channel abs-max scale along `axis`, shaped for broadcast
    against `w`.  The SINGLE definition of the channel-wise grid: both
    the QAT fake-quant (training) and the deploy artifact (save) use
    it, so the deployed quantization provably matches what training
    simulated."""
    red = tuple(d for d in range(w.ndim) if d != axis)
    shape = [1] * w.ndim
    shape[axis] = w.shape[axis]
    return xp.maximum(xp.max(xp.abs(w), axis=red),
                      1e-8).reshape(shape)


def fake_quant(x, scale, bits=8):
    """Public fake-quant op: quantize to `bits` and dequantize, with a
    straight-through estimator for training (reference
    fake_quantize_dequantize_abs_max)."""
    qmax = float(2 ** (bits - 1) - 1)
    return apply(lambda v, s: _fq(v, s, jnp.asarray(qmax, v.dtype)),
                 wrap(x), wrap(scale), op_name='fake_quant')


class FakeQuantAbsMax(Layer):
    """Per-tensor dynamic abs-max fake quant (reference
    quantization_pass.py fake_quantize_abs_max)."""

    def __init__(self, bits=8, channel_wise=False, axis=0):
        super().__init__()
        self.bits = bits
        self.channel_wise = channel_wise
        self.axis = axis

    def forward(self, x):
        qmax = float(2 ** (self.bits - 1) - 1)

        def fn(v):
            if self.channel_wise:
                s = _channel_scale(v, self.axis)
            else:
                s = jnp.max(jnp.abs(v))
            return _fq(v, s, jnp.asarray(qmax, v.dtype))

        return apply(fn, wrap(x), op_name='fake_quant_abs_max')


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation fake quant with a moving-average scale (reference
    fake_quantize_moving_average_abs_max): the scale is LEARNED state
    during training and frozen for eval."""

    def __init__(self, bits=8, moving_rate=0.9):
        super().__init__()
        self.bits = bits
        self.moving_rate = moving_rate
        self.scale = self.create_buffer(
            'scale', jnp.asarray([0.0], jnp.float32))

    def forward(self, x):
        qmax = float(2 ** (self.bits - 1) - 1)
        r = self.moving_rate
        training = self.training

        def fn(v, scale):
            cur = jnp.max(jnp.abs(v)).astype(jnp.float32)
            if training:
                new_scale = jnp.where(scale[0] > 0,
                                      r * scale[0] + (1 - r) * cur, cur)
            else:
                new_scale = jnp.where(scale[0] > 0, scale[0], cur)
            out = _fq(v, new_scale.astype(v.dtype),
                      jnp.asarray(qmax, v.dtype))
            return out, new_scale[None]

        out, new_scale = apply(fn, wrap(x), self.scale,
                               op_name='fake_quant_moving_avg')
        if self.training:
            self.scale.value = new_scale.value \
                if hasattr(new_scale, 'value') else new_scale
        return out

    def create_buffer(self, name, value):
        from ..core.tensor import Tensor
        buf = Tensor(value)
        buf.stop_gradient = True
        self.register_buffer(name, buf)
        return buf


class QuantedLayer(Layer):
    """Wrapper installing fake quant on a layer's weight and input —
    the dygraph QuantizedConv2D/QuantizedLinear equivalent (reference
    imperative/quant_layers.py)."""

    def __init__(self, layer, weight_bits=8, act_bits=8,
                 weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max',
                 moving_rate=0.9):
        super().__init__()
        self.inner = layer
        channel_wise = weight_quantize_type == 'channel_wise_abs_max'
        # Linear weights are [in, out] -> channel axis 1; Conv [O, I, kh,
        # kw] -> axis 0
        w = getattr(layer, 'weight', None)
        axis = 1 if (w is not None and len(w.shape) == 2) else 0
        self.weight_fq = FakeQuantAbsMax(weight_bits,
                                         channel_wise=channel_wise,
                                         axis=axis)
        if activation_quantize_type == 'moving_average_abs_max':
            self.act_fq = FakeQuantMovingAverageAbsMax(act_bits,
                                                       moving_rate)
        else:
            self.act_fq = FakeQuantAbsMax(act_bits)

    def forward(self, x):
        x = self.act_fq(x)
        inner = self.inner
        w = inner.weight
        orig = w.value
        # fake-quant the weight for this call; restore after (the
        # optimizer keeps training the fp master weight)
        fq_w = self.weight_fq(w)
        w.value = fq_w.value if hasattr(fq_w, 'value') else fq_w
        try:
            out = inner(x)
        finally:
            w.value = orig
        return out


_DEFAULT_QUANTIZABLE = ('Conv2D', 'Linear')


class ImperativeQuantAware:
    """Rewrite a dygraph model's quantizable sublayers in place for QAT
    (reference imperative/qat.py:40)."""

    def __init__(self, quantizable_layer_type=_DEFAULT_QUANTIZABLE,
                 weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max',
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 **unused):
        self.types = tuple(t if isinstance(t, str) else t.__name__
                           for t in quantizable_layer_type)
        self.wq = weight_quantize_type
        self.aq = activation_quantize_type
        self.wbits = weight_bits
        self.abits = activation_bits
        self.moving_rate = moving_rate

    def quantize(self, model):
        """Swap every matching sublayer for its QuantedLayer wrapper
        in place (the reference mutates the dygraph tree the same way)."""
        self._quantize_tree(model)
        return model

    def _quantize_tree(self, layer):
        for name, child in list(getattr(layer, '_sub_layers',
                                        {}).items()):
            if type(child).__name__ in self.types \
                    and getattr(child, 'weight', None) is not None:
                wrapped = QuantedLayer(
                    child, self.wbits, self.abits, self.wq, self.aq,
                    self.moving_rate)
                layer._sub_layers[name] = wrapped
            else:
                self._quantize_tree(child)

    def save_quantized_model(self, model, path, input_spec=None):
        """Persist int8 weights + scales (the deploy artifact; the
        reference emits a quantized inference Program)."""
        state = {}
        for name, layer in _named_sublayers(model):
            if isinstance(layer, QuantedLayer):
                w = np.asarray(layer.inner.weight.value)
                if layer.weight_fq.channel_wise:
                    # per-channel scales along the SAME axis (and via
                    # the same helper) the QAT fake-quant simulated —
                    # a single per-tensor scale here would deploy
                    # coarser quantization than was trained for
                    scale = _channel_scale(
                        w, layer.weight_fq.axis,
                        xp=np).astype(np.float32)
                else:
                    scale = np.float32(float(np.abs(w).max()) or 1e-8)
                # the artifact's grid must be the one QAT simulated:
                # qmax from the layer's weight_bits, not a fixed 127
                bits = layer.weight_fq.bits
                if bits > 8:
                    raise ValueError(
                        f'cannot store {bits}-bit weights in the int8 '
                        'artifact')
                qmax = float(2 ** (bits - 1) - 1)
                q = np.clip(np.round(w / scale * qmax), -qmax,
                            qmax).astype(np.int8)
                state[f'{name}.qweight'] = q
                state[f'{name}.scale'] = scale
                state[f'{name}.qmax'] = np.float32(qmax)
                act_scale = getattr(layer.act_fq, 'scale', None)
                if act_scale is not None:
                    state[f'{name}.act_scale'] = np.asarray(
                        act_scale.value)
        import pickle
        with open(path + '.quant', 'wb') as f:
            pickle.dump(state, f)
        return state


def _named_sublayers(model):
    """Dotted (name, layer) pairs — the Layer system's own traversal
    (layers.py::named_sublayers), excluding the root."""
    return model.named_sublayers()


class PostTrainingQuantization:
    """PTQ: run calibration batches through the model, record per-layer
    abs-max activation scales, emit int8 weights + scales (reference
    post_training_quantization.py, abs_max algo)."""

    def __init__(self, model, data_loader=None, batch_nums=10,
                 algo='abs_max', quantizable_op_type=_DEFAULT_QUANTIZABLE):
        if algo not in ('abs_max',):
            raise NotImplementedError(f'PTQ algo {algo!r}; abs_max only')
        self.model = model
        self.loader = data_loader
        self.batch_nums = batch_nums
        self.types = tuple(t if isinstance(t, str) else t.__name__
                           for t in quantizable_op_type)
        self._act_scales = {}

    def quantize(self):
        """Calibrate + build the quantized state dict."""
        hooks = []
        for name, layer in _named_sublayers(self.model):
            if type(layer).__name__ in self.types \
                    and getattr(layer, 'weight', None) is not None:
                def make_hook(nm):
                    def hook(layer, inputs):
                        x = inputs[0]
                        v = float(np.abs(np.asarray(
                            x.value if hasattr(x, 'value') else x)).max())
                        self._act_scales[nm] = max(
                            self._act_scales.get(nm, 0.0), v)
                    return hook
                hooks.append(layer.register_forward_pre_hook(
                    make_hook(name)))
        try:
            if self.loader is not None:
                for i, batch in enumerate(self.loader):
                    if i >= self.batch_nums:
                        break
                    xs = batch[0] if isinstance(batch, (list, tuple)) \
                        else batch
                    from ..core.tensor import Tensor
                    self.model(Tensor(jnp.asarray(np.asarray(xs))))
        finally:
            for h in hooks:
                h.remove()
        out = {}
        for name, layer in _named_sublayers(self.model):
            if type(layer).__name__ in self.types \
                    and getattr(layer, 'weight', None) is not None:
                w = np.asarray(layer.weight.value)
                scale = float(np.abs(w).max()) or 1e-8
                out[f'{name}.qweight'] = np.clip(
                    np.round(w / scale * 127), -127, 127).astype(np.int8)
                out[f'{name}.scale'] = np.float32(scale)
                if name in self._act_scales:
                    out[f'{name}.act_scale'] = np.float32(
                        self._act_scales[name])
        return out

    def save_quantized_model(self, save_model_path, **kw):
        state = self.quantize()
        import pickle
        with open(save_model_path + '.quant', 'wb') as f:
            pickle.dump(state, f)
        return state


def quant_post_dynamic(model):
    """Weight-only dynamic quantization: int8 weights + scales, no
    calibration (reference's WeightQuantization.quantize_weight_to_int)."""
    return PostTrainingQuantization(model, data_loader=None).quantize()


class Int8DynamicLinear(Layer):
    """Serving-time nn.Linear replacement that EXECUTES on the MXU's
    native int8 path (ops/int8_matmul.py) — unlike the .quant
    artifact path, which dequantizes back to float at load.  Weights
    stay int8 in HBM (half the bytes of bf16 — the KV-cache decode
    step is weight-bandwidth-bound), activations quantize dynamically
    per call, the dot accumulates in int32.  Inference-only: gradients
    do not flow into the int8 weights."""

    def __init__(self, linear):
        super().__init__()
        from ..core.tensor import Tensor
        from ..ops.int8_matmul import quantize_weight_int8
        w_shape = linear.weight.shape          # [in, out] all variants
        self.in_features = int(w_shape[0])
        self.out_features = int(w_shape[1])
        # quantize on-device: a host round-trip per Linear would cost
        # seconds for a 100M-param model over the tunnel
        q, scale = quantize_weight_int8(linear.weight.value)
        self.register_buffer('qweight',
                             Tensor(q, stop_gradient=True))
        self.register_buffer('wscale',
                             Tensor(scale, stop_gradient=True))
        self.bias = linear.bias

    def forward(self, x):
        from ..ops.int8_matmul import dynamic_int8_matmul

        def fn(xv, qv, sv, *maybe_b):
            out_dtype = xv.dtype if jnp.issubdtype(
                xv.dtype, jnp.floating) else jnp.bfloat16
            return dynamic_int8_matmul(
                xv, qv, sv, maybe_b[0] if maybe_b else None,
                out_dtype=out_dtype)

        args = [wrap(x), wrap(self.qweight), wrap(self.wscale)]
        if self.bias is not None:
            args.append(wrap(self.bias))
        return apply(fn, *args, op_name='int8_linear')

    def extra_repr(self):
        return f'in={self.in_features}, out={self.out_features}, int8'


class Int4DynamicLinear(Layer):
    """Serving-time nn.Linear replacement on PACKED int4 weights
    (ops/int8_matmul.quantize_weight_int4_packed): two H-rows per
    uint8 in HBM — a QUARTER of bf16's weight bytes on the
    weight-bandwidth-bound decode step — unpacked to int8 in the
    kernel and fed through the same int8 x int8 -> int32 dot as
    :class:`Int8DynamicLinear`.  Coarser grid (qmax=7): gate quality
    per model before shipping (tools/quant_accuracy for the wire;
    eval-set perplexity for PTQ weights).  Inference-only."""

    def __init__(self, linear):
        super().__init__()
        from ..core.tensor import Tensor
        from ..ops.int8_matmul import quantize_weight_int4_packed
        w_shape = linear.weight.shape          # [in, out] all variants
        self.in_features = int(w_shape[0])
        self.out_features = int(w_shape[1])
        packed, scale = quantize_weight_int4_packed(linear.weight.value)
        self.register_buffer('qweight',
                             Tensor(packed, stop_gradient=True))
        self.register_buffer('wscale',
                             Tensor(scale, stop_gradient=True))
        self.bias = linear.bias

    def forward(self, x):
        from ..ops.int8_matmul import dynamic_int4_matmul
        rows = self.in_features

        def fn(xv, qv, sv, *maybe_b):
            out_dtype = xv.dtype if jnp.issubdtype(
                xv.dtype, jnp.floating) else jnp.bfloat16
            return dynamic_int4_matmul(
                xv, qv, sv, rows=rows,
                bias=maybe_b[0] if maybe_b else None,
                out_dtype=out_dtype)

        args = [wrap(x), wrap(self.qweight), wrap(self.wscale)]
        if self.bias is not None:
            args.append(wrap(self.bias))
        return apply(fn, *args, op_name='int4_linear')

    def extra_repr(self):
        return f'in={self.in_features}, out={self.out_features}, int4'


def _quantize_dynamic(model, make_layer, layer_filter=None):
    """Swap every plain nn.Linear sublayer of `model` for
    ``make_layer(sub)``, in place.  Only exact nn.Linear instances are
    swapped — subclasses (tp-sharded parallel linears under a live tp
    axis, already-wrapped QuantedLayers) keep their own math.
    `layer_filter(full_name, layer) -> bool` opts layers out (e.g.
    keep a numerically-sensitive head in bf16).  Returns `model`."""
    from ..nn import Linear
    from ..distributed import env as dist_env
    from ..distributed.fleet.meta_parallel import (ColumnParallelLinear,
                                                   RowParallelLinear)

    # tp-sharded parallel linears are functionally plain Linears when
    # no tp mesh axis is live (single-chip serving — the decode A/B
    # target); with a real tp axis their weights are sharded and the
    # per-shard quantization story is different, so they are skipped
    mesh = dist_env.get_mesh()
    tp_live = mesh is not None and 'tp' in mesh.axis_names \
        and mesh.shape['tp'] > 1
    swappable = (Linear,) if tp_live else \
        (Linear, ColumnParallelLinear, RowParallelLinear)

    def walk(layer, prefix=''):
        n = 0
        for name, sub in list(layer._sub_layers.items()):
            full = f'{prefix}.{name}' if prefix else name
            if type(sub) in swappable and (layer_filter is None
                                           or layer_filter(full, sub)):
                layer._sub_layers[name] = make_layer(sub)
                n += 1
            elif isinstance(sub, QuantedLayer):
                # QuantedLayer.forward re-reads inner.weight for fake
                # quant — swapping its inner Linear would break it;
                # QAT models export through the .quant artifact path
                continue
            else:
                n += walk(sub, full)
        return n

    if walk(model) == 0:
        hint = ''
        if type(model) in swappable:
            hint = (' — the ROOT layer is itself a quantizable '
                    'Linear, but an in-place swap needs a parent: '
                    'wrap it (e.g. nn.Sequential(model)) and '
                    'quantize that')
        raise ValueError('no quantizable Linear sublayers found'
                         + hint)
    return model


def quantize_dynamic_int8(model, layer_filter=None):
    """Swap every plain nn.Linear sublayer of `model` for an
    Int8DynamicLinear, in place (the executing analog of
    quant_post_dynamic; reference serving runs int8 through
    PaddleSlim + TensorRT kernels, here it is one int8 dot_general on
    the MXU).  Typical decode use:

        model.eval()
        quantize_dynamic_int8(model)
        model.generate(ids, max_new_tokens=128)   # one XLA module
    """
    return _quantize_dynamic(model, Int8DynamicLinear, layer_filter)


def quantize_dynamic_int4(model, layer_filter=None):
    """int4 twin of :func:`quantize_dynamic_int8`: packed nibbles in
    HBM, unpacked in the kernel (ops/int8_matmul.dynamic_int4_matmul).
    A quarter of bf16's weight bytes; coarser grid — measure quality
    before shipping."""
    return _quantize_dynamic(model, Int4DynamicLinear, layer_filter)


_SERVING_MODES = {'int8': quantize_dynamic_int8,
                  'int4': quantize_dynamic_int4}


def quantize_for_serving(model, mode='int8', layer_filter=None):
    """Weight-only PTQ of a serving model, in place — the
    ``ServeConfig(quantize=...)`` entry point.  ``mode`` is 'int8'
    (Int8DynamicLinear) or 'int4' (packed Int4DynamicLinear); every
    decode then reads half-width (or quarter-width) weights from HBM
    through the MXU's native int8 path.  Activations stay dynamic
    per-call; the KV cache and embeddings keep their dtype.  Returns
    `model`."""
    fn = _SERVING_MODES.get(mode)
    if fn is None:
        raise ValueError(
            f'quantize_for_serving mode {mode!r}: expected one of '
            f'{sorted(_SERVING_MODES)}')
    model.eval()
    fn(model, layer_filter)
    # the swap is IRREVERSIBLE (float weights are dropped): mark the
    # model so a ServingEngine whose config declares a different
    # quantize mode refuses instead of compiling a mis-keyed surface
    model._ptq_mode = mode
    return model


def load_quantized_model(model, path):
    """Load a `.quant` artifact back onto `model`: int8 weights
    dequantize through their scales into the live fp parameters —
    weight-only int8 inference (the reference's quantized inference
    Program reads the same scales from its ProgramDesc attrs).

    `model` must have the same layer names as the saver (wrapped
    QuantedLayers load into `<name>.inner`)."""
    import pickle
    with open(path + '.quant', 'rb') as f:
        state = pickle.load(f)
    layers = dict(_named_sublayers(model))
    n = 0
    for key, q in state.items():
        if not key.endswith('.qweight'):
            continue
        name = key[:-len('.qweight')]
        scale = state[name + '.scale']
        target = layers.get(name)
        if target is None:
            raise KeyError(f'{name!r} not found in model')
        if isinstance(target, QuantedLayer):
            target = target.inner
        # scale is a scalar (per-tensor) or a broadcast-shaped vector
        # (channel_wise_abs_max: one scale per output channel); qmax
        # defaults to 127 for artifacts predating the qmax field
        qmax = float(state.get(name + '.qmax', 127.0))
        w = (np.asarray(q, np.float32)
             * np.asarray(scale, np.float32) / qmax)
        target.weight.value = jnp.asarray(w, target.weight.value.dtype)
        n += 1
    if n == 0:
        raise ValueError(f'no quantized weights in {path}.quant')
    return model
