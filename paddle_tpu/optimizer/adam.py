"""Adam / AdamW / Adamax / Lamb.

Reference analogue: /root/reference/python/paddle/optimizer/{adam,adamw,
adamax,lamb}.py with fused CUDA kernels (fluid/operators/optimizers/
adam_op.h).  TPU-native: pure jnp update rules; XLA fuses the whole
parameter update into the train-step module, and `donate_argnums` in the
jit wrapper makes it an in-place HBM update.
"""
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ['Adam', 'AdamW', 'Adamax', 'Lamb']


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_state(self, p):
        return {'moment1': jnp.zeros_like(p), 'moment2': jnp.zeros_like(p)}

    def _rule(self, p, g, state, lr, t):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state['moment1'] + (1 - b1) * g
        v = b2 * state['moment2'] + (1 - b2) * jnp.square(g)
        t = jnp.asarray(t, jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        new_p = p - (lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype)
        return new_p, {'moment1': m, 'moment2': v}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        self._wd = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _rule(self, p, g, state, lr, t):
        # decoupled decay (Loshchilov & Hutter), applied before the Adam
        # step; apply_decay_param_fun(name)==False exempts a param (the
        # reference uses it to skip biases/LayerNorm weights)
        fn = self._apply_decay_param_fun
        if fn is None or fn(self._ctx_param_name):
            p = p * (1 - lr * self._wd)
        return super()._rule(p, g, state, lr, t)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_state(self, p):
        return {'moment': jnp.zeros_like(p), 'inf_norm': jnp.zeros_like(p)}

    def _rule(self, p, g, state, lr, t):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state['moment'] + (1 - b1) * g
        u = jnp.maximum(b2 * state['inf_norm'], jnp.abs(g))
        t = jnp.asarray(t, jnp.float32)
        new_p = p - (lr / (1 - b1 ** t) * m / (u + eps)).astype(p.dtype)
        return new_p, {'moment': m, 'inf_norm': u}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _create_state(self, p):
        return {'moment1': jnp.zeros_like(p), 'moment2': jnp.zeros_like(p)}

    def _rule(self, p, g, state, lr, t):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state['moment1'] + (1 - b1) * g
        v = b2 * state['moment2'] + (1 - b2) * jnp.square(g)
        t = jnp.asarray(t, jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + eps) + self._lamb_wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r.astype(jnp.float32))))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = p - (lr * trust * r).astype(p.dtype)
        return new_p, {'moment1': m, 'moment2': v}
