"""Optimizers (reference: /root/reference/python/paddle/optimizer/)."""
from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .adam import Adam, AdamW, Adamax, Lamb  # noqa: F401
from .sgd_family import (  # noqa: F401
    SGD, Momentum, Adagrad, Adadelta, RMSProp, Lars)
from .dgc import DGCMomentum  # noqa: F401

__all__ = ['Optimizer', 'Adam', 'AdamW', 'Adamax', 'Lamb', 'SGD',
           'Momentum', 'Adagrad', 'Adadelta', 'RMSProp', 'Lars',
           'DGCMomentum', 'lr']
