"""SGD / Momentum / Adagrad / Adadelta / RMSProp (+LARS).

Reference analogue: /root/reference/python/paddle/optimizer/{sgd,momentum,
adagrad,adadelta,rmsprop}.py and fleet's lars_optimizer.
"""
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ['SGD', 'Momentum', 'Adagrad', 'Adadelta', 'RMSProp', 'Lars']


class SGD(Optimizer):
    def _rule(self, p, g, state, lr, t):
        return p - (lr * g).astype(p.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _create_state(self, p):
        return {'velocity': jnp.zeros_like(p)}

    def _rule(self, p, g, state, lr, t):
        mu = self._momentum
        v = mu * state['velocity'] + g
        if self._nesterov:
            upd = g + mu * v
        else:
            upd = v
        return p - (lr * upd).astype(p.dtype), {'velocity': v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_state(self, p):
        return {'moment': jnp.full_like(p, self._init_acc)}

    def _rule(self, p, g, state, lr, t):
        acc = state['moment'] + jnp.square(g)
        new_p = p - (lr * g / (jnp.sqrt(acc) + self._epsilon)).astype(
            p.dtype)
        return new_p, {'moment': acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._rho = rho

    def _create_state(self, p):
        return {'avg_squared_grad': jnp.zeros_like(p),
                'avg_squared_update': jnp.zeros_like(p)}

    def _rule(self, p, g, state, lr, t):
        rho, eps = self._rho, self._epsilon
        asg = rho * state['avg_squared_grad'] + (1 - rho) * jnp.square(g)
        upd = (jnp.sqrt(state['avg_squared_update'] + eps) /
               jnp.sqrt(asg + eps)) * g
        asu = rho * state['avg_squared_update'] + (1 - rho) * jnp.square(upd)
        return (p - (lr * upd).astype(p.dtype),
                {'avg_squared_grad': asg, 'avg_squared_update': asu})


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_state(self, p):
        st = {'mean_square': jnp.zeros_like(p),
              'momentum': jnp.zeros_like(p)}
        if self._centered:
            st['mean_grad'] = jnp.zeros_like(p)
        return st

    def _rule(self, p, g, state, lr, t):
        rho, eps, mu = self._rho, self._epsilon, self._momentum
        ms = rho * state['mean_square'] + (1 - rho) * jnp.square(g)
        if self._centered:
            mg = rho * state['mean_grad'] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + eps)
        mom = mu * state['momentum'] + lr * g / denom
        new_state = {'mean_square': ms, 'momentum': mom}
        if mg is not None:
            new_state['mean_grad'] = mg
        return p - mom.astype(p.dtype), new_state


class Lars(Momentum):
    """LARS (fleet meta_optimizers/lars_optimizer.py): layerwise-adaptive
    trust ratio on top of momentum."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, name=None):
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def _rule(self, p, g, state, lr, t):
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm /
            (g_norm + self._lars_wd * w_norm + 1e-12), 1.0)
        g = g + self._lars_wd * p
        mu = self._momentum
        v = mu * state['velocity'] + (lr * local_lr) * g
        return p - v.astype(p.dtype), {'velocity': v}
