"""DGC — Deep Gradient Compression momentum optimizer.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py:21
(DGCMomentumOptimizer backed by the dgc_op CUDA kernels: top-k gradient
selection, local error feedback, momentum correction, sparse NCCL
all-gather).  TPU-native: the *convergence semantics* (Lin et al. 2018 —
momentum-corrected residual accumulation, top-k masking by magnitude)
are reproduced as a pure jnp update rule; the *wire format* is not,
deliberately: XLA reduces dense gradients over ICI, whose bandwidth
makes sparse encodings counterproductive (gather/scatter breaks MXU
tiling and XLA fusion for no transfer win).  So `DGCMomentum` trains
like the reference's DGC run, while the collective stays dense.

Update per parameter (sparsity s(t), after rampup_begin_step):
    u <- m * u + g          (momentum correction: accumulate velocity)
    v <- v + u              (error feedback residual)
    thr = quantile(|v|, s(t))
    mask = |v| >= thr
    p <- p - lr * (v * mask)
    v <- v * !mask ; u <- u * !mask
Before rampup_begin_step it is plain heavy-ball momentum (lax.cond, so
the warmup steps never pay for the quantile sort).  During the ramp the
sparsity walks through the `sparsity` list — entry i holds for
rampup_step/len(sparsity) steps — Lin et al.'s warmup schedule (75% ->
93.75% -> ... -> 99.9%) that the reference realizes in
DGCMomentumOptimizer's rampup attributes.
"""
import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ['DGCMomentum']


class DGCMomentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1,
                 sparsity=(0.999,), use_nesterov=False, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = bool(use_nesterov)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(int(rampup_step), 1)
        seq = sparsity if isinstance(sparsity, (tuple, list)) else [sparsity]
        self._sparsity_seq = tuple(float(s) for s in seq)

    def _create_state(self, p):
        return {'u': jnp.zeros_like(p), 'v': jnp.zeros_like(p)}

    def _sparsity_at(self, t):
        """Traced sparsity for step t: walks the ramp list, holding each
        entry for rampup_step/len intervals, then stays at the last."""
        seq = jnp.asarray(self._sparsity_seq, jnp.float32)
        n = len(self._sparsity_seq)
        # first sparse step is t = rampup_begin + 1 (the `>` gate in
        # _rule), which must land on ramp entry 0 — hence the -1
        since = jnp.maximum(jnp.asarray(t) - self._rampup_begin - 1, 0)
        idx = jnp.clip(since * n // self._rampup_step, 0, n - 1)
        return seq[idx]

    def _rule(self, p, g, state, lr, t):
        m = self._momentum
        u = m * state['u'] + g
        v = state['v'] + u
        t_arr = jnp.asarray(t)

        def sparse_step(_):
            flat = jnp.abs(v.reshape(-1))
            if flat.size > 1:
                thr = jnp.quantile(flat, self._sparsity_at(t_arr))
            else:
                thr = jnp.zeros((), flat.dtype)
            mask = (jnp.abs(v) >= thr).astype(v.dtype)
            step = v * mask
            if self._nesterov:
                step = m * step + (g * mask)
            return p - lr * step, {'u': u * (1 - mask), 'v': v * (1 - mask)}

        def dense_step(_):
            step = m * u + g if self._nesterov else u
            return p - lr * step, {'u': u, 'v': jnp.zeros_like(v)}

        # lax.cond: warmup steps skip the O(n log n) quantile entirely
        return jax.lax.cond(t_arr > self._rampup_begin,
                            sparse_step, dense_step, None)
