"""DGC — Deep Gradient Compression momentum optimizer.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py:21
(DGCMomentumOptimizer backed by the dgc_op CUDA kernels: top-k gradient
selection, local error feedback, momentum correction, sparse NCCL
all-gather).  TPU-native: the *convergence semantics* (Lin et al. 2018 —
momentum-corrected residual accumulation, top-k masking by magnitude)
are reproduced as a pure jnp update rule; the *wire format* is not,
deliberately: XLA reduces dense gradients over ICI, whose bandwidth
makes sparse encodings counterproductive (gather/scatter breaks MXU
tiling and XLA fusion for no transfer win).  So `DGCMomentum` trains
like the reference's DGC run, while the collective stays dense.

Update per parameter (sparsity s, after rampup_begin_step):
    u <- m * u + g          (momentum correction: accumulate velocity)
    v <- v + u              (error feedback residual)
    thr = quantile(|v|, s)
    mask = |v| >= thr
    p <- p - lr * (v * mask)
    v <- v * !mask ; u <- u * !mask
Before rampup_begin_step it is plain heavy-ball momentum.
"""
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ['DGCMomentum']


class DGCMomentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1,
                 sparsity=(0.999,), weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._rampup_begin = int(rampup_begin_step)
        seq = sparsity if isinstance(sparsity, (tuple, list)) else [sparsity]
        self._sparsity = float(seq[-1])

    def _create_state(self, p):
        return {'u': jnp.zeros_like(p), 'v': jnp.zeros_like(p)}

    def _rule(self, p, g, state, lr, t):
        m = self._momentum
        u = m * state['u'] + g
        v = state['v'] + u
        flat = jnp.abs(v.reshape(-1))
        if flat.size > 1:
            thr = jnp.quantile(flat, self._sparsity)
        else:
            thr = jnp.zeros((), flat.dtype)
        mask = (jnp.abs(v) >= thr).astype(v.dtype)
        sparse_step = (p - lr * v * mask,
                       {'u': u * (1 - mask), 'v': v * (1 - mask)})
        dense_step = (p - lr * u, {'u': u, 'v': jnp.zeros_like(v)})
        t_arr = jnp.asarray(t)
        use_sparse = t_arr > self._rampup_begin
        new_p = jnp.where(use_sparse, sparse_step[0], dense_step[0])
        new_state = {
            k: jnp.where(use_sparse, sparse_step[1][k], dense_step[1][k])
            for k in ('u', 'v')}
        return new_p, new_state
