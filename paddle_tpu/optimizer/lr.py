"""LR schedulers.

Reference analogue: /root/reference/python/paddle/optimizer/lr.py.
TPU-native: every scheduler also exposes value_at(step) as a pure
function of the step count so compiled train steps can evaluate the LR
on-device inside jit (no host sync); the stateful get_lr()/step() API is
kept for eager parity.
"""
import math

__all__ = [
    'LRScheduler', 'NoamDecay', 'ExponentialDecay', 'NaturalExpDecay',
    'InverseTimeDecay', 'PolynomialDecay', 'PiecewiseDecay', 'CosineAnnealingDecay',
    'MultiStepDecay', 'StepDecay', 'LambdaDecay', 'ReduceOnPlateau',
    'LinearWarmup',
]


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.last_lr = float(learning_rate)
        self.verbose = verbose
        self.step()

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def get_lr(self):
        return self.value_at(self.last_epoch)

    def value_at(self, step):
        """Pure function of step → lr (jit-traceable with jnp step)."""
        raise NotImplementedError

    def state_dict(self):
        return {'last_epoch': self.last_epoch, 'last_lr': self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state['last_epoch']
        self.last_lr = state['last_lr']

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        import jax.numpy as jnp
        s = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)
        a = s ** -0.5
        b = s * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * jnp.minimum(a, b)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr * self.gamma ** step

    get_lr = lambda self: self.base_lr * self.gamma ** self.last_epoch  # noqa: E731


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        import jax.numpy as jnp
        return self.base_lr * jnp.exp(-self.gamma * step)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr / (1 + self.gamma * step)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(max(step, 1) / self.decay_steps)
            decay_steps = self.decay_steps * max(div, 1)
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        return ((self.base_lr - self.end_lr) *
                (1 - step / decay_steps) ** self.power + self.end_lr)

    def value_at(self, step):
        import jax.numpy as jnp
        s = jnp.minimum(jnp.asarray(step, jnp.float32), self.decay_steps)
        return ((self.base_lr - self.end_lr) *
                (1 - s / self.decay_steps) ** self.power + self.end_lr)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]

    def value_at(self, step):
        import jax.numpy as jnp
        lr = jnp.asarray(self.values[len(self.boundaries)], jnp.float32)
        for b, v in zip(reversed(self.boundaries),
                        reversed(self.values[:len(self.boundaries)])):
            lr = jnp.where(step < b, v, lr)
        return lr


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (self.eta_min + (self.base_lr - self.eta_min) *
                (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2)

    def value_at(self, step):
        import jax.numpy as jnp
        return (self.eta_min + (self.base_lr - self.eta_min) *
                (1 + jnp.cos(jnp.pi * step / self.T_max)) / 2)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n

    def value_at(self, step):
        import jax.numpy as jnp
        n = sum((step >= m).astype(jnp.int32) if hasattr(step, 'astype')
                else int(step >= m) for m in self.milestones)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch //
                                             self.step_size)

    def value_at(self, step):
        return self.base_lr * self.gamma ** (step // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)

    def value_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode='min', factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode='rel', cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.best = None
        self.cooldown_counter = 0
        self.num_bad_epochs = 0
        self.base_lr = float(learning_rate)
        self.last_lr = float(learning_rate)
        self.last_epoch = 0
        self.verbose = verbose

    def step(self, metrics=None, epoch=None):
        self.last_epoch += 1
        if metrics is None:
            return
        current = float(metrics.item() if hasattr(metrics, 'item')
                        else metrics)
        if self.best is None or self._better(current, self.best):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > self.epsilon:
                self.last_lr = new_lr
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0

    def _better(self, a, best):
        if self.mode == 'min':
            if self.threshold_mode == 'rel':
                return a < best * (1 - self.threshold)
            return a < best - self.threshold
        if self.threshold_mode == 'rel':
            return a > best * (1 + self.threshold)
        return a > best + self.threshold

    def get_lr(self):
        return self.last_lr

    def value_at(self, step):
        return self.last_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate,
                                                    LRScheduler) else None
        self.after_lr = (learning_rate if not self.lr_sched else None)
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(end_lr if self.lr_sched is None else
                         self.lr_sched.base_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.start_lr + (self.end_lr - self.start_lr) *
                    self.last_epoch / self.warmup_steps)
        if self.lr_sched is not None:
            self.lr_sched.last_epoch = self.last_epoch - self.warmup_steps
            return self.lr_sched.get_lr()
        return self.after_lr

    def value_at(self, step):
        import jax.numpy as jnp
        warm = (self.start_lr + (self.end_lr - self.start_lr) *
                step / self.warmup_steps)
        if self.lr_sched is not None:
            after = self.lr_sched.value_at(
                jnp.maximum(step - self.warmup_steps, 0)
                if hasattr(step, 'dtype') else max(step - self.warmup_steps,
                                                   0))
        else:
            after = self.after_lr
        if hasattr(step, 'dtype'):
            return jnp.where(step < self.warmup_steps, warm, after)
        return warm if step < self.warmup_steps else after
