"""Optimizer base.

Reference analogue: /root/reference/python/paddle/optimizer/optimizer.py
(+ per-op C++ kernels like adam_op.cu).  TPU-native: each optimizer is a
pure update rule `_rule(p, g, state, lr, t) -> (p', state')` over raw jnp
arrays.  Eager `step()` applies it per-parameter; the compiled path
(paddle_tpu.jit / hapi / fleet) calls `init()` + `apply_gradients()` on
whole pytrees inside ONE jitted XLA module, where states can be sharded
across the `dp` mesh axis for ZeRO-1 semantics.
"""
import jax.numpy as jnp

from ..core.tensor import Tensor
from .lr import LRScheduler

__all__ = ['Optimizer']


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        self._parameter_list = list(parameters) if parameters is not None \
            else None
        # static-mode minimize() re-resolves _parameter_list; keep the
        # constructor's explicit choice separate so precedence holds.
        # Built from the already-materialized list: `parameters` may be a
        # generator (common paddle idiom), which a second list() would
        # silently exhaust into [].
        self._ctor_parameter_list = None if self._parameter_list is None \
            else list(self._parameter_list)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if weight_decay is None:
            self._coupled_wd = 0.0
        elif isinstance(weight_decay, float):
            self._coupled_wd = weight_decay
        else:  # L1Decay / L2Decay object
            self._coupled_wd = weight_decay
        self._accumulators = {}   # id(param) -> state dict
        self._global_step = 0
        self._ctx_param_name = None  # name of the param currently in _rule

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return self._learning_rate

    def set_lr(self, value):
        self._learning_rate = float(value)

    def _lr_value(self, step):
        """LR as a traceable value for compiled steps."""
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate.value_at(step)
        return self._learning_rate

    # -- state ---------------------------------------------------------------
    def _create_state(self, p_value):
        """Return dict name→array of per-param slots (subclass)."""
        return {}

    def _rule(self, p, g, state, lr, t):
        """Pure update: (new_p, new_state) (subclass)."""
        raise NotImplementedError

    def _apply_weight_decay_grad(self, p, g):
        """Coupled (L2-to-grad) decay like the reference's regularizer."""
        wd = self._coupled_wd
        if wd:
            coeff = getattr(wd, '_coeff', wd)
            if getattr(wd, '_mode', 'l2') == 'l1':
                return g + coeff * jnp.sign(p)
            return g + coeff * p
        return g

    # -- eager API -----------------------------------------------------------
    @property
    def _params(self):
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters")
        return self._parameter_list

    def step(self):
        t = self._global_step + 1
        lr = self.get_lr()
        pg = [(p, p.grad) for p in self._params
              if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)
        for i, (p, g) in enumerate(pg):
            key = id(p)
            if key not in self._accumulators:
                self._accumulators[key] = self._create_state(p.value)
            g_v = g.value.astype(p.value.dtype)
            g_v = self._apply_weight_decay_grad(p.value, g_v)
            plr = lr * getattr(p, 'optimize_attr',
                               {'learning_rate': 1.0})['learning_rate']
            self._ctx_param_name = p.name or str(i)
            new_p, new_state = self._rule(p.value, g_v,
                                          self._accumulators[key], plr, t)
            p.value = new_p
            self._accumulators[key] = new_state
        self._global_step = t

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.program import Variable
        if isinstance(loss, Variable):
            # static mode: append the training section to the Program;
            # Executor.run compiles grad+update into the same XLA module.
            # Parameter selection per the reference's precedence:
            # minimize(parameters=...) > constructor list > every
            # trainable param the Program read — re-resolved on EVERY
            # minimize so layers added after an earlier call train too;
            # no_grad_set always excludes.
            ng = {id(p) for p in (no_grad_set or [])}
            if parameters is not None:
                chosen = [p for p in parameters if id(p) not in ng]
            elif self._ctor_parameter_list is not None:
                chosen = [p for p in self._ctor_parameter_list
                          if id(p) not in ng]
            else:
                chosen = loss.program.trainable_parameters(no_grad_set)
            self._parameter_list = list(chosen)
            loss.program.train_section = (loss, self)
            loss.program.bump()
            return None, []
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._params]

    def _accumulators_for(self, p):
        st = self._accumulators.get(id(p))
        if st is None:
            st = self._create_state(p.value)
            self._accumulators[id(p)] = st
        return st

    def clear_grad(self):
        for p in self._params:
            p.clear_grad()

    clear_gradients = clear_grad

    # -- functional API (compiled path) --------------------------------------
    def init(self, params):
        """params: pytree of raw arrays → pytree of state dicts."""
        import jax
        return jax.tree_util.tree_map(self._create_state, params)

    def apply_gradients(self, params, grads, state, step, lr=None):
        """Pure pytree update; call inside jit. Returns (params', state').

        `lr` (traced scalar) overrides the schedule — compiled callers
        pass the host-side get_lr() so scheduler/set_lr state changes
        reach the step without recompiling."""
        import jax
        if lr is None:
            lr = self._lr_value(step)
        paths_p, treedef = jax.tree_util.tree_flatten_with_path(params)
        leaves_p = [v for _, v in paths_p]
        names = ['/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                          for k in path) for path, _ in paths_p]
        leaves_g = treedef.flatten_up_to(grads)
        leaves_s = treedef.flatten_up_to(state)
        if self._grad_clip is not None:
            leaves_g = self._grad_clip.clip_values(leaves_g)
        new_p, new_s = [], []
        for p, g, s, name in zip(leaves_p, leaves_g, leaves_s, names):
            g = self._apply_weight_decay_grad(p, g.astype(p.dtype))
            self._ctx_param_name = name
            np_, ns_ = self._rule(p, g, s, lr, step)
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s))

    # -- checkpointing -------------------------------------------------------
    def state_dict(self):
        sd = {'global_step': self._global_step}
        if isinstance(self._learning_rate, LRScheduler):
            sd['LR_Scheduler'] = self._learning_rate.state_dict()
        for i, p in enumerate(self._params):
            st = self._accumulators.get(id(p))
            if st:
                for k, v in st.items():
                    sd[f'{p.name or i}_{k}'] = Tensor(v)
        return sd

    def set_state_dict(self, sd):
        self._global_step = sd.get('global_step', 0)
        if isinstance(self._learning_rate, LRScheduler) and \
                'LR_Scheduler' in sd:
            self._learning_rate.set_state_dict(sd['LR_Scheduler'])
        for i, p in enumerate(self._params):
            st = self._create_state(p.value)
            found = False
            for k in st:
                key = f'{p.name or i}_{k}'
                if key in sd:
                    v = sd[key]
                    st[k] = v.value if isinstance(v, Tensor) else \
                        jnp.asarray(v)
                    found = True
            if found:
                self._accumulators[id(p)] = st

    set_dict = set_state_dict
