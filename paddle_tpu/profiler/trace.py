"""On-device trace parsing: perfetto ``*.trace.json.gz`` → per-op
durations, with a census join against the compiled HLO module.

``jax.profiler.start_trace`` / ``stop_trace`` emit a gzip'd Chrome/
perfetto trace under ``<logdir>/plugins/profile/<run>/`` — on TPU the
device timeline threads carry one event per executed HLO instruction;
on the CPU backend the thunk executor annotates every instruction the
same way (one event per device per execution, named exactly like the
instruction in the compiled module text: ``all-reduce``, ``dot.1``,
``broadcast_multiply_fusion``).  That name identity is the whole
trick: a profiled collective joins the ``analysis.hlo`` census by
**instruction name**, which carries its opcode, operand bytes and
replica-group — so observed microseconds meet predicted wire bytes
and phases with no side channel.

Stdlib-only parsing (gzip + json — no tensorflow/tensorboard import):
this must run inside the training process at a profile-window close
and on a dev machine against an archived trace.

    prof = parse_trace('…/host.trace.json.gz')
    idx  = analysis.hlo.collective_instrs(module, mesh_shape=…)
    rows = match_collectives(prof, idx, num_partitions=8)
    # rows are ready to emit as ``collective_observed`` events

``telemetry.profile.StepProfiler`` drives exactly this pipeline on a
sampled schedule; ``tools/profile_run.py`` is the one-shot driver.
"""
import glob
import gzip
import json
import os
import re

__all__ = ['find_traces', 'parse_trace', 'TraceProfile',
           'match_collectives', 'collective_base', 'is_op_event_name']

# collective base opcodes (mirrors analysis.costmodel.COLLECTIVE_OPS;
# kept literal so this module imports nothing from the package and
# stays usable on a bare dev machine)
COLLECTIVE_OPS = ('all-reduce', 'all-gather', 'reduce-scatter',
                  'all-to-all', 'collective-permute')

# an XLA instruction name: lowercase opcode root plus dotted/dashed
# suffixes ('fusion.3', 'all-reduce-start.1', 'dot_general');
# runtime/infra annotations carry '::', '(', spaces, '$' or a
# CamelCase head ('ParseArguments') — instruction names never do
_OP_NAME_RE = re.compile(r'^[a-z_][\w.\-]*$')
# infra events that match the name shape anyway (seen on the CPU
# thunk runtime); anything here is host bookkeeping, not device work
_INFRA_NAMES = frozenset((
    'ParseArguments', 'CopyToDevice', 'CopyFromDevice', 'Execute',
    'ExecuteHelper', 'BufferFromHostBuffer', 'ToLiteral',
))
_SUFFIX_RE = re.compile(r'\.\d+$')


def is_op_event_name(name):
    """True when a trace event name looks like an executed HLO
    instruction (vs runtime scaffolding)."""
    if not name or name in _INFRA_NAMES:
        return False
    return bool(_OP_NAME_RE.match(name))


def collective_base(name):
    """Base collective opcode of an instruction name, or None.
    ``all-reduce-start.1`` → ``all-reduce``."""
    root = _SUFFIX_RE.sub('', name)
    for suffix in ('-start', '-done'):
        if root.endswith(suffix):
            root = root[:-len(suffix)]
    return root if root in COLLECTIVE_OPS else None


def _done_half(name):
    """True for the '-done' half of an async pair: its duration is
    the WAIT, already covered by the '-start' op's transfer time —
    totals that summed both would double-count one collective."""
    return _SUFFIX_RE.sub('', name).endswith('-done')


def find_traces(logdir):
    """All ``*.trace.json.gz`` under `logdir`, oldest → newest (one
    per host per capture; jax nests them under plugins/profile/<run>)."""
    pats = (os.path.join(logdir, '**', '*.trace.json.gz'),
            os.path.join(logdir, '*.trace.json.gz'))
    out = []
    for p in pats:
        out += glob.glob(p, recursive=True)
    out = sorted(set(out), key=lambda f: (os.path.getmtime(f), f))
    return out


class TraceProfile:
    """Aggregated per-op view of one captured trace.

    ``ops`` maps instruction name → {count, total_us, avg_us}; counts
    include every device's execution of every step inside the window
    (8 devices × 3 steps → count 24).  ``device_total_us`` /
    ``collective_total_us`` sum all op events — divide by
    (devices × steps) for a per-step-per-device figure.
    """

    __slots__ = ('ops', 'n_events', 'device_total_us',
                 'collective_total_us', 'source', 'device_pids')

    def __init__(self, ops, n_events=0, device_pids=0, source=None):
        self.ops = ops
        self.n_events = n_events
        self.device_pids = device_pids
        self.source = source
        self.device_total_us = sum(r['total_us'] for r in ops.values())
        self.collective_total_us = sum(
            r['total_us'] for r in ops.values()
            if collective_base(r['name'])
            and not _done_half(r['name']))

    def collectives(self):
        """The collective op rows, keyed by instruction name."""
        return {n: r for n, r in self.ops.items()
                if collective_base(n)}

    def top(self, k=20):
        return sorted(self.ops.values(),
                      key=lambda r: r['total_us'], reverse=True)[:k]

    def summary(self):
        return {'n_ops': len(self.ops), 'n_events': self.n_events,
                'device_total_us': round(self.device_total_us, 3),
                'collective_total_us': round(
                    self.collective_total_us, 3),
                'source': self.source}


def _load_doc(path_or_doc):
    if isinstance(path_or_doc, dict):
        return path_or_doc, None
    path = path_or_doc
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rt') as fh:
        return json.load(fh), path


def parse_trace(path_or_doc):
    """Parse one trace (a ``*.trace.json[.gz]`` path or an already-
    loaded dict) into a :class:`TraceProfile`.

    Device selection: when the trace carries device processes
    (``process_name`` metadata containing ``/device:`` — the TPU/GPU
    layout), only events on those pids count as op events; otherwise
    (CPU thunk runtime: one ``/host:CPU`` process whose worker threads
    run the thunks) every complete event whose name has the
    instruction shape counts.
    """
    doc, path = _load_doc(path_or_doc)
    events = doc.get('traceEvents', [])
    device_pids = set()
    for e in events:
        if e.get('ph') == 'M' and e.get('name') == 'process_name':
            pname = (e.get('args') or {}).get('name', '')
            if '/device:' in pname:
                device_pids.add(e.get('pid'))
    ops = {}
    n = 0
    for e in events:
        if e.get('ph') != 'X':
            continue
        name = e.get('name')
        if device_pids and e.get('pid') not in device_pids:
            continue
        if not is_op_event_name(name):
            continue
        dur = e.get('dur')
        if dur is None:
            continue
        row = ops.setdefault(name, {'name': name, 'count': 0,
                                    'total_us': 0.0})
        row['count'] += 1
        row['total_us'] += float(dur)
        n += 1
    for row in ops.values():
        row['total_us'] = round(row['total_us'], 3)
        row['avg_us'] = round(row['total_us'] / row['count'], 3)
    return TraceProfile(ops, n_events=n, device_pids=len(device_pids),
                        source=path)


def match_collectives(profile, instr_index, *, num_partitions=1,
                      name=None):
    """Join a trace profile against the compiled module's collective
    census index (``analysis.hlo.collective_instrs``).

    For each census instruction, the trace row of the same name (or
    its async ``-start`` twin — the start op carries the transfer
    time) yields observed per-call microseconds: the trace counts one
    event per device per execution, so ``us = total / count`` is the
    per-call, per-device duration and ``calls = count / devices`` the
    executions inside the window.  Returns rows shaped for
    ``collective_observed`` telemetry events: op, instr, us,
    wire_bytes, phases, calls, bytes, group_size, axes, predicted_us.

    Census instructions the trace never timed (elided by the backend)
    are skipped; trace collectives with no census row (no HLO text in
    hand) are NOT emitted — without bytes they cannot feed the
    calibration fit.
    """
    rows = []
    per_dev = max(1, int(num_partitions or 1))
    for iname, info in instr_index.items():
        # the census disambiguates cross-computation name collisions
        # as 'name@computation'; the trace knows only the bare name
        tname = iname.split('@', 1)[0]
        row = profile.ops.get(tname)
        if row is None:
            # async pair: census keys the '-start' op already, but a
            # backend may time the bare name (or vice versa).  The
            # numeric suffix stays OUTSIDE the toggle:
            # 'all-reduce-start.1' <-> 'all-reduce.1'
            m = _SUFFIX_RE.search(tname)
            root, suffix = (tname[:m.start()], m.group(0)) if m \
                else (tname, '')
            alt_root = root[:-len('-start')] \
                if root.endswith('-start') else root + '-start'
            row = profile.ops.get(alt_root + suffix)
        if row is None or not row['count']:
            continue
        calls = max(1, row['count'] // per_dev)
        out = {'op': info['op'], 'instr': iname,
               'us': round(row['total_us'] / row['count'], 3),
               'calls': calls,
               'wire_bytes': info['wire_bytes'],
               'phases': info['phases'],
               'bytes': info['bytes'],
               'group_size': info['group_size'],
               'axes': [list(a) for a in info.get('axes') or ()],
               'wire_dtype': info.get('wire_dtype'),
               'predicted_us': info.get('est_us')}
        if name:
            out['name'] = name
        rows.append(out)
    return rows
