"""Profiler (reference: python/paddle/fluid/profiler.py + platform/
profiler).  TPU-native: wraps jax.profiler traces (viewable in
TensorBoard/XProf) and adds host-side step timers — the reference's
nvprof hooks have no TPU meaning.  `op_summary` is the per-op table
(reference stop_profiler(sorted_key=...) prints per-op CUDA times;
here rows come from the step's optimized HLO, ranked by memory
traffic — the honest time proxy on an HBM-bound chip).

The trace a start/stop window emits is not just for the TensorBoard
UI any more: ``profiler.trace`` parses the perfetto ``*.trace.json.gz``
into per-op durations (stdlib gzip+json), and ``stop_profiler``
returns a parsed :class:`trace.TraceProfile` when asked — profiled
collectives join the ``analysis.hlo`` census by instruction name and
become ``collective_observed`` telemetry events (the calibration-fit
input).  The sampled in-training capture loop lives in
``telemetry.profile`` (``fit(profile=…)``,
``ParallelTrainer(profile=…)``, ``PADDLE_TPU_PROFILE``).
"""
import contextlib
import sys

import jax

# THE step timer of the stack lives in telemetry (its stop() feeds the
# recorder's step-time reservoir); this module and utils/profiler used
# to carry near-duplicate implementations — both now re-export it.
from ..telemetry import StepTimer  # noqa: F401
from . import trace  # noqa: F401
from .trace import (  # noqa: F401
    TraceProfile, parse_trace, find_traces, match_collectives)

__all__ = ['Profiler', 'start_profiler', 'stop_profiler', 'profiler',
           'reset_profiler', 'cuda_profiler', 'StepTimer', 'RecordEvent',
           'op_summary', 'trace', 'TraceProfile', 'parse_trace',
           'find_traces', 'match_collectives']


def op_summary(fn, *args, sorted_by='total', top=25, stream=None,
               print_table=True, hlo_text=None, totals=None):
    """Per-op summary table for one jitted step (reference
    fluid/profiler.py prints a per-op table via
    stop_profiler(sorted_key); there the rows are CUDA kernel times —
    here they come from the step's compiled, optimized HLO module).

    `fn` is a jitted callable (or anything `jax.jit` accepts) and
    `args` its example inputs; the step is lowered+compiled but NOT
    executed.  Each row aggregates one HLO opcode post-fusion:
    calls, output bytes (the HBM write traffic — the time proxy on a
    bandwidth-bound chip), and its ratio of the module total.  Rows
    cover the ENTRY computation plus while/cond bodies (counted once,
    not by trip count); fusion internals are folded into their single
    `fusion` call-site row.
    Module-level flops / bytes-accessed from
    `compiled.cost_analysis()` head the table when XLA reports them.

    sorted_by: 'total'/'bytes' ranks by bytes, 'calls' by call count.
    Returns the rows as a list of dicts (opcode, calls, bytes, ratio).

    hlo_text: compiled HLO text already in hand (a trainer's
    ``compiled_text()``, the planner's lowering memo, or the
    persistent compile cache's text tier) — skips the lower+compile
    entirely, so profiling a just-trained fn is free.  Module-total
    cost_analysis rows need the live compiled object: pass them via
    ``totals`` when the caller has them (ParallelTrainer stashes
    them at its one lowering), else they are omitted on that path.
    """
    if sorted_by not in ('total', 'bytes', 'calls'):
        raise ValueError(
            f"sorted_by must be 'total', 'bytes' or 'calls', "
            f'got {sorted_by!r}')
    totals = dict(totals or {})
    if hlo_text is None:
        jitted = fn if hasattr(fn, 'lower') else jax.jit(fn)
        compiled = jitted.lower(*args).compile()
        hlo_text = compiled.as_text()
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):  # older jax returns [dict]
                ca = ca[0] if ca else {}
            for key in ('flops', 'bytes accessed'):
                if ca.get(key):
                    totals[key] = float(ca[key])
        except Exception:       # backend without cost analysis
            pass

    # the HLO-text grammar lives in ONE place: analysis.hlo's parser
    # (walk() = ENTRY + while/cond bodies, fusion internals folded
    # into their call-site `fusion` row — exactly the rows we want)
    from ..analysis import hlo as _hlo
    agg = {}
    for _comp, ins in _hlo.parse_module(hlo_text).walk():
        if ins.opcode in ('parameter', 'constant', 'tuple',
                          'get-tuple-element'):
            continue        # plumbing, not work
        row = agg.setdefault(ins.opcode, {'opcode': ins.opcode,
                                          'calls': 0, 'bytes': 0})
        row['calls'] += 1
        row['bytes'] += ins.bytes
    grand = sum(r['bytes'] for r in agg.values()) or 1
    key = 'calls' if sorted_by == 'calls' else 'bytes'
    rows = sorted(agg.values(), key=lambda r: r[key], reverse=True)
    for r in rows:
        r['ratio'] = r['bytes'] / grand
    if print_table:
        out = stream or sys.stdout
        print('------------------------- op summary '
              '-------------------------', file=out)
        for k, v in totals.items():
            print(f'module {k}: {v:.3e}', file=out)
        print(f'{"op":<28}{"calls":>8}{"out bytes":>14}{"ratio":>8}',
              file=out)
        for r in rows[:top]:
            print(f'{r["opcode"]:<28}{r["calls"]:>8}'
                  f'{r["bytes"]:>14,}{r["ratio"]:>8.2%}', file=out)
        if len(rows) > top:
            rest = sum(r['bytes'] for r in rows[top:])
            print(f'{"... (" + str(len(rows) - top) + " more)":<28}'
                  f'{"":>8}{rest:>14,}{rest / grand:>8.2%}', file=out)
    return rows

_active_logdir = None


def reset_profiler():
    """Drop profiling state gathered so far (reference:
    fluid.profiler.reset_profiler).  XLA traces are windowed by
    start/stop, so there is no cumulative op table to clear — an active
    trace is aborted and restarted on the same logdir."""
    global _active_logdir
    if _active_logdir is not None:
        logdir = _active_logdir
        jax.profiler.stop_trace()
        jax.profiler.start_trace(logdir)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """nvprof hook (reference: fluid.profiler.cuda_profiler) — no CUDA
    on TPU, so this delegates to the XLA trace so legacy scripts still
    produce a usable (XProf) profile."""
    import warnings
    warnings.warn('cuda_profiler has no CUDA meaning on TPU; recording '
                  'an XLA trace instead (view with tensorboard)')
    start_profiler()
    try:
        yield
    finally:
        stop_profiler()


def start_profiler(state=None, tracer_option=None,
                   logdir='/tmp/paddle_tpu_profile'):
    """Begin a device+host trace (reference: fluid.profiler.start_profiler).
    View with tensorboard --logdir <logdir>."""
    global _active_logdir
    jax.profiler.start_trace(logdir)
    _active_logdir = logdir
    return logdir


def stop_profiler(sorted_key=None, profile_path=None, parse=False):
    """End the window.  Returns the logdir (legacy contract), or —
    with ``parse=True`` — the parsed :class:`trace.TraceProfile` of
    the newest emitted trace (None when nothing was written)."""
    global _active_logdir
    jax.profiler.stop_trace()
    out = _active_logdir
    _active_logdir = None
    if parse and out is not None:
        files = find_traces(out)
        return parse_trace(files[-1]) if files else None
    return out


@contextlib.contextmanager
def profiler(state=None, sorted_key=None,
             logdir='/tmp/paddle_tpu_profile'):
    start_profiler(state, logdir=logdir)
    try:
        yield
    finally:
        stop_profiler(sorted_key)


class RecordEvent:
    """Named host-side trace annotation (reference: RecordEvent);
    shows up in the XProf timeline via jax.profiler.TraceAnnotation."""

    def __init__(self, name):
        self.name = name
        self._ctx = None

    def __enter__(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        self._ctx = None


class Profiler:
    """paddle.profiler.Profiler-style context (2.x API shape)."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 logdir='/tmp/paddle_tpu_profile'):
        self.logdir = logdir
        self.timer = StepTimer()
        self._running = False

    def start(self):
        start_profiler(logdir=self.logdir)
        self._running = True
        self.timer.start()

    def stop(self):
        if self._running:
            stop_profiler()
            self._running = False

    def step(self, sync=None):
        self.timer.stop(sync)
        self.timer.start()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, *a, **k):
        return self.timer.summary()
