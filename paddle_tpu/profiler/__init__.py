"""Profiler (reference: python/paddle/fluid/profiler.py + platform/
profiler).  TPU-native: wraps jax.profiler traces (viewable in
TensorBoard/XProf) and adds host-side step timers — the reference's
nvprof hooks have no TPU meaning.
"""
import contextlib
import time

import jax

__all__ = ['Profiler', 'start_profiler', 'stop_profiler', 'profiler',
           'reset_profiler', 'cuda_profiler', 'StepTimer', 'RecordEvent']

_active_logdir = None


def reset_profiler():
    """Drop profiling state gathered so far (reference:
    fluid.profiler.reset_profiler).  XLA traces are windowed by
    start/stop, so there is no cumulative op table to clear — an active
    trace is aborted and restarted on the same logdir."""
    global _active_logdir
    if _active_logdir is not None:
        logdir = _active_logdir
        jax.profiler.stop_trace()
        jax.profiler.start_trace(logdir)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """nvprof hook (reference: fluid.profiler.cuda_profiler) — no CUDA
    on TPU, so this delegates to the XLA trace so legacy scripts still
    produce a usable (XProf) profile."""
    import warnings
    warnings.warn('cuda_profiler has no CUDA meaning on TPU; recording '
                  'an XLA trace instead (view with tensorboard)')
    start_profiler()
    try:
        yield
    finally:
        stop_profiler()


def start_profiler(state=None, tracer_option=None,
                   logdir='/tmp/paddle_tpu_profile'):
    """Begin a device+host trace (reference: fluid.profiler.start_profiler).
    View with tensorboard --logdir <logdir>."""
    global _active_logdir
    jax.profiler.start_trace(logdir)
    _active_logdir = logdir
    return logdir


def stop_profiler(sorted_key=None, profile_path=None):
    global _active_logdir
    jax.profiler.stop_trace()
    out = _active_logdir
    _active_logdir = None
    return out


@contextlib.contextmanager
def profiler(state=None, sorted_key=None,
             logdir='/tmp/paddle_tpu_profile'):
    start_profiler(state, logdir=logdir)
    try:
        yield
    finally:
        stop_profiler(sorted_key)


class RecordEvent:
    """Named host-side trace annotation (reference: RecordEvent);
    shows up in the XProf timeline via jax.profiler.TraceAnnotation."""

    def __init__(self, name):
        self.name = name
        self._ctx = None

    def __enter__(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        self._ctx = None


class StepTimer:
    """Rolling step-time statistics for training loops.

    Blocks on `sync` targets (device arrays) so timings reflect device
    completion, not dispatch."""

    def __init__(self, window=50):
        self.window = window
        self._times = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, sync=None):
        if sync is not None:
            jax.block_until_ready(sync)
        dt = time.perf_counter() - self._t0
        self._times.append(dt)
        if len(self._times) > self.window:
            self._times.pop(0)
        return dt

    @property
    def mean_ms(self):
        if not self._times:
            return 0.0
        return sum(self._times) / len(self._times) * 1000.0

    def summary(self):
        if not self._times:
            return {}
        ts = sorted(self._times)
        n = len(ts)
        return {'mean_ms': self.mean_ms,
                'p50_ms': ts[n // 2] * 1000.0,
                'p90_ms': ts[min(n - 1, int(n * 0.9))] * 1000.0,
                'max_ms': ts[-1] * 1000.0,
                'steps': n}


class Profiler:
    """paddle.profiler.Profiler-style context (2.x API shape)."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 logdir='/tmp/paddle_tpu_profile'):
        self.logdir = logdir
        self.timer = StepTimer()
        self._running = False

    def start(self):
        start_profiler(logdir=self.logdir)
        self._running = True
        self.timer.start()

    def stop(self):
        if self._running:
            stop_profiler()
            self._running = False

    def step(self, sync=None):
        self.timer.stop(sync)
        self.timer.start()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, *a, **k):
        return self.timer.summary()
