"""Inference API (reference: paddle/fluid/inference + paddle.inference).

The reference's Predictor loads a serialized Program and runs it through
the C++ analysis/optimization passes; here a saved `paddle_tpu.jit`
artifact (StableHLO + params) reloads as a jitted callable — XLA is the
analysis/optimization stack.  Config/Predictor/Tensor mirror the
reference's surface so deployment scripts port directly.
"""
import numpy as np

__all__ = ['Config', 'create_predictor', 'Predictor', 'PredictorTensor']


class Config:
    def __init__(self, prog_file=None, params_file=None):
        # jit.save writes one prefix; either arg may carry it
        self.model_path = prog_file
        self._use_tpu = True
        self._memory_optim = True
        self._glog_info = False

    # GPU knobs exist for parity; TPU ignores them
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        self._memory_optim = True

    def disable_glog_info(self):
        self._glog_info = False

    def switch_ir_optim(self, flag=True):
        pass

    def model_dir(self):
        return self.model_path


class PredictorTensor:
    """Input/output handle (reference: paddle_infer::Tensor)."""

    def __init__(self, name):
        self.name = name
        self._data = None

    def copy_from_cpu(self, arr):
        self._data = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._data)

    def reshape(self, shape):
        if self._data is not None:
            self._data = self._data.reshape(shape)


class Predictor:
    def __init__(self, config):
        from .. import jit as _jit
        self._fn = _jit.load(config.model_path)
        self._inputs = {}
        self._outputs = []

    def get_input_names(self):
        """Real tensor names from the saved InputSpecs (reference
        deployments feed by name); positional input_i only when the
        artifact predates named specs."""
        names = getattr(self._fn, 'input_names', None)
        if callable(names):
            got = names()
            if got:
                return got
        n = getattr(self._fn, 'n_inputs', None) or 1
        return [f'input_{i}' for i in range(n)]

    def get_input_handle(self, name):
        h = self._inputs.get(name)
        if h is None:
            h = self._inputs[name] = PredictorTensor(name)
        return h

    def run(self):
        names = self.get_input_names()
        missing = [n for n in names if n not in self._inputs]
        if missing:
            raise KeyError(
                f'inputs {missing} were not fed — call '
                f'get_input_handle(name).copy_from_cpu(...) for each of '
                f'{names} before run()')
        args = [self._inputs[n]._data for n in names]
        out = self._fn(*args)
        if not isinstance(out, (tuple, list)):
            out = [out]
        self._outputs = []
        for i, o in enumerate(out):
            t = PredictorTensor(f'output_{i}')
            t._data = np.asarray(getattr(o, 'value', o))
            self._outputs.append(t)
        return True

    def get_output_names(self):
        return [t.name for t in self._outputs]

    def get_output_handle(self, name):
        for t in self._outputs:
            if t.name == name:
                return t
        raise KeyError(name)


def create_predictor(config):
    return Predictor(config)
