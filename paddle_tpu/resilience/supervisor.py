"""Self-healing runtime: the plan supervisor ACTUATOR.

PRs 13–15 built the sensors — latched ``slo_breach`` /
``drift_detected`` edges (telemetry.monitors), ``straggler_suspect``
/ ``rank_divergence`` from the cluster view, the watchdog's own
``straggler`` / ``quorum_lost`` escalations, measured step budgets
and live-fitted calibration.  This module closes the observe→act
loop: a :class:`PlanSupervisor` subscribes to the Recorder's event
stream, debounces and classifies each trigger into a remediation
policy, re-runs the PR-6 planner against the *current* health of the
cluster (live calibration, healthy ranks only), AOT-compiles the
winning candidate's real train step in the background through the
PR-7 persistent compile cache, and swaps plans at a chunk boundary —
the compiled sharded module as the reconfiguration unit, in the
spirit of Flex-TPU's runtime-reconfigurable dataflow.

The actuator is governed by a STRICT safety ladder; every rung
degrades to the incumbent plan, never crashes the job:

1. classify   the trigger maps to a policy (``replan`` /
              ``exclude_rank`` / ``backoff``); unknown triggers are
              dropped.
2. debounce   triggers within ``debounce_s`` of the first coalesce
              into ONE incident; a cooldown window after any
              actuation suppresses re-fire, so a single sustained
              incident actuates exactly once (``remediation`` events
              record suppressed triggers).
3. re-plan    the planner runs with the incident-adjusted
              calibration (a drifted collective's measured penalty is
              folded into ``per_op``) and only the healthy device
              set.  Planner failure → degrade.
4. margin     the candidate's predicted step must beat the
              incumbent's estimate — the incumbent re-scored in the
              SAME planner run when possible, else the live-measured
              step profile — by at least ``margin`` (fractional).
              Not better → hold.
5. precompile the candidate's REAL train step AOT-compiles in the
              background (through the compile cache, so the post-swap
              rebuild deserializes instead of recompiling).  Compile
              failure → degrade.
6. swap       the new plan is queued; the trainer applies it at the
              next step/chunk boundary via the elastic-reshape
              restore path and emits ``plan_swap``.  Swap failure →
              revert to the incumbent state, degrade.

Opt-in posture (the watchdog's exactly): ``ParallelTrainer(
supervisor=True|dict|SupervisorConfig)`` or the
``PADDLE_TPU_SUPERVISOR`` env (default OFF; explicit ``False`` beats
the env; conftest pins ``0`` so no test arms it by accident).
``ChaosCluster(supervisor=...)`` / ``tools/soak_run.py`` arm it
inside chaos workers, where the multi-process swap path rides the
:func:`paddle_tpu.distributed.elastic.request_reshape` coordinated
restart (no ``max_restarts`` burn — same posture as preemptions).
"""
import json
import os
import queue
import threading
import time

__all__ = ['SUPERVISOR_ENV', 'TRIGGER_POLICIES', 'SupervisorConfig',
           'resolve_supervisor', 'PlanSupervisor', 'TrainerHost',
           'drift_calibration', 'memory_budget_hint',
           'write_reshape_request', 'read_reshape_request',
           'RESHAPE_REQUEST_NAME']

SUPERVISOR_ENV = 'PADDLE_TPU_SUPERVISOR'

# trigger event kind -> remediation policy.  ``replan`` runs the full
# safety ladder; ``exclude_rank`` re-plans over the healthy subset
# (the suspect's devices dropped when the host can attribute them);
# ``backoff`` records the incident and arms the cooldown WITHOUT
# actuating — divergence and lost quorum are states a new sharding
# plan cannot fix (restore/restart machinery owns them), so acting
# would only thrash.
TRIGGER_POLICIES = {
    'drift_detected': 'replan',
    'slo_breach': 'replan',
    'memory_pressure': 'replan',
    'straggler_suspect': 'exclude_rank',
    'straggler': 'exclude_rank',
    'rank_divergence': 'backoff',
    'quorum_lost': 'backoff',
    # an attributed SPMD-contract divergence (the collective flight
    # recorder named the first mismatched collective + call sites):
    # like rank_divergence no sharding plan fixes it, but the incident
    # now carries the exact call site instead of a blind loss split
    'collective_mismatch': 'backoff',
}

_MONO = time.monotonic


def _emit(kind, **data):
    from .. import telemetry as _tel
    return _tel.event(kind, **data)


class SupervisorConfig:
    """Knobs of the safety ladder.

    debounce_s   triggers arriving within this window of the first
                 coalesce into one incident (sensors latch, but
                 several sensors can fire for one cause).
    cooldown_s   after ANY terminal outcome (swap/hold/degraded/
                 backoff) new triggers are suppressed for this long —
                 the hysteresis making "one incident → at most one
                 actuation" structural, and giving a fresh plan time
                 to prove itself before it can be re-judged.
    margin       fractional improvement the candidate's predicted
                 step must show over the incumbent's estimate
                 (0.1 = 10% faster) before a swap is worth its cost.
    max_swaps    lifetime cap on actuated swaps (None = unbounded) —
                 a mis-tuned sensor can never turn the supervisor
                 into a plan-thrashing loop.
    policies     overrides merged over TRIGGER_POLICIES (a dict, or
                 ``{'slo_breach': None}`` to drop a trigger).
    """

    def __init__(self, debounce_s=0.25, cooldown_s=30.0, margin=0.1,
                 max_swaps=None, policies=None):
        self.debounce_s = float(debounce_s)
        self.cooldown_s = float(cooldown_s)
        self.margin = float(margin)
        self.max_swaps = None if max_swaps is None else int(max_swaps)
        self.policies = dict(TRIGGER_POLICIES)
        for k, v in (policies or {}).items():
            if v is None:
                self.policies.pop(k, None)
            else:
                self.policies[k] = v

    @classmethod
    def from_env(cls, text):
        """Parse the PADDLE_TPU_SUPERVISOR value: '1'/'on' ->
        defaults; 'margin=0.2,cooldown=10,debounce=1' -> numbers."""
        text = (text or '').strip()
        if text.lower() in ('', '0', 'off', 'false'):
            return None
        if text.lower() in ('1', 'on', 'true'):
            return cls()
        kwargs = {}
        keymap = {'debounce': 'debounce_s', 'cooldown': 'cooldown_s',
                  'margin': 'margin', 'max_swaps': 'max_swaps'}
        for part in text.split(','):
            if '=' not in part:
                continue
            k, v = part.split('=', 1)
            k = keymap.get(k.strip())
            if k is None:
                continue
            try:
                kwargs[k] = float(v) if k != 'max_swaps' else int(v)
            except ValueError:
                pass
        return cls(**kwargs)

    def to_dict(self):
        return {'debounce_s': self.debounce_s,
                'cooldown_s': self.cooldown_s, 'margin': self.margin,
                'max_swaps': self.max_swaps}


def resolve_supervisor(arg):
    """The shared opt-in posture (resolve_watchdog's exactly):
    explicit False -> None (off even if the env says on); True ->
    SupervisorConfig(); config/dict pass through; None -> the
    PADDLE_TPU_SUPERVISOR env decides.  Returns a SupervisorConfig or
    None."""
    if arg is False:
        return None
    if arg is None:
        return SupervisorConfig.from_env(os.environ.get(SUPERVISOR_ENV))
    if arg is True:
        return SupervisorConfig()
    if isinstance(arg, SupervisorConfig):
        return arg
    if isinstance(arg, dict):
        return SupervisorConfig(**arg)
    raise TypeError(
        f'supervisor= expects bool/dict/SupervisorConfig, got {arg!r}')


def drift_calibration(base, incidents):
    """Fold the observed drift back into the planner's cost model: a
    ``drift_detected`` trigger carries the measured
    observed/predicted ``us_ratio`` for one collective — the re-plan
    must score that op at its MEASURED cost, or it would happily
    re-pick the plan the drift just invalidated.  Returns a new
    ``costmodel.Calibration`` (base entries preserved; the drifted
    op's alpha/beta scaled by the ratio), or ``base`` unchanged when
    no trigger carries a usable ratio."""
    from ..analysis import costmodel as _cm
    per_op = {}
    if base is not None:
        per_op.update({k: dict(v) for k, v in base.per_op.items()})
    touched = False
    for data in incidents:
        op = data.get('op')
        ratio = data.get('us_ratio')
        if not op or not ratio or ratio <= 1.0:
            continue
        ent = per_op.get(op, {})
        alpha = ent.get('alpha_us')
        beta = ent.get('beta_us_per_byte')
        if alpha is None:
            alpha = _cm.DEFAULT_LINK_LATENCY_US
        if beta is None:
            # analytic default: 1 / (bw in bytes/us)
            beta = 1.0 / (_cm.DEFAULT_LINK_BW_GBPS * 1e3)
        per_op[op] = {'alpha_us': alpha * ratio,
                      'beta_us_per_byte': beta * ratio}
        touched = True
    if not touched:
        return base
    return _cm.Calibration(
        per_op=per_op,
        link_bw_gbps=getattr(base, 'link_bw_gbps', None),
        link_latency_us=getattr(base, 'link_latency_us', None),
        meta={'source': 'supervisor-drift'})


def memory_budget_hint(incidents, safety=0.9):
    """A TIGHTENED ``hbm_budget_gb`` for the re-plan after a
    ``memory_pressure`` trigger, or None when no trigger carries the
    live numbers.  The breached plan passed the planner's HBM gate yet
    overshot live — the liveness estimate understates this workload by
    (at worst) observed/budget — so the re-plan must clear a gate
    shrunk by that factor times a safety margin, making the swapped-in
    plan provably fit where the incumbent provably did not."""
    hint = None
    for data in incidents:
        observed = data.get('observed_bytes')
        budget = data.get('budget_bytes')
        if not observed or not budget:
            continue
        gb = (budget / float(1 << 30)) \
            * min(1.0, budget / observed) * float(safety)
        hint = gb if hint is None else min(hint, gb)
    return hint


# -- multi-process swap path: the coordinated-reshape request file ------------

RESHAPE_REQUEST_NAME = 'reshape_request.json'


def write_reshape_request(workdir, mesh=None, env=None, reason=None,
                          seq=None):
    """Queue a supervisor-initiated coordinated restart for the
    elastic supervisor watching this workdir: atomically write
    ``reshape_request.json`` with a monotone ``seq`` (the watch loop
    acts once per new seq).  ``env`` entries are merged into every
    worker's environment on the restart — how a new mesh/plan reaches
    the next incarnation.  Returns the seq written."""
    from .manifest import atomic_write
    path = os.path.join(workdir, RESHAPE_REQUEST_NAME)
    if seq is None:
        prev = read_reshape_request(workdir)
        seq = (prev.get('seq', 0) if prev else 0) + 1
    doc = {'seq': int(seq), 'ts': time.time(),
           'mesh': dict(mesh) if mesh else None,
           'env': {k: str(v) for k, v in (env or {}).items()},
           'reason': reason}
    atomic_write(path, lambda f: f.write(json.dumps(doc,
                                                    sort_keys=True)))
    return doc['seq']


def read_reshape_request(workdir):
    """The pending reshape request under `workdir`, or None (missing
    or torn file — a half-written request must read as absent, never
    crash the watch loop)."""
    try:
        with open(os.path.join(workdir, RESHAPE_REQUEST_NAME)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) and 'seq' in doc else None


class PlanSupervisor:
    """The actuator thread: recorder subscription in, remediation
    (``remediation`` / ``plan_swap`` telemetry) out.

    `host` supplies the environment the ladder runs against — a
    :class:`TrainerHost` wrapping a live ``ParallelTrainer`` (the
    in-process path), or any object with the same five methods (the
    chaos soak uses a rank-0 file-writing host):

      healthy_devices(incident) -> device list for the re-plan
      replan(devices, calibration) -> planner PlanResult
      incumbent() -> (plan, step_estimate_s) — either may be None
      precompile(plan, devices) -> None (raise on failure)
      request_swap(plan, devices, incident) -> True when queued

    Every host call runs on the supervisor's own daemon thread; a
    raised exception anywhere degrades that incident to the incumbent
    plan.  ``stop()`` (or the thread dying) leaves training entirely
    untouched — the trainer only ever sees a queued plan it applies
    at its own boundary."""

    def __init__(self, host, config=None):
        self.host = host
        self.config = config or SupervisorConfig()
        self._q = queue.Queue()
        # _lock covers the state shared between the worker thread and
        # whoever calls start()/stop() or reads the counters (bench,
        # tests, the trainer's teardown).  Held for dict/counter
        # updates only — never across host calls or joins.
        self._lock = threading.Lock()
        self._thread = None         # guarded-by: _lock
        self._stop = threading.Event()
        self._cooldown_until = 0.0  # guarded-by: _lock
        self._subscribed = False    # guarded-by: _lock
        self.swaps = 0              # guarded-by: _lock (lifetime swaps)
        self.incidents = []         # guarded-by: _lock (terminal recs)
        self._suppressed = 0        # guarded-by: _lock

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Subscribe to the recorder and start the worker thread.
        Idempotent (and safe against concurrent start/stop); returns
        self."""
        from ..telemetry import get_recorder
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            subscribe = not self._subscribed
            self._subscribed = True
            t = self._thread = threading.Thread(
                target=self._run, name='plan-supervisor', daemon=True)
        if subscribe:
            get_recorder().subscribe(self._on_event)
        t.start()
        return self

    def stop(self, timeout=5.0):
        """Unsubscribe and stop the worker.  Training continues
        untouched — an already-queued swap still applies (the trainer
        owns it), but no new incident is ever processed."""
        with self._lock:
            unsub = self._subscribed
            self._subscribed = False
            t, self._thread = self._thread, None
        if unsub:
            from ..telemetry import get_recorder
            try:
                get_recorder().unsubscribe(self._on_event)
            except Exception:
                pass
        self._stop.set()
        # join OUTSIDE the lock: a worker parked in _handle must be
        # able to take _lock to finish its incident while we wait
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout)

    # -- recorder subscriber ----------------------------------------------
    def _on_event(self, rec):
        """Called inline by the recorder's notify loop: filter to the
        trigger vocabulary and enqueue — never block, never raise
        (the recorder swallows exceptions, but a slow subscriber
        would stall every emitter)."""
        try:
            kind = rec.get('kind')
            if kind not in self.config.policies:
                return
            if self._stop.is_set():
                return
            self._q.put_nowait(dict(rec))
        except Exception:
            pass

    # -- worker ------------------------------------------------------------
    def _run(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._handle(first)
            except Exception:
                # the ladder has its own degrade path; this catches
                # bookkeeping bugs — the actuator must never die loud
                pass

    def _drain(self, deadline):
        """Coalesce triggers until `deadline`; returns them."""
        more = []
        while True:
            left = deadline - _MONO()
            if left <= 0:
                break
            try:
                more.append(self._q.get(timeout=left))
            except queue.Empty:
                break
        return more

    def _handle(self, first):
        cfg = self.config
        now = _MONO()
        with self._lock:
            cooled = now < self._cooldown_until
        if cooled:
            # inside the cooldown: the incident already actuated (or
            # terminally resolved); count, don't act
            n = 1 + self._qsize_drain()
            with self._lock:
                self._suppressed += n
            return
        triggers = [first] + self._drain(now + cfg.debounce_s)
        incident = {
            'trigger': first.get('kind'),
            'policy': cfg.policies.get(first.get('kind')),
            'triggers': len(triggers),
            'kinds': sorted({t.get('kind') for t in triggers}),
            'data': triggers,
        }
        with self._lock:
            self._suppressed = 0
        # the ladder (planner re-entry, AOT compile) runs UNLOCKED —
        # holding _lock across it would park stop() for minutes
        outcome = self._remediate(incident)
        incident['outcome'] = outcome
        with self._lock:
            self._cooldown_until = _MONO() + cfg.cooldown_s
            self.incidents.append(incident)

    def _qsize_drain(self):
        n = 0
        while True:
            try:
                self._q.get_nowait()
                n += 1
            except queue.Empty:
                return n

    def _terminal(self, incident, outcome, **data):
        _emit('remediation', trigger=incident['trigger'],
              policy=incident['policy'], outcome=outcome,
              triggers=incident['triggers'],
              kinds=incident['kinds'], **data)
        return outcome

    def _remediate(self, incident):
        """One incident through the safety ladder; returns the
        terminal outcome string ('swap'/'hold'/'backoff'/
        'degraded')."""
        cfg = self.config
        policy = incident['policy']
        if policy == 'backoff':
            return self._terminal(incident, 'backoff')
        with self._lock:
            swaps = self.swaps
        if cfg.max_swaps is not None and swaps >= cfg.max_swaps:
            return self._terminal(incident, 'hold',
                                  reason='max_swaps reached')
        host = self.host
        # rung 3: re-plan over the healthy set with the incident-
        # adjusted calibration
        try:
            devices = host.healthy_devices(incident)
            cal = drift_calibration(
                host.calibration(), incident['data'])
            # a memory_pressure trigger tightens the re-plan's HBM
            # gate; passed conditionally so hosts with the classic
            # 2-arg replan keep working for every other trigger
            hint = memory_budget_hint(incident['data'])
            if hint is None:
                result = host.replan(devices, cal)
            else:
                incident['hbm_budget_gb'] = round(hint, 4)
                try:
                    result = host.replan(devices, cal,
                                         hbm_budget_gb=hint)
                except TypeError:
                    result = host.replan(devices, cal)
            cand = result.winner if result is not None else None
        except Exception as e:
            return self._terminal(incident, 'degraded', stage='plan',
                                  error=repr(e))
        if cand is None:
            return self._terminal(incident, 'degraded', stage='plan',
                                  error='no candidate fit the budget')
        # rung 4: the margin gate.  Prefer the incumbent re-scored in
        # the SAME planner run (identical cost model, so the
        # comparison is apples-to-apples); fall back to the live-
        # measured step estimate.
        try:
            inc_plan, inc_meas_s = host.incumbent()
        except Exception:
            inc_plan, inc_meas_s = None, None
        if inc_plan is not None \
                and dict(cand.mesh_axes) == dict(inc_plan.mesh_axes) \
                and cand.assignment == getattr(inc_plan, 'assignment',
                                               None):
            return self._terminal(
                incident, 'hold', reason='winner is the incumbent',
                mesh=dict(cand.mesh_axes))
        inc_s = None
        if inc_plan is not None and result is not None:
            for p in result.candidates + result.fallbacks:
                if dict(p.mesh_axes) == dict(inc_plan.mesh_axes) \
                        and p.assignment == inc_plan.assignment:
                    inc_s = p.score_us * 1e-6
                    break
        if inc_s is None:
            inc_s = inc_meas_s
        cand_s = cand.score_us * 1e-6
        if inc_s is not None and cand_s > inc_s * (1.0 - cfg.margin):
            return self._terminal(
                incident, 'hold', reason='margin not met',
                candidate_s=round(cand_s, 6),
                incumbent_s=round(inc_s, 6), margin=cfg.margin)
        # rung 5: background AOT compile of the real step
        try:
            host.precompile(cand, devices)
        except Exception as e:
            return self._terminal(incident, 'degraded',
                                  stage='compile', error=repr(e))
        # rung 6: queue the swap at the trainer's boundary
        try:
            if not host.request_swap(cand, devices, incident):
                return self._terminal(incident, 'hold',
                                      reason='swap refused')
        except Exception as e:
            return self._terminal(incident, 'degraded', stage='swap',
                                  error=repr(e))
        with self._lock:
            self.swaps += 1
        extra = {}
        if incident.get('hbm_budget_gb') is not None:
            extra['hbm_budget_gb'] = incident['hbm_budget_gb']
        return self._terminal(
            incident, 'swap', mesh=dict(cand.mesh_axes),
            assignment=cand.assignment,
            candidate_s=round(cand_s, 6),
            incumbent_s=None if inc_s is None else round(inc_s, 6),
            **extra)


class TrainerHost:
    """The in-process host: the ladder runs against a live
    ``ParallelTrainer``.  Planner re-entry reuses the trainer's model
    / batch shapes / HBM budget; the swap is QUEUED
    (``trainer._pending_plan``) and applied by the trainer itself at
    the next step/chunk boundary — the supervisor thread never
    touches live device state."""

    def __init__(self, trainer):
        self.trainer = trainer

    def calibration(self):
        return self.trainer._resolved_calibration()

    def healthy_devices(self, incident):
        """The device set the re-plan may use: the trainer's current
        mesh (else all visible), minus any devices attributed to a
        straggler suspect when the policy excludes ranks and the
        attribution maps onto local devices (single-host multi-device
        meshes; on one-device-per-process topologies exclusion is the
        elastic layer's job)."""
        import jax
        t = self.trainer
        devices = (list(t.mesh.devices.flat) if t.mesh is not None
                   else list(jax.devices()))
        if incident.get('policy') != 'exclude_rank':
            return devices
        suspects = {d.get('suspect') for d in incident['data']
                    if d.get('suspect') is not None}
        if not suspects:
            return devices
        healthy = [d for d in devices if d.id not in suspects]
        # never exclude below half the fleet: mass exclusion is a
        # sensor failure, not a remediation
        if len(healthy) < max(1, len(devices) // 2):
            return devices
        return healthy or devices

    def incumbent(self):
        t = self.trainer
        meas = None
        try:
            dts = list(t._measured_dts)
            if dts:
                dts.sort()
                meas = dts[len(dts) // 2]        # median live step
        except Exception:
            meas = None
        return t.plan, meas

    def replan(self, devices, calibration, hbm_budget_gb=None):
        from ..analysis import planner as _planner
        t = self.trainer
        vals = getattr(t, '_example_vals', None)
        if not vals:
            raise RuntimeError('trainer has not compiled a step yet')
        batch = tuple(vals[:t.n_inputs])
        budget = (t.hbm_budget_gb if hbm_budget_gb is None
                  else hbm_budget_gb)
        return _planner.plan_model(
            t.model, batch, chips=len(devices), devices=list(devices),
            hbm_budget_gb=budget, calibration=calibration,
            include_pp=False, name=type(t.model).__name__)

    def precompile(self, plan, devices):
        self.trainer.precompile_plan(plan, devices)

    def request_swap(self, plan, devices, incident):
        t = self.trainer
        if getattr(t, '_pending_plan', None) is not None:
            return False
        t._pending_plan = (plan, list(devices), {
            'trigger': incident.get('trigger'),
            'policy': incident.get('policy')})
        return True
