"""NanSentinel — divergence policy for training loops.

A non-finite loss or gradient norm on a TPU pod is almost never a
one-off: by the time a human sees it in a dashboard the optimizer
state is poisoned and days of compute follow it down.  The sentinel
encodes the standard production response as a tiny state machine:

  finite step          -> 'ok'        (strike counter resets)
  non-finite step      -> 'skip'      (the update was/will be dropped;
                                       the amp GradScaler's found_inf
                                       skip composes — both count as
                                       strikes here)
  K consecutive skips  -> 'rollback'  (reload the last committed
                                       checkpoint; counter resets so
                                       the resumed run gets K fresh
                                       strikes before re-rolling back)

The sentinel is deliberately host-side and pure-Python: the cheap
`isfinite(loss) & isfinite(grad_norm)` reduction runs inside the
compiled step (see hapi.Model / ParallelTrainer), and only the single
boolean crosses to the host where policy lives.
"""
import math

__all__ = ['NanSentinel', 'finite_step', 'guard_update']


def finite_step(loss, grads):
    """In-graph health check: isfinite(loss) & isfinite(‖grads‖²) as
    ONE boolean (f32 accumulation; an inf gradient overflows the
    square into inf, a NaN propagates — both trip the flag).  Traced
    inside compiled train steps by hapi.Model and ParallelTrainer so
    only this boolean ever crosses to the host."""
    import jax
    import jax.numpy as jnp
    leaves = jax.tree_util.tree_leaves(grads)
    gnorm2 = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in leaves) if leaves else jnp.zeros(())
    return jnp.isfinite(loss) & jnp.isfinite(gnorm2)


def guard_update(ok, new, old):
    """Select `new` when ok else `old`, leaf-wise — the device-side
    skip: a non-finite step keeps the previous params/opt/buffers
    inside the same XLA module (safe with donated inputs: the select
    reads the donated buffers before the outputs alias them)."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(ok, a, b), new, old)


class NanSentinel:
    def __init__(self, patience=3, max_rollbacks=2, on_event=None):
        """`patience`: consecutive non-finite steps before a rollback
        is requested.  `max_rollbacks`: after this many rollbacks the
        sentinel raises FloatingPointError instead — a run that NaNs
        straight out of every restored checkpoint has a real bug and
        must fail loudly, not loop forever.  `on_event(kind, info)`
        observes 'skip'/'rollback'/'fatal' transitions."""
        if patience < 1:
            raise ValueError('patience must be >= 1')
        self.patience = patience
        self.max_rollbacks = max_rollbacks
        self.on_event = on_event
        self.strikes = 0
        self.rollbacks = 0
        self.total_skipped = 0

    @staticmethod
    def _finite(v):
        if v is None:
            return True
        try:
            return math.isfinite(float(v))
        except (TypeError, ValueError):
            return False

    @staticmethod
    def _telemetry(kind, **data):
        """nan_skip / nan_rollback / nan_fatal land in the run's
        telemetry stream + flight recorder (never raises)."""
        try:
            from .. import telemetry
            telemetry.event(kind, **data)
            telemetry.add(f'{kind}.count')
        except Exception:       # pragma: no cover - defensive
            pass

    def observe(self, loss=None, grad_norm=None, finite=None):
        """Record one step's health; -> 'ok' | 'skip' | 'rollback'.

        Callers that already computed the in-graph finiteness flag pass
        `finite=`; others pass host scalars for loss/grad_norm.
        """
        if finite is None:
            finite = self._finite(loss) and self._finite(grad_norm)
        if finite:
            self.strikes = 0
            return 'ok'
        self.strikes += 1
        self.total_skipped += 1
        if self.strikes < self.patience:
            if self.on_event:
                self.on_event('skip', {'strikes': self.strikes,
                                       'loss': loss})
            self._telemetry('nan_skip', strikes=self.strikes,
                            total_skipped=self.total_skipped)
            return 'skip'
        # patience exhausted: demand a rollback
        self.strikes = 0
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            if self.on_event:
                self.on_event('fatal', {'rollbacks': self.rollbacks})
            self._telemetry('nan_fatal', rollbacks=self.rollbacks)
            raise FloatingPointError(
                f'training diverged: {self.patience} consecutive '
                f'non-finite steps after {self.rollbacks - 1} '
                'rollback(s) — refusing to loop; check LR/data/loss '
                'scaling')
        if self.on_event:
            self.on_event('rollback', {'rollbacks': self.rollbacks})
        self._telemetry('nan_rollback', rollbacks=self.rollbacks,
                        patience=self.patience)
        return 'rollback'

    def state_dict(self):
        return {'strikes': self.strikes, 'rollbacks': self.rollbacks,
                'total_skipped': self.total_skipped}

    def load_state_dict(self, state):
        self.strikes = int(state.get('strikes', 0))
        self.rollbacks = int(state.get('rollbacks', 0))
        self.total_skipped = int(state.get('total_skipped', 0))
