"""Graceful preemption: SIGTERM → checkpoint at the step boundary.

TPU hosts get preempted with a SIGTERM and a short grace window.  The
wrong responses are both fatal: dying instantly loses up-to-an-epoch
of work, and ignoring the signal gets the SIGKILL anyway.  The right
response — and what `GracefulShutdown` implements — is to latch the
request, let the in-flight step finish, write one final synchronous
checkpoint, and exit with `PREEMPTED_EXIT_CODE` so the elastic
supervisor knows this was a CLEAN preemption: it restarts the worker
WITHOUT consuming the max_restarts failure budget (a fleet that
preempts a job 10 times must not exhaust a 3-restart budget meant for
real crashes).

Signal handlers only latch a flag (async-signal-safe); all real work
happens on the main loop at `requested()` checkpoints —
incubate.checkpoint.auto_checkpoint's train ranges and hapi.Model.fit
poll it every step.
"""
import os
import signal
import sys
import threading

__all__ = ['PREEMPTED_EXIT_CODE', 'GracefulShutdown',
           'install_shutdown', 'shutdown_requested', 'exit_if_requested']

# Distinct from every exit code the stack produces organically: shells
# use 126/127, Python tracebacks exit 1, argparse exits 2, signal
# deaths surface as negative returncodes / 128+N.  Exported to workers
# as PADDLE_TPU_PREEMPTED_EXIT_CODE for non-Python launch targets.
PREEMPTED_EXIT_CODE = int(os.environ.get(
    'PADDLE_TPU_PREEMPTED_EXIT_CODE', '117'))


class GracefulShutdown:
    """Latch SIGTERM/SIGINT into a poll-able "please checkpoint and
    exit" request.

        gs = GracefulShutdown().install()
        for step in ...:
            train_step()
            if gs.requested():
                save_final_checkpoint()
                gs.exit()          # sys.exit(PREEMPTED_EXIT_CODE)

    `install()` chains to the previous handler on the SECOND signal:
    the first SIGINT requests a graceful stop, an impatient second one
    falls through to the default KeyboardInterrupt.  Installation is
    a no-op off the main thread (CPython restriction) — `requested()`
    then only reflects `request()` calls, which tests and embedding
    runtimes use directly.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 exit_code=PREEMPTED_EXIT_CODE, on_request=None):
        self.signals = tuple(signals)
        self.exit_code = exit_code
        self.on_request = on_request
        self._event = threading.Event()
        self._prev = {}
        self._installed = False
        self.signum = None

    def install(self):
        if self._installed:
            return self
        # pre-create the telemetry recorder on the MAIN thread so the
        # signal handler's flight-ring note never has to construct it
        # (get_recorder() takes a non-reentrant creation lock the
        # handler must not touch)
        try:
            from ..telemetry import active, get_recorder
            if active():
                get_recorder()
        except Exception:       # pragma: no cover - defensive
            pass
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handler)
            self._installed = True
        except ValueError:
            # not the main thread: polling still works via request()
            self._prev.clear()
        return self

    def uninstall(self):
        for s, h in self._prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, TypeError):
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def _handler(self, signum, frame):
        if self._event.is_set():
            if signum == getattr(signal, 'SIGINT', None):
                # second Ctrl-C: the USER is done waiting — restore
                # and re-raise into the previous (usually default)
                # handler
                prev = self._prev.get(signum)
                signal.signal(signum, prev if callable(prev)
                              else signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            # a repeated SIGTERM stays latched: fleets deliver the
            # preemption signal to the whole process group AND the
            # supervisor forwards it, so doubles are normal — dying
            # on the second one would lose the final checkpoint the
            # grace window exists for
            return
        self.signum = signum
        self._event.set()
        self._note_preemption(signum)
        if self.on_request is not None:
            self.on_request(signum)

    @staticmethod
    def _note_preemption(signum):
        """Land a ``preemption`` event in the telemetry flight ring.
        Runs in signal-handler context: event_unlocked is one atomic
        deque append — no locks of any kind (a signal landing while
        another thread holds the recorder lock — or the singleton
        creation lock inside get_recorder() — must not deadlock the
        latch), no file I/O (the JSONL copy is written by the poll
        site, e.g. Model.fit's step boundary).  Reads the module
        global directly: if no recorder exists yet the note is
        skipped (install() pre-creates it on the main thread, so in
        practice it exists)."""
        try:
            from ..telemetry import recorder as _rmod
            rec = _rmod._recorder
            if rec is not None and not _rmod.hard_off():
                rec.event_unlocked('preemption', signum=signum)
        except Exception:       # pragma: no cover - defensive
            pass

    def request(self, signum=None):
        """Programmatic preemption request (tests; cluster agents that
        learn of preemption via metadata server rather than signal)."""
        self.signum = signum
        self._event.set()
        self._note_preemption(signum)

    def requested(self):
        return self._event.is_set()

    def clear(self):
        """Un-latch a handled request (a loop that chose to stop
        WITHOUT exiting — e.g. an interactive fit stopped by Ctrl-C —
        clears so the next loop starts fresh)."""
        self.signum = None
        self._event.clear()

    def exit(self, final=None):
        """Run `final` (the last checkpoint) and exit preempted."""
        if final is not None:
            final()
        sys.exit(self.exit_code)


# -- process-wide singleton ----------------------------------------------
# auto_checkpoint / hapi.fit poll the same instance the launcher (or
# user code) installed, so one SIGTERM stops every loop in the process.
_default = None


def install_shutdown(**kwargs):
    """Install (once) and return the process-wide GracefulShutdown."""
    global _default
    if _default is None:
        _default = GracefulShutdown(**kwargs)
    return _default.install()


def shutdown_requested():
    """True iff a graceful shutdown was requested on the process-wide
    handler (False when none was ever installed)."""
    return _default is not None and _default.requested()


def preemption_signal():
    """The latched signum of the process-wide request, or None (no
    handler / no request / programmatic request()).  Lets loops tell
    fleet preemption (SIGTERM → checkpoint and EXIT preempted) from a
    user interrupt (SIGINT → stop and hand control back)."""
    if _default is not None and _default.requested():
        return _default.signum
    return None


def exit_if_requested(final=None):
    """Checkpoint-and-exit when preempted; no-op otherwise."""
    if shutdown_requested():
        _default.exit(final)


def clear_shutdown():
    """Un-latch the process-wide request (see GracefulShutdown.clear)."""
    if _default is not None:
        _default.clear()


def handler_installed():
    """True iff the process-wide handler currently owns the signals
    (lets scoped installers — e.g. Model.fit — restore the previous
    handlers on exit instead of holding them for process lifetime)."""
    return _default is not None and _default._installed


def uninstall_shutdown():
    """Restore the signal handlers the process-wide install replaced."""
    if _default is not None:
        _default.uninstall()
