"""paddle_tpu.resilience — fault-tolerant training runtime.

TPU fleets preempt hosts routinely (maintenance, defrag, spot
reclaim), and at pod scale *something* is always failing: a host dies
mid-async-checkpoint and leaves a torn orbax directory, a transient
NFS hiccup breaks a weight-cache read, a bad batch NaNs the loss.
This package is the one place those failure modes are handled, and the
rest of the stack composes with it:

  manifest   verified checkpoints — a commit manifest (step, leaf
             spec, per-file sizes + checksums) written atomically
             AFTER the (async) save finishes; a directory without a
             valid manifest never existed as far as restore is
             concerned.  Used by distributed.checkpoint.
  shutdown   GracefulShutdown — SIGTERM/SIGINT turn into a "finish
             the step, checkpoint, exit PREEMPTED_EXIT_CODE" request;
             distributed.elastic recognizes that exit code as a clean
             preemption and restarts WITHOUT consuming the
             max_restarts budget.
  sentinel   NanSentinel — loss/grad-norm divergence policy: skip
             non-finite updates, roll back to the last committed
             checkpoint after K consecutive strikes.  Wired into
             hapi.Model.fit (NanGuard callback) and
             parallel.ParallelTrainer(nan_guard=True).
  retry      the shared retry(fn, retries, backoff, jitter, retry_on,
             deadline) decorator for transient host-side failures
             (shared-fs reads, checkpoint commits) — replaces ad-hoc
             loops; deadline caps barrier waits.
  chaos      deterministic, seeded fault injection (FaultPlan /
             ChaosEngine) + the resilience invariant checker — the
             proof harness for everything above.  ChaosCluster spawns
             a TRUE multi-process topology (N workers + supervisor)
             with collective-layer fault seams.  Driven by
             tools/chaos_run.py, tools/soak_run.py and the `chaos`
             pytest fixture.
  watchdog   straggler/hang supervision: per-step and per-collective
             deadline budgets (cost-model-derived), heartbeat quorum
             across ranks, and the timeout -> flight-dump ->
             coordinated-abort -> elastic-restart escalation so a
             hung rank costs one restart, never a deadlocked cluster.
  plangen    property-based chaos plan generation: seeded composition
             of legal fault sequences for long soaks, plus shrinking
             a failing plan to a minimal committed reproducer
             (tools/soak_run.py).
  supervisor the self-healing ACTUATOR closing the observe->act loop:
             PlanSupervisor subscribes to the telemetry event stream
             (slo_breach / drift_detected / straggler_suspect / ...),
             classifies triggers into remediation policies, re-plans
             over the healthy device set with live calibration,
             AOT-precompiles the candidate, and queues a safe plan
             swap at a step boundary (in-process) or a coordinated
             reshape restart (multi-process clusters).  Default OFF
             (PADDLE_TPU_SUPERVISOR / ParallelTrainer(supervisor=)).

Reference analogue: the reference framework spreads this over fleet
elastic (etcd heartbeats), checkpoint_saver (versioned dirs) and the
GradScaler's found_inf plumbing; here it is one subsystem.
"""
from .manifest import (  # noqa: F401
    MANIFEST_NAME, TWO_PHASE_DIR, write_manifest, read_manifest,
    verify_manifest, is_committed, file_checksum, atomic_write,
    write_intent, read_intents, intent_age, finalize_two_phase,
    CommitBarrierTimeout)
from .retry import retry  # noqa: F401
from .shutdown import (  # noqa: F401
    PREEMPTED_EXIT_CODE, GracefulShutdown, install_shutdown,
    shutdown_requested, exit_if_requested, preemption_signal,
    clear_shutdown, handler_installed, uninstall_shutdown)
from .sentinel import NanSentinel, finite_step, guard_update  # noqa: F401
from .chaos import (  # noqa: F401
    Fault, FaultPlan, ChaosEngine, ChaosCluster, check_invariants,
    load_run_events, ServingFaultInjector)
from .watchdog import (  # noqa: F401
    Watchdog, Budget, WATCHDOG_EXIT_CODE, collective_budget,
    remaining_budget, resolve_watchdog)
from .supervisor import (  # noqa: F401
    PlanSupervisor, SupervisorConfig, TrainerHost, resolve_supervisor,
    TRIGGER_POLICIES, write_reshape_request, read_reshape_request)

__all__ = [
    'MANIFEST_NAME', 'TWO_PHASE_DIR', 'write_manifest', 'read_manifest',
    'verify_manifest', 'is_committed', 'file_checksum', 'atomic_write',
    'write_intent', 'read_intents', 'intent_age', 'finalize_two_phase',
    'CommitBarrierTimeout',
    'retry',
    'PREEMPTED_EXIT_CODE', 'GracefulShutdown', 'install_shutdown',
    'shutdown_requested', 'exit_if_requested', 'preemption_signal',
    'clear_shutdown', 'handler_installed', 'uninstall_shutdown',
    'NanSentinel', 'finite_step', 'guard_update',
    'Fault', 'FaultPlan', 'ChaosEngine', 'ChaosCluster',
    'check_invariants', 'load_run_events',
    'Watchdog', 'Budget', 'WATCHDOG_EXIT_CODE', 'collective_budget',
    'remaining_budget', 'resolve_watchdog',
    'PlanSupervisor', 'SupervisorConfig', 'TrainerHost',
    'resolve_supervisor', 'TRIGGER_POLICIES', 'write_reshape_request',
    'read_reshape_request',
]
