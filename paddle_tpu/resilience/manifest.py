"""Commit manifests: the "this checkpoint finished" marker.

An async orbax save that dies mid-flight leaves a directory that LOOKS
like a checkpoint (metadata files land early) but whose shard payloads
are truncated or missing — and `CheckpointManager.latest_step()` used
to happily select it.  The fix is the classic commit-record protocol:

  1. write all checkpoint data (orbax, any layout),
  2. fsync + atomically `os.replace` a manifest JSON into the dir
     recording every file's size and checksum plus the step id.

A directory is *committed* iff its manifest is present and parses;
it is *verified* iff every recorded file exists with the recorded
size/checksum.  Readers treat anything else as a torn save: it never
happened.  The manifest is written by the SAME process that ran the
save, strictly after the save barrier (`wait_until_finished`), so a
SIGKILL anywhere in between simply yields an uncommitted dir.
"""
import hashlib
import json
import os
import tempfile

__all__ = ['MANIFEST_NAME', 'atomic_write', 'file_checksum',
           'write_manifest', 'read_manifest', 'verify_manifest',
           'is_committed', 'leaf_spec', 'spec_mismatches']

MANIFEST_NAME = '_PADDLE_COMMIT.json'
_FORMAT = 1


def atomic_write(path, write_fn, mode='w', prefix='.tmp'):
    """Crash-safe file write: tmp file in the target's directory,
    `write_fn(f)`, flush+fsync, `os.replace`.  A crash at ANY point
    leaves either the previous file or none — never a torn one.  The
    shared protocol behind commit manifests and auto-checkpoint
    snapshots."""
    path = os.path.abspath(path)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), prefix=prefix)
    try:
        with os.fdopen(fd, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def file_checksum(path, algo='sha256', chunk=1 << 20):
    """Streaming checksum — checkpoint shards can be GBs; never slurp."""
    h = hashlib.new(algo)
    with open(path, 'rb') as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _walk_files(directory):
    for root, dirs, files in os.walk(directory):
        # deterministic order → deterministic manifests (diffable)
        dirs.sort()
        for f in sorted(files):
            if f == MANIFEST_NAME:
                continue
            p = os.path.join(root, f)
            yield os.path.relpath(p, directory), p


def leaf_spec(tree):
    """Flat {leaf-path: {shape, dtype}} of a pytree — recorded in the
    manifest so restore can cross-check the template before touching
    tensorstore (a wrong-model restore fails fast with a readable
    message instead of an orbax shape error)."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    spec = {}
    for path, v in flat:
        key = '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                       for k in path)
        shape = tuple(getattr(v, 'shape', ()) or ())
        dtype = str(getattr(v, 'dtype', type(v).__name__))
        spec[key] = {'shape': list(shape), 'dtype': dtype}
    return spec


def spec_mismatches(recorded, template):
    """Compare two leaf_spec dicts -> list of human-readable diffs
    (empty = compatible).  CheckpointManager.restore runs this before
    handing the template to orbax, so restoring into the wrong model
    fails with named leaves instead of a raw tensorstore shape error."""
    out = []
    for key in sorted(set(recorded) | set(template)):
        a, b = recorded.get(key), template.get(key)
        if a is None:
            out.append(f'{key}: not in checkpoint')
        elif b is None:
            out.append(f'{key}: not in restore template')
        elif list(a['shape']) != list(b['shape']) or \
                a['dtype'] != b['dtype']:
            out.append(
                f'{key}: checkpoint {a["shape"]}/{a["dtype"]} vs '
                f'template {b["shape"]}/{b["dtype"]}')
    return out


def write_manifest(directory, step=None, tree=None, algo='sha256',
                   checksums=True):
    """Scan `directory` and atomically commit its manifest.

    Must be called only after the save fully finished (sync save
    returned / async save's wait_until_finished passed).  tmp +
    fsync + os.replace: a crash during THIS write leaves either the
    previous manifest or none — never a torn one.

    `checksums=False` records presence + sizes only: that still
    catches every torn-write mode a crash produces (missing files,
    truncation) without re-reading the shards — the right trade at
    multi-GB checkpoint scale, where hashing inside the post-save
    barrier would eat the async overlap.  Full checksums additionally
    catch bit-level corruption.
    """
    directory = os.path.abspath(directory)
    files = {}
    for rel, p in _walk_files(directory):
        meta = {'size': os.path.getsize(p)}
        if checksums:
            meta[algo] = file_checksum(p, algo)
        files[rel] = meta
    doc = {'format': _FORMAT, 'step': step, 'algo': algo, 'files': files}
    if tree is not None:
        doc['leaf_spec'] = leaf_spec(tree)
    atomic_write(os.path.join(directory, MANIFEST_NAME),
                 lambda f: json.dump(doc, f, indent=1, sort_keys=True),
                 prefix='.commit_tmp')
    return doc


def read_manifest(directory):
    """The parsed manifest, or None when absent/unreadable (an
    unreadable manifest is indistinguishable from a torn commit and is
    treated the same way)."""
    try:
        with open(os.path.join(directory, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_committed(directory):
    return read_manifest(directory) is not None


def verify_manifest(directory, checksums=True):
    """-> (ok, errors).  Checks every manifest-recorded file for
    presence, size, and (optionally) checksum.  Extra files are
    ignored — orbax versions differ in auxiliary artifacts, and extras
    cannot corrupt a restore that only reads recorded data."""
    directory = os.path.abspath(directory)
    doc = read_manifest(directory)
    if doc is None:
        return False, ['missing or unreadable manifest '
                       f'({MANIFEST_NAME})']
    algo = doc.get('algo', 'sha256')
    errors = []
    for rel, meta in sorted(doc.get('files', {}).items()):
        p = os.path.join(directory, rel)
        if not os.path.isfile(p):
            errors.append(f'{rel}: missing')
            continue
        size = os.path.getsize(p)
        if size != meta.get('size'):
            errors.append(
                f'{rel}: size {size} != recorded {meta.get("size")}')
            continue
        if checksums and algo in meta:
            got = file_checksum(p, algo)
            if got != meta[algo]:
                errors.append(f'{rel}: {algo} mismatch')
    return not errors, errors
