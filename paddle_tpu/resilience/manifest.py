"""Commit manifests: the "this checkpoint finished" marker.

An async orbax save that dies mid-flight leaves a directory that LOOKS
like a checkpoint (metadata files land early) but whose shard payloads
are truncated or missing — and `CheckpointManager.latest_step()` used
to happily select it.  The fix is the classic commit-record protocol:

  1. write all checkpoint data (orbax, any layout),
  2. fsync + atomically `os.replace` a manifest JSON into the dir
     recording every file's size and checksum plus the step id.

A directory is *committed* iff its manifest is present and parses;
it is *verified* iff every recorded file exists with the recorded
size/checksum.  Readers treat anything else as a torn save: it never
happened.  The manifest is written by the SAME process that ran the
save, strictly after the save barrier (`wait_until_finished`), so a
SIGKILL anywhere in between simply yields an uncommitted dir.

Multi-host saves use the TWO-PHASE variant of the same protocol.  On a
pod every host writes its own shard files to the shared filesystem;
process 0 finishing ITS save proves nothing about host 7's.  So:

  phase 1 (every host, after its local save barrier): atomically write
      an intent/ack file ``_PADDLE_2PC/intent.r<host>`` recording the
      shard files that host wrote (sizes + digests);
  phase 2 (process 0 only): wait — bounded by a deadline, with
      retry backoff — until ALL hosts' acks are present, merge them,
      and atomically write the final manifest (with ``hosts`` and a
      per-file ``host`` tag).

A SIGKILL *between* the phases leaves acks but no manifest: readers
see an uncommitted dir exactly as before, and once the acks are stale
(nobody can still be finalizing) the half-committed dir is safe to
quarantine.  The final `os.replace` of the manifest remains the single
linearization point — there is no state in which a reader can observe
a committed-but-incomplete checkpoint.
"""
import hashlib
import json
import os
import tempfile

__all__ = ['MANIFEST_NAME', 'TWO_PHASE_DIR', 'atomic_write',
           'file_checksum', 'write_manifest', 'read_manifest',
           'verify_manifest', 'is_committed', 'leaf_spec',
           'spec_mismatches', 'write_intent', 'read_intents',
           'intent_age', 'finalize_two_phase', 'CommitBarrierTimeout']

MANIFEST_NAME = '_PADDLE_COMMIT.json'
TWO_PHASE_DIR = '_PADDLE_2PC'
_FORMAT = 2


class CommitBarrierTimeout(TimeoutError):
    """The two-phase finalize deadline expired with acks still
    missing.  Carries the missing host ids so the caller (or operator)
    knows WHICH host never finished its save."""

    def __init__(self, directory, missing, timeout):
        self.directory = directory
        self.missing = sorted(missing)
        self.timeout = timeout
        super().__init__(
            f'commit barrier for {directory} timed out after '
            f'{timeout:.1f}s waiting for host ack(s) {self.missing}')


def atomic_write(path, write_fn, mode='w', prefix='.tmp'):
    """Crash-safe file write: tmp file in the target's directory,
    `write_fn(f)`, flush+fsync, `os.replace`.  A crash at ANY point
    leaves either the previous file or none — never a torn one.  The
    shared protocol behind commit manifests and auto-checkpoint
    snapshots."""
    path = os.path.abspath(path)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), prefix=prefix)
    try:
        with os.fdopen(fd, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def file_checksum(path, algo='sha256', chunk=1 << 20):
    """Streaming checksum — checkpoint shards can be GBs; never slurp."""
    h = hashlib.new(algo)
    with open(path, 'rb') as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _walk_files(directory):
    for root, dirs, files in os.walk(directory):
        # deterministic order → deterministic manifests (diffable);
        # the 2PC intent dir is protocol state, not checkpoint payload
        dirs.sort()
        if TWO_PHASE_DIR in dirs:
            dirs.remove(TWO_PHASE_DIR)
        for f in sorted(files):
            if f == MANIFEST_NAME:
                continue
            p = os.path.join(root, f)
            yield os.path.relpath(p, directory), p


def leaf_spec(tree):
    """Flat {leaf-path: {shape, dtype}} of a pytree — recorded in the
    manifest so restore can cross-check the template before touching
    tensorstore (a wrong-model restore fails fast with a readable
    message instead of an orbax shape error)."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    spec = {}
    for path, v in flat:
        key = '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                       for k in path)
        shape = tuple(getattr(v, 'shape', ()) or ())
        dtype = str(getattr(v, 'dtype', type(v).__name__))
        spec[key] = {'shape': list(shape), 'dtype': dtype}
    return spec


def spec_mismatches(recorded, template):
    """Compare two leaf_spec dicts -> list of human-readable diffs
    (empty = compatible).  CheckpointManager.restore runs this before
    handing the template to orbax, so restoring into the wrong model
    fails with named leaves instead of a raw tensorstore shape error."""
    out = []
    for key in sorted(set(recorded) | set(template)):
        a, b = recorded.get(key), template.get(key)
        if a is None:
            out.append(f'{key}: not in checkpoint')
        elif b is None:
            out.append(f'{key}: not in restore template')
        elif list(a['shape']) != list(b['shape']) or \
                a['dtype'] != b['dtype']:
            out.append(
                f'{key}: checkpoint {a["shape"]}/{a["dtype"]} vs '
                f'template {b["shape"]}/{b["dtype"]}')
    return out


def write_manifest(directory, step=None, tree=None, algo='sha256',
                   checksums=True, meta=None):
    """Scan `directory` and atomically commit its manifest.

    Must be called only after the save fully finished (sync save
    returned / async save's wait_until_finished passed).  tmp +
    fsync + os.replace: a crash during THIS write leaves either the
    previous manifest or none — never a torn one.

    `checksums=False` records presence + sizes only: that still
    catches every torn-write mode a crash produces (missing files,
    truncation) without re-reading the shards — the right trade at
    multi-GB checkpoint scale, where hashing inside the post-save
    barrier would eat the async overlap.  Full checksums additionally
    catch bit-level corruption.

    `meta` (a dict) is merged into the manifest document — the sharded
    save path records the saving mesh's axis sizes and process count
    so a reshape restore can tell (and log) that the topology changed.
    """
    directory = os.path.abspath(directory)
    files = {}
    for rel, p in _walk_files(directory):
        rec = {'size': os.path.getsize(p)}
        if checksums:
            rec[algo] = file_checksum(p, algo)
        files[rel] = rec
    doc = {'format': _FORMAT, 'step': step, 'algo': algo, 'files': files}
    if meta:
        doc.update(meta)
    if tree is not None:
        doc['leaf_spec'] = leaf_spec(tree)
    atomic_write(os.path.join(directory, MANIFEST_NAME),
                 lambda f: json.dump(doc, f, indent=1, sort_keys=True),
                 prefix='.commit_tmp')
    return doc


# -- two-phase (cross-host) commit --------------------------------------------

def _intent_name(host):
    return f'intent.r{int(host)}'


def _host_from_rel(rel):
    """Best-effort owner of an unclaimed artifact: orbax/tensorstore
    per-process paths carry ``process_<idx>``; everything else
    (metadata written by the finalize rank) is host 0's."""
    import re
    m = re.search(r'process_(\d+)', rel)
    return int(m.group(1)) if m else 0


def write_intent(directory, host, step=None, files=None, algo='sha256',
                 checksums=True):
    """Phase 1: host `host` acknowledges that ITS shard files are fully
    on disk.  Called strictly after that host's local save barrier, so
    the ack is a durable promise — a SIGKILL before this call simply
    leaves the ack missing and the finalize barrier times out.

    `files` restricts the ack to the relative paths this host wrote
    (orbax per-process artifacts); None records every payload file
    currently visible (single-host, or a process-0 catch-all)."""
    directory = os.path.abspath(directory)
    d2 = os.path.join(directory, TWO_PHASE_DIR)
    os.makedirs(d2, exist_ok=True)
    rec = {}
    if files is None:
        pairs = list(_walk_files(directory))
    else:
        pairs = [(rel, os.path.join(directory, rel)) for rel in files]
    for rel, p in pairs:
        entry = {'size': os.path.getsize(p)}
        if checksums:
            entry[algo] = file_checksum(p, algo)
        rec[rel] = entry
    doc = {'format': _FORMAT, 'host': int(host), 'step': step,
           'algo': algo, 'files': rec}
    atomic_write(os.path.join(d2, _intent_name(host)),
                 lambda f: json.dump(doc, f, indent=1, sort_keys=True),
                 prefix='.intent_tmp')
    try:
        from .. import telemetry
        telemetry.event('commit_intent', step=step, host=int(host),
                        files=len(rec), path=directory)
    except Exception:       # pragma: no cover - defensive
        pass
    return doc


def read_intents(directory):
    """{host: intent doc} for every readable ack under the 2PC dir.
    A torn intent (atomic_write makes that external damage only) is
    skipped — it reads as a missing ack, which the barrier treats as
    'that host has not finished'."""
    d2 = os.path.join(os.path.abspath(directory), TWO_PHASE_DIR)
    out = {}
    try:
        names = os.listdir(d2)
    except OSError:
        return out
    for f in names:
        if not f.startswith('intent.r'):
            continue
        try:
            with open(os.path.join(d2, f)) as fh:
                doc = json.load(fh)
            out[int(doc['host'])] = doc
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def intent_age(directory):
    """Seconds since the NEWEST intent file changed, or None when no
    intent exists.  Readers use this to tell a half-committed save
    (stale acks, finalizer died between the phases — quarantineable)
    from one whose finalize may still be in flight."""
    import time
    d2 = os.path.join(os.path.abspath(directory), TWO_PHASE_DIR)
    newest = None
    try:
        for f in os.listdir(d2):
            if not f.startswith('intent.r'):
                continue
            try:
                m = os.path.getmtime(os.path.join(d2, f))
            except OSError:
                continue
            newest = m if newest is None else max(newest, m)
    except OSError:
        return None
    return None if newest is None else max(0.0, time.time() - newest)


def finalize_two_phase(directory, num_hosts, step=None, tree=None,
                       algo='sha256', checksums=True, meta=None,
                       timeout=120.0, poll=0.05):
    """Phase 2 (process 0 only): wait for every host's ack, merge the
    per-host file records, and atomically commit the final manifest.

    The wait is a retry() loop with exponential backoff capped by a
    hard `timeout` deadline — a dead host must surface as
    CommitBarrierTimeout (and an uncommitted, later-quarantined dir),
    never as an infinite barrier.  Each file entry in the merged
    manifest carries its owning ``host``, which check_ckpt --deep uses
    to report missing-host vs torn vs digest-mismatch distinctly."""
    from .retry import retry
    directory = os.path.abspath(directory)
    try:
        from .. import telemetry
        _span = telemetry.span('commit_barrier', step=step,
                               hosts=num_hosts)
    except Exception:       # pragma: no cover - defensive
        import contextlib
        _span = contextlib.nullcontext()
    with _span:
        state = {}

        def gather():
            intents = read_intents(directory)
            missing = [h for h in range(num_hosts) if h not in intents]
            if missing:
                raise OSError(
                    f'missing commit ack(s) from host(s) {missing}')
            state['intents'] = intents

        try:
            # retries is effectively unbounded; the deadline is the cap
            retry(gather, retries=1 << 30, backoff=poll, max_backoff=1.0,
                  jitter=False, deadline=timeout)()
        except OSError:
            intents = read_intents(directory)
            raise CommitBarrierTimeout(
                directory,
                [h for h in range(num_hosts) if h not in intents],
                timeout) from None
        files = {}
        for host in sorted(state['intents']):
            for rel, entry in state['intents'][host]['files'].items():
                files[rel] = dict(entry, host=host)
        # files no ack claimed (orbax layouts where a host cannot
        # attribute its own artifacts): every host's data is durable
        # once all acks landed, so the finalizer scans and records
        # them itself, attributing by the per-process path convention
        # where one exists
        for rel, p in _walk_files(directory):
            if rel in files:
                continue
            entry = {'size': os.path.getsize(p)}
            if checksums:
                entry[algo] = file_checksum(p, algo)
            files[rel] = dict(entry, host=_host_from_rel(rel))
        doc = {'format': _FORMAT, 'step': step, 'algo': algo,
               'hosts': num_hosts, 'files': files}
        if meta:
            doc.update(meta)
        if tree is not None:
            doc['leaf_spec'] = leaf_spec(tree)
        atomic_write(os.path.join(directory, MANIFEST_NAME),
                     lambda f: json.dump(doc, f, indent=1,
                                         sort_keys=True),
                     prefix='.commit_tmp')
    try:
        from .. import telemetry
        telemetry.event('commit_finalize', step=step, hosts=num_hosts,
                        files=len(files), path=directory)
    except Exception:       # pragma: no cover - defensive
        pass
    return doc


def read_manifest(directory):
    """The parsed manifest, or None when absent/unreadable (an
    unreadable manifest is indistinguishable from a torn commit and is
    treated the same way)."""
    try:
        with open(os.path.join(directory, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_committed(directory):
    return read_manifest(directory) is not None


def verify_manifest(directory, checksums=True):
    """-> (ok, errors).  Checks every manifest-recorded file for
    presence, size, and (optionally) checksum.  Extra files are
    ignored — orbax versions differ in auxiliary artifacts, and extras
    cannot corrupt a restore that only reads recorded data."""
    directory = os.path.abspath(directory)
    doc = read_manifest(directory)
    if doc is None:
        return False, ['missing or unreadable manifest '
                       f'({MANIFEST_NAME})']
    algo = doc.get('algo', 'sha256')
    errors = []
    for rel, meta in sorted(doc.get('files', {}).items()):
        p = os.path.join(directory, rel)
        if not os.path.isfile(p):
            errors.append(f'{rel}: missing')
            continue
        size = os.path.getsize(p)
        if size != meta.get('size'):
            errors.append(
                f'{rel}: size {size} != recorded {meta.get("size")}')
            continue
        if checksums and algo in meta:
            got = file_checksum(p, algo)
            if got != meta[algo]:
                errors.append(f'{rel}: {algo} mismatch')
    return not errors, errors
