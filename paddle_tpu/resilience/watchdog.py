"""Straggler/hang supervision: deadline budgets, heartbeat quorum,
and the timeout -> flight-dump -> coordinated-abort -> elastic-restart
escalation path.

A hung rank is the one failure the rest of the resilience stack cannot
see: a SIGKILL leaves a corpse the supervisor restarts, a NaN trips the
sentinel, but a rank stuck inside a collective just... waits, and every
peer waits with it — a deadlocked cluster that burns its reservation
until an operator notices.  The watchdog turns that into a bounded,
attributed, restartable event:

* **Budget** — per-step and per-collective deadline budgets.  Defaults
  derive from the PR-6 cost-model estimate x a slack factor when a plan
  or census estimate exists (``Budget.from_costmodel``); the
  ``PADDLE_TPU_WATCHDOG`` env (``1`` or ``step=30,collective=5,
  slack=8``) configures it fleet-wide.  Off unless explicitly enabled —
  ``ParallelTrainer(watchdog=True)`` or the env.
* **Watchdog** — a daemon thread that (a) tracks the main loop's step
  deadline (``step_started``/``step_finished`` are two attribute
  writes: nothing on the step path blocks or syncs), (b) publishes a
  per-rank heartbeat into the cluster KV store and checks peers' ages
  (a slow peer -> ``straggler`` event with rank attribution; a majority
  gone -> ``quorum_lost``), and (c) on a blown deadline escalates:
  ``timeout`` telemetry event -> flight-recorder dump -> cluster abort
  flag (peers waiting in host collectives raise CoordinatedAbort
  within one poll instead of burning their own timeouts) -> process
  exit with ``WATCHDOG_EXIT_CODE`` so distributed.elastic restarts the
  rank as ONE failure restart — never a deadlock.  The exit is
  ``os._exit``: the main thread is by definition stuck (possibly
  inside XLA, uninterruptible), and a watchdog that politely raises in
  its own thread un-wedges nothing.
* **collective_budget** — a thread-local deadline scope the host
  transport (and anything else doing bounded cluster waits) arms;
  ``resilience.retry(deadline=)`` clamps to the remaining budget so a
  retry loop INSIDE a collective deadline cannot outlive it.
"""
import contextlib
import json
import os
import threading
import time

__all__ = ['WATCHDOG_EXIT_CODE', 'WATCHDOG_ENV', 'Budget', 'Watchdog',
           'collective_budget', 'remaining_budget', 'resolve_watchdog',
           'default_collective_s']

# distinct from PREEMPTED_EXIT_CODE (117, free restart): a watchdog
# kill IS a failure — a hung rank must cost one restart from the
# budget, or a deterministic hang restarts forever
WATCHDOG_EXIT_CODE = int(os.environ.get(
    'PADDLE_TPU_WATCHDOG_EXIT_CODE', '121'))
WATCHDOG_ENV = 'PADDLE_TPU_WATCHDOG'


class Budget:
    """Deadline budgets for one supervised loop.

    step_s        wall-clock allowance for one host-loop step (dispatch
                  to dispatch).  None -> default_step_s.
                  Under a FUSED K-step loop (core.scan_loop) one
                  host-visible "step" is a whole K-chunk: the trainer
                  passes ``step_started(budget_s=K x step_s)`` so the
                  budget covers the chunk, and — when an explicit
                  step_s was armed together with a cost-model step
                  estimate — K itself clamps so a hung chunk is still
                  detected inside the armed deadline
                  (``scan_loop.clamp_chunk`` /
                  ``ParallelTrainer.fused_chunk_len``).
    collective_s  allowance for one host collective's wait.
    slack         multiplier applied to cost-model estimates when
                  deriving budgets (estimates are ideal-wire numbers;
                  real steps pay host work, stragglers, fs jitter).
    first_step_s  allowance for the first step (compile rides on it).
    straggler_frac  fraction of step_s after which a still-running
                  step emits a ``straggler`` event (soft warning
                  before the hard timeout).
    """

    def __init__(self, step_s=None, collective_s=None, slack=8.0,
                 first_step_s=None, straggler_frac=0.5,
                 default_step_s=60.0, grace_s=5.0):
        self.step_s = None if step_s is None else float(step_s)
        self.collective_s = (None if collective_s is None
                             else float(collective_s))
        self.slack = float(slack)
        self.first_step_s = (None if first_step_s is None
                             else float(first_step_s))
        self.straggler_frac = float(straggler_frac)
        self.default_step_s = float(default_step_s)
        self.grace_s = float(grace_s)
        # where step_s came from — the adaptation ladder: an operator's
        # EXPLICIT deadline is never overridden; the analytic
        # cost-model estimate and the global default both yield to a
        # MEASURED rolling profile once one exists (note_measured)
        self.step_source = ('explicit' if step_s is not None
                            else 'default')

    def effective_step_s(self):
        return self.step_s if self.step_s is not None \
            else self.default_step_s

    def effective_first_step_s(self):
        if self.first_step_s is not None:
            return self.first_step_s
        # compile dominates the first step; be generous but bounded
        return max(120.0, 4 * self.effective_step_s())

    # -- serving SLO derivations (telemetry.monitors / serving) -----------
    def ttft_budget_s(self):
        """The aggregate TTFT allowance the serving SLO monitor
        compares its rolling p99 against: queueing + prefill ride on
        the first-step allowance, exactly like the per-request
        deadline derivation — one budget machinery, two consumers."""
        return self.effective_first_step_s()

    def request_budget_s(self, max_new_tokens, span=1):
        """Per-request completion allowance: first-step (prefill +
        compile headroom) plus one step allowance per fused decode
        span.  ``ServingEngine.request_deadline_s`` derives per-request
        deadlines from this; ``SLOMonitor`` uses the same numbers as
        aggregate thresholds."""
        import math
        spans = math.ceil(max(1, int(max_new_tokens) - 1)
                          / max(1, int(span)))
        return self.effective_first_step_s() \
            + spans * self.effective_step_s()

    @classmethod
    def from_costmodel(cls, est_step_us, slack=8.0, min_step_s=5.0,
                       **kwargs):
        """Derive the step budget from a cost-model estimate (the
        planner's ``est_us + compute_us``, or a census total): budget =
        max(min_step_s, est * slack).  The estimate is a lower bound on
        device time; the slack covers host work and real-world jitter
        while keeping the deadline proportional to the workload instead
        of one global constant."""
        step_s = max(min_step_s, float(est_step_us) * 1e-6 * slack)
        budget = cls(step_s=step_s, slack=slack, **kwargs)
        budget.step_source = 'costmodel'
        return budget

    def note_measured(self, times_s, min_samples=16, quantile=0.95,
                      min_step_s=1.0):
        """Refresh the step budget from MEASURED per-step wall times
        (the ROADMAP item-3 carry-over: budgets from rolling per-step
        profiles, not the analytic estimate).

        ``times_s`` is a window of recent host-side step durations in
        seconds.  The new budget is the window's ``quantile`` x
        ``slack`` (the same slack posture the cost-model derivation
        uses), floored at ``min_step_s``.  Only non-explicit budgets
        adapt: an operator's armed ``step=`` deadline is a contract,
        while the cost-model/default numbers are estimates the
        measured profile strictly improves on.  Returns the new step_s,
        or None when nothing changed (explicit budget, or too few
        samples)."""
        if self.step_source == 'explicit':
            return None
        ts = sorted(float(t) for t in times_s if t is not None)
        if len(ts) < int(min_samples):
            return None
        est = ts[min(len(ts) - 1, int(len(ts) * float(quantile)))]
        new = max(float(min_step_s), est * self.slack)
        self.step_s = new
        self.step_source = 'measured'
        return new

    def reset_measured(self, est_step_us=None, min_step_s=5.0):
        """Forget a MEASURED step budget after a plan swap: the new
        plan's steps share nothing with the degraded plan's p95, so
        the rolling profile must re-learn from scratch.  The budget
        drops back one rung on the adaptation ladder — to the new
        plan's cost-model estimate when one is given, else the global
        default.  Explicit budgets are a contract and never reset.
        Returns the new step_s (None = default)."""
        if self.step_source == 'explicit':
            return None
        if est_step_us:
            self.step_s = max(float(min_step_s),
                              float(est_step_us) * 1e-6 * self.slack)
            self.step_source = 'costmodel'
        else:
            self.step_s = None
            self.step_source = 'default'
        return self.step_s

    @classmethod
    def from_env(cls, text):
        """Parse the PADDLE_TPU_WATCHDOG value: '1'/'on' -> defaults;
        'step=30,collective=5,slack=8' -> explicit numbers."""
        text = (text or '').strip()
        if text.lower() in ('', '0', 'off', 'false'):
            return None
        if text.lower() in ('1', 'on', 'true'):
            return cls()
        kwargs = {}
        keymap = {'step': 'step_s', 'collective': 'collective_s',
                  'slack': 'slack', 'first': 'first_step_s',
                  'grace': 'grace_s'}
        for part in text.split(','):
            if '=' not in part:
                continue
            k, v = part.split('=', 1)
            k = keymap.get(k.strip(), None)
            if k is None:
                continue
            try:
                kwargs[k] = float(v)
            except ValueError:
                pass
        return cls(**kwargs)

    def to_dict(self):
        return {'step_s': self.step_s, 'collective_s': self.collective_s,
                'slack': self.slack, 'first_step_s': self.first_step_s}


def resolve_watchdog(arg):
    """The shared opt-in posture: explicit False -> None (off even if
    the env says on); True -> Budget(); Budget/dict pass through; None
    -> the PADDLE_TPU_WATCHDOG env decides.  Returns a Budget or
    None."""
    if arg is False:
        return None
    if arg is None:
        return Budget.from_env(os.environ.get(WATCHDOG_ENV))
    if arg is True:
        return Budget()
    if isinstance(arg, Budget):
        return arg
    if isinstance(arg, dict):
        return Budget(**arg)
    raise TypeError(f'watchdog= expects bool/dict/Budget, got {arg!r}')


# -- collective-deadline scope (retry() clamps to it) -------------------------

_budget_local = threading.local()


@contextlib.contextmanager
def collective_budget(seconds):
    """Arm a thread-local deadline for the enclosed cluster wait.  The
    host transport wraps its exchanges in this; retry(deadline=) and
    nested transport calls clamp to the REMAINING budget, so no layer
    of retrying can outlive the collective's allowance."""
    prev = getattr(_budget_local, 'deadline', None)
    mine = time.monotonic() + float(seconds)
    _budget_local.deadline = mine if prev is None else min(prev, mine)
    try:
        yield
    finally:
        _budget_local.deadline = prev


def remaining_budget():
    """Seconds left in the innermost armed collective budget, or None
    when no budget is armed.  Never negative."""
    deadline = getattr(_budget_local, 'deadline', None)
    if deadline is None:
        return None
    return max(0.0, deadline - time.monotonic())


# the per-collective allowance of the currently-started Watchdog
# (Budget.collective_s), process-global: the host transport clamps
# every exchange's wait to it, which is what makes
# PADDLE_TPU_WATCHDOG=collective=5 actually bound collectives instead
# of being parsed-and-ignored configuration
_default_collective_s = None


def default_collective_s():
    """The started Watchdog's per-collective budget in seconds, or
    None when no watchdog (or none with collective_s) is running."""
    return _default_collective_s


class Watchdog:
    """Supervise one step loop (and, with a KV client, the cluster's
    heartbeat quorum).  Use as a context manager or start()/stop().

    The step path stays sync-free: ``step_started``/``step_finished``
    are plain attribute writes.  All detection runs on the daemon
    thread at ``poll`` cadence.

    Escalation on a blown step deadline (or lost quorum):
      1. ``timeout`` (or ``quorum_lost``) telemetry event, with rank
         and elapsed/budget attribution;
      2. flight-recorder dump to ``flight_dir`` (post-mortemable);
      3. cluster abort flag via the transport (peers stop waiting);
      4. ``on_escalate(info)`` — the default exits the process with
         WATCHDOG_EXIT_CODE after ``budget.grace_s`` (a cooperative
         exit may beat it when the main thread was stuck in a host
         collective and already raised CoordinatedAbort).  Tests pass
         their own callback.
    """

    def __init__(self, budget=None, name='train', rank=None, world=None,
                 transport=None, kv=None, namespace='ptpu',
                 heartbeat_interval=0.5, peer_stale_s=None,
                 on_escalate=None, flight_dir=None, poll=0.05):
        from ..distributed.collective import HostCollectives
        self.budget = budget or Budget()
        self.name = name
        self.transport = transport
        if self.transport is None and kv is not None:
            self.transport = HostCollectives(client=kv, rank=rank,
                                             world=world,
                                             namespace=namespace)
        self.rank = (self.transport.rank if self.transport is not None
                     else (0 if rank is None else int(rank)))
        self.world = (self.transport.world
                      if self.transport is not None
                      else (1 if world is None else int(world)))
        self.heartbeat_interval = float(heartbeat_interval)
        # a peer is a straggler when its heartbeat is older than the
        # step budget; the quorum is lost when a majority of ranks is
        self.peer_stale_s = (float(peer_stale_s)
                             if peer_stale_s is not None
                             else self.budget.effective_step_s())
        self.on_escalate = on_escalate
        self.flight_dir = flight_dir
        self.poll = float(poll)
        self._thread = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._step_no = None
        self._step_deadline = None
        self._step_started_at = None
        self._straggler_noted = False
        self._escalated = False
        self._peer_flagged = set()
        self.events = []        # local record for tests/reports

    # -- step-loop notifications (sync-free) ---------------------------------

    def step_started(self, step_no, budget_s=None, first=False):
        if budget_s is None:
            budget_s = (self.budget.effective_first_step_s() if first
                        else self.budget.effective_step_s())
        now = time.monotonic()
        with self._lock:
            self._step_no = step_no
            self._step_started_at = now
            self._step_deadline = now + budget_s
            self._straggler_noted = False

    def step_finished(self, step_no=None):
        with self._lock:
            self._step_deadline = None
            self._step_started_at = None

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        global _default_collective_s
        if self.budget.collective_s is not None:
            self._prev_collective_s = _default_collective_s
            _default_collective_s = self.budget.collective_s
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f'watchdog-{self.name}',
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        global _default_collective_s
        if hasattr(self, '_prev_collective_s'):
            _default_collective_s = self._prev_collective_s
            del self._prev_collective_s
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- detection loop ------------------------------------------------------

    def _loop(self):
        last_hb = 0.0
        while not self._stop.wait(self.poll):
            now = time.monotonic()
            if (self.transport is not None
                    and now - last_hb >= self.heartbeat_interval):
                self._publish_heartbeat()
                last_hb = now
                self._check_quorum()
            self._check_step(now)

    def _check_step(self, now):
        with self._lock:
            deadline = self._step_deadline
            started = self._step_started_at
            step_no = self._step_no
            straggler_noted = self._straggler_noted
        if deadline is None or self._escalated:
            return
        elapsed = now - started
        budget = deadline - started
        if not straggler_noted and \
                elapsed > budget * self.budget.straggler_frac:
            with self._lock:
                self._straggler_noted = True
            self._emit('straggler', step=step_no, rank=self.rank,
                       elapsed_s=round(elapsed, 3),
                       budget_s=round(budget, 3))
            self._probe_ledger('straggler')
        if now > deadline:
            self._escalate('timeout', step=step_no,
                           elapsed_s=round(elapsed, 3),
                           budget_s=round(budget, 3))

    def _publish_heartbeat(self):
        tr = self.transport
        try:
            # rank/step/budget ride along so the cluster aggregator's
            # heartbeat join can show WHAT deadline a silent rank was
            # under, not just that it went silent
            doc = json.dumps({'ts': time.time(), 'step': self._step_no,
                              'rank': self.rank,
                              'budget_s': round(
                                  self.budget.effective_step_s(), 3),
                              'budget_source': getattr(
                                  self.budget, 'step_source', None)})
            tr.client.key_value_set_bytes(
                f'{tr.namespace}/hb/r{self.rank}', doc.encode('utf-8'))
        except Exception:
            pass
        try:
            # republish the collective ledger ring at heartbeat
            # cadence: trainer-loop entries (shard_map sync sites)
            # reach peers for cross-rank diffing even when no host
            # collective runs to piggyback on
            from ..distributed.collective import (
                ledger_enabled, get_ledger, LEDGER_KEY)
            if ledger_enabled():
                tr.post_stats(get_ledger(self.rank).frame(),
                              key=LEDGER_KEY)
        except Exception:
            pass

    def _peer_heartbeats(self):
        """{rank: age_s} for every peer with a readable heartbeat —
        via the transport's client-agnostic try_get, so quorum
        detection works on the jax coordination-service client too,
        not only the FileKVStore."""
        tr = self.transport
        if tr is None:
            return {}
        out = {}
        now = time.time()
        for r in range(self.world):
            if r == self.rank:
                continue
            raw = tr.try_get(f'{tr.namespace}/hb/r{r}')
            if raw is None:
                continue
            try:
                out[r] = now - json.loads(raw.decode('utf-8'))['ts']
            except (ValueError, KeyError, UnicodeDecodeError):
                continue
        return out

    def _check_quorum(self):
        if self.world <= 1 or self._escalated:
            return
        ages = self._peer_heartbeats()
        stale = sorted(r for r, age in ages.items()
                       if age > self.peer_stale_s)
        for r in stale:
            if r not in self._peer_flagged:
                self._peer_flagged.add(r)
                self._emit('straggler', peer=r, rank=self.rank,
                           heartbeat_age_s=round(ages[r], 3),
                           stale_after_s=self.peer_stale_s)
                self._probe_ledger('straggler')
        self._peer_flagged -= {r for r in list(self._peer_flagged)
                               if r in ages and
                               ages[r] <= self.peer_stale_s}
        # live = self + peers with fresh (or not-yet-published, i.e.
        # still starting) heartbeats; quorum lost when live ranks are
        # a STRICT minority (live < world/2) — at exactly half (one
        # stale peer of two) the peer's own watchdog/elastic restart
        # handles it, and escalating here too would bill the hang
        # twice against the restart budget
        live = 1 + sum(1 for r, age in ages.items()
                       if age <= self.peer_stale_s)
        unknown = self.world - 1 - len(ages)
        if (live + unknown) * 2 < self.world and self.world > 1:
            self._escalate('quorum_lost', live=live, stale=stale,
                           world=self.world)

    def _probe_ledger(self, trigger):
        """Diff the collective flight-recorder rings on a straggler /
        escalation edge (rank 0 only — one attributed
        ``collective_mismatch`` per incident, not one per rank).
        Never raises; must never kill the watchdog thread."""
        if self.rank != 0 or self.transport is None:
            return None
        try:
            from ..distributed.collective import probe_mismatch
            return probe_mismatch(self.transport, trigger=trigger)
        except Exception:
            return None

    # -- escalation ----------------------------------------------------------

    def _emit(self, kind, **data):
        self.events.append(dict(kind=kind, **data))
        try:
            from .. import telemetry
            telemetry.event(kind, name=self.name, **data)
            telemetry.add(f'watchdog.{kind}')
        except Exception:
            pass

    def _escalate(self, kind, **data):
        if self._escalated:
            return
        self._escalated = True
        info = dict(kind=kind, rank=self.rank, name=self.name, **data)
        # attribute BEFORE the generic escalation event: a ledger
        # divergence turns "rank N hung" into "rank N issued a
        # different collective at seq S (file.py:line)"
        self._probe_ledger(kind)
        self._emit(kind, rank=self.rank, **data)
        # durable evidence BEFORE the abort: this process may be about
        # to _exit, and the flight ring holds the straggler/timeout
        # trail that explains the restart
        try:
            from .. import telemetry
            d = self.flight_dir or telemetry.flight_dir()
            if d:
                path = os.path.join(
                    d, f'flightrec-watchdog-r{self.rank}-'
                       f'{self._step_no}.json')
                telemetry.dump_flight(path)
                info['flight'] = path
        except Exception:
            pass
        if self.transport is not None:
            try:
                self.transport.request_abort(
                    f'watchdog {kind} on rank {self.rank}')
                self._emit('coordinated_abort', rank=self.rank,
                           reason=kind)
            except Exception:
                pass
        if self.on_escalate is not None:
            try:
                self.on_escalate(info)
            except Exception:
                pass
            return
        self._default_escalate(info)

    def _default_escalate(self, info):
        """Grace, then hard exit.  The grace window lets a main thread
        that was stuck in a HOST collective observe the abort flag and
        exit cooperatively (also WATCHDOG_EXIT_CODE, via the worker's
        abort handler); a thread wedged inside XLA or a dead fs gets
        os._exit — the only call guaranteed to free the rank so the
        elastic supervisor can respawn it."""
        time.sleep(self.budget.grace_s)
        os._exit(WATCHDOG_EXIT_CODE)
