"""paddle_tpu.resilience.chaos — deterministic, seeded fault injection.

The resilience runtime (verified commits, two-phase cross-host
finalize, preemption handling, NaN rollback) makes promises it could
not previously PROVE: nothing in the repo injected the faults those
paths exist for.  This module is that proof harness.

A :class:`FaultPlan` is a declarative, *seeded* list of faults:

    plan = FaultPlan(seed=7, faults=[
        Fault('io_error', path='_PADDLE_COMMIT', prob=0.5,
              errno_name='EIO'),
        Fault('torn_write', at_step=3),
        Fault('sigkill', at_step=5),
        Fault('nan_grads', at_step=4),
    ])

and a :class:`ChaosEngine` applies it through *scoped monkeypatch
seams* on the boundaries real failures hit:

  file seam        ``resilience.manifest.atomic_write`` — EIO/ENOSPC
                   raised mid-commit, slow (sleep-injected) writes,
                   torn writes (the tmp file lands, truncated, WITHOUT
                   the atomic rename — what a dying NFS client leaves)
  ckpt seam        ``distributed.checkpoint._SaveHandle.wait`` — shard
                   truncation / byte corruption / dropped or
                   half-finished commits applied the instant a save
                   barrier completes (exactly when a host dies)
  process seam     ``engine.step(n)`` called from the training loop —
                   SIGTERM (graceful-preemption path) or SIGKILL
                   (crash path) delivered at step N, heartbeat files
                   deleted or back-dated
  grads seam       ``engine.poison(n, *arrays)`` — NaN written into
                   the step-N batch so the compiled step's finiteness
                   reduction (hapi / ParallelTrainer / 1F1B pipeline)
                   sees a genuinely non-finite gradient

Determinism is the load-bearing property: every probabilistic decision
comes from ``random.Random(plan.seed)``, consulted in a fixed seam
order, so the SAME plan replays the SAME injected-fault sequence —
``engine.sequence()`` — twice.  Every injection also lands in
telemetry as a ``fault_injected`` event, which tools/run_report.py
merges into the resilience timeline next to the commit-barrier spans
and rollbacks it provoked.

:func:`check_invariants` is the assertion side: given a checkpoint
directory (and optionally the run's telemetry events) it verifies the
resilience invariant set — restore() can only ever yield a committed,
verifiable step; committed steps are monotonic; preemptions exited
PREEMPTED_EXIT_CODE; restarts stayed within budget.  tools/chaos_run.py
drives a training script under a plan and gates on it; bench.py's
``--chaos-smoke`` preflight runs one short plan before spending chip
time.
"""
import contextlib
import errno as _errno
import json
import os
import random
import signal
import time

__all__ = ['FAULT_KINDS', 'Fault', 'FaultPlan', 'ChaosEngine',
           'ChaosCallback', 'check_invariants', 'plan_from_env',
           'PLAN_ENV']

PLAN_ENV = 'PADDLE_TPU_CHAOS_PLAN'

FAULT_KINDS = (
    'io_error',          # raise OSError(errno) from matching file writes
    'slow_io',           # sleep delay_s inside matching file writes
    'torn_write',        # leave a truncated tmp file, skip the rename
    'drop_commit',       # save barrier passes, manifest never written
    'corrupt_shard',     # flip bytes in the largest committed payload
    'truncate_shard',    # truncate the largest committed payload
    'sigterm',           # graceful preemption at step N
    'sigkill',           # hard crash at step N
    'delete_heartbeat',  # remove the heartbeat file at step N
    'stale_heartbeat',   # back-date the heartbeat mtime at step N
    'nan_grads',         # poison the step-N batch with NaN
)


class Fault:
    """One declarative fault.

    kind        one of FAULT_KINDS.
    at_step     fire exactly at this training step (process/grads
                seams), or at the save of this step (ckpt seam).
    prob        fire probabilistically per opportunity (file seam);
                drawn from the plan's seeded RNG.
    count       max number of injections (default 1 for at_step
                faults, unbounded for prob faults).
    path        substring filter on the file path (file/ckpt seams).
    errno_name  'EIO' | 'ENOSPC' | ... for io_error.
    delay_s     sleep for slow_io.
    """

    def __init__(self, kind, at_step=None, prob=None, count=None,
                 path=None, errno_name='EIO', delay_s=0.05):
        if kind not in FAULT_KINDS:
            raise ValueError(f'unknown fault kind {kind!r}; '
                             f'one of {FAULT_KINDS}')
        self.kind = kind
        self.at_step = at_step
        self.prob = prob
        self.count = count if count is not None else \
            (1 if at_step is not None else None)
        self.path = path
        self.errno_name = errno_name
        self.delay_s = delay_s
        self.fired = 0

    def to_dict(self):
        return {k: getattr(self, k) for k in
                ('kind', 'at_step', 'prob', 'count', 'path',
                 'errno_name', 'delay_s')}

    @classmethod
    def from_dict(cls, d):
        return cls(**{k: v for k, v in d.items()
                      if k in ('kind', 'at_step', 'prob', 'count',
                               'path', 'errno_name', 'delay_s')})

    def _exhausted(self):
        return self.count is not None and self.fired >= self.count

    def __repr__(self):
        bits = [self.kind]
        if self.at_step is not None:
            bits.append(f'at_step={self.at_step}')
        if self.prob is not None:
            bits.append(f'prob={self.prob}')
        return f'Fault({", ".join(bits)})'


class FaultPlan:
    """A seeded, declarative set of faults — JSON-serializable so the
    chaos_run driver can ship it to a worker subprocess through one
    env var and a replayed run sees the identical plan."""

    def __init__(self, seed=0, faults=(), name=None):
        self.seed = int(seed)
        self.faults = [f if isinstance(f, Fault) else Fault.from_dict(f)
                       for f in faults]
        self.name = name

    def to_json(self):
        return json.dumps({'seed': self.seed, 'name': self.name,
                           'faults': [f.to_dict() for f in self.faults]},
                          sort_keys=True)

    @classmethod
    def from_json(cls, text):
        d = json.loads(text)
        return cls(seed=d.get('seed', 0), faults=d.get('faults', ()),
                   name=d.get('name'))


def plan_from_env(env=PLAN_ENV):
    """The FaultPlan shipped via the environment, or None.  Workers
    call this at startup so ANY training script becomes chaos-runnable
    without code changes beyond engine.step()/poison() hooks."""
    text = os.environ.get(env)
    return FaultPlan.from_json(text) if text else None


class ChaosEngine:
    """Applies one FaultPlan through scoped monkeypatch seams.

    Use as a context manager (``with ChaosEngine(plan) as eng:``) or
    via activate()/deactivate().  All patches are process-local and
    fully undone on exit — the `chaos` pytest fixture guarantees
    deactivation even on test failure.
    """

    def __init__(self, plan, heartbeat_file=None):
        self.plan = plan if isinstance(plan, FaultPlan) else \
            FaultPlan(**plan) if isinstance(plan, dict) else plan
        self.rng = random.Random(self.plan.seed)
        self.heartbeat_file = heartbeat_file
        self.injected = []          # deterministic injection log
        self._saved = []            # (obj, attr, original) undo stack
        self._active = False

    # -- bookkeeping ---------------------------------------------------------

    def record(self, fault, **info):
        """One injection: appended to the deterministic sequence and
        emitted as a ``fault_injected`` telemetry event."""
        fault.fired += 1
        entry = dict(fault=fault.kind, seq=len(self.injected), **info)
        self.injected.append(entry)
        try:
            from .. import telemetry
            telemetry.event('fault_injected', seed=self.plan.seed,
                            plan=self.plan.name, **entry)
            telemetry.add('chaos.injected')
        except Exception:       # pragma: no cover - defensive
            pass
        return entry

    def sequence(self):
        """The injected-fault sequence so far — the replayability
        contract: same plan (same seed), same scenario ⇒ identical
        sequence."""
        return list(self.injected)

    def _matching(self, kinds, path=None, step=None):
        """Armed faults of `kinds` matching the path/step filters, in
        plan order (deterministic)."""
        out = []
        for f in self.plan.faults:
            if f.kind not in kinds or f._exhausted():
                continue
            if path is not None and f.path is not None \
                    and f.path not in str(path):
                continue
            if step is not None and f.at_step is not None \
                    and f.at_step != step:
                continue
            if path is None and f.path is not None:
                continue
            out.append(f)
        return out

    def _roll(self, fault):
        """Seeded probability gate.  at_step faults fire
        deterministically; prob faults consult the plan RNG — one draw
        per opportunity, so the decision stream is a pure function of
        the seed and the seam-call order."""
        if fault.prob is None:
            return True
        return self.rng.random() < fault.prob

    # -- seams ---------------------------------------------------------------

    def _patch(self, obj, attr, repl):
        self._saved.append((obj, attr, getattr(obj, attr)))
        setattr(obj, attr, repl)

    def activate(self):
        if self._active:
            return self
        from . import manifest as _manifest
        from ..distributed import checkpoint as _ckpt

        orig_write = _manifest.atomic_write

        def chaotic_atomic_write(path, write_fn, mode='w',
                                 prefix='.tmp'):
            for f in self._matching(('io_error',), path=path):
                if self._roll(f):
                    self.record(f, path=str(path),
                                errno=f.errno_name)
                    code = getattr(_errno, f.errno_name, _errno.EIO)
                    raise OSError(code, os.strerror(code), str(path))
            for f in self._matching(('slow_io',), path=path):
                if self._roll(f):
                    self.record(f, path=str(path), delay_s=f.delay_s)
                    time.sleep(f.delay_s)
            for f in self._matching(('torn_write',), path=path):
                if self._roll(f):
                    # what a dying writer leaves on a non-atomic fs:
                    # half the bytes under the REAL name, no fsync, no
                    # rename discipline — the strongest tear the
                    # verify/quarantine path must catch
                    import io
                    buf = io.BytesIO() if 'b' in mode else io.StringIO()
                    write_fn(buf)
                    data = buf.getvalue()
                    half = data[:max(1, len(data) // 2)]
                    with open(path, 'wb' if 'b' in mode else 'w') as fh:
                        fh.write(half)
                    self.record(f, path=str(path),
                                bytes_kept=len(half))
                    return
            return orig_write(path, write_fn, mode=mode, prefix=prefix)

        self._patch(_manifest, 'atomic_write', chaotic_atomic_write)

        orig_wait = _ckpt._SaveHandle.wait
        eng = self

        def chaotic_wait(handle):
            step = getattr(handle, '_step', None)
            for f in eng._matching(('drop_commit',), step=step):
                if eng._roll(f):
                    # the save barrier drains but the process "dies"
                    # before its commit: exactly the SIGKILL-between-
                    # save-and-commit window, minus the actual kill
                    if hasattr(handle._ckptr, 'wait_until_finished'):
                        handle._ckptr.wait_until_finished()
                    handle._ckptr.close()
                    handle._drained = True
                    handle._done = True
                    eng.record(f, step=step)
                    return
            orig_wait(handle)
            for f in eng._matching(('corrupt_shard', 'truncate_shard'),
                                   step=step):
                if eng._roll(f):
                    # handle has no path; the fault carries it
                    target = f.path
                    if target and os.path.isdir(target):
                        victim = eng._damage_dir(target,
                                                 flip=f.kind ==
                                                 'corrupt_shard')
                        eng.record(f, step=step, path=victim)

        self._patch(_ckpt._SaveHandle, 'wait', chaotic_wait)
        self._active = True
        return self

    def deactivate(self):
        while self._saved:
            obj, attr, orig = self._saved.pop()
            setattr(obj, attr, orig)
        self._active = False

    def __enter__(self):
        return self.activate()

    def __exit__(self, *exc):
        self.deactivate()
        return False

    @staticmethod
    def _damage_dir(directory, flip=True):
        """Largest payload file in `directory`: byte-flip (bit-level
        corruption under an intact size) or truncate (torn write)."""
        from .manifest import MANIFEST_NAME, TWO_PHASE_DIR
        victim, size = None, -1
        for root, dirs, files in os.walk(directory):
            if TWO_PHASE_DIR in dirs:
                dirs.remove(TWO_PHASE_DIR)
            for f in files:
                if f == MANIFEST_NAME:
                    continue
                p = os.path.join(root, f)
                if os.path.getsize(p) > size:
                    victim, size = p, os.path.getsize(p)
        if victim is None:
            return None
        with open(victim, 'r+b') as fh:
            if flip:
                fh.seek(max(0, size // 2))
                b = fh.read(1)
                fh.seek(max(0, size // 2))
                fh.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
            else:
                fh.truncate(max(0, size // 2))
        return victim

    # -- process / heartbeat seam -------------------------------------------

    def step(self, step_no):
        """Call once per training step (the chaos_run worker and the
        ChaosCallback do).  Fires process-level faults scheduled for
        this step: SIGTERM (latched by GracefulShutdown → graceful
        preemption), SIGKILL (hard crash), heartbeat tampering."""
        for f in self._matching(('delete_heartbeat',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                hb = self.heartbeat_file
                self.record(f, step=step_no, path=hb)
                if hb:
                    try:
                        os.remove(hb)
                    except OSError:
                        pass
        for f in self._matching(('stale_heartbeat',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                hb = self.heartbeat_file
                self.record(f, step=step_no, path=hb)
                if hb and os.path.exists(hb):
                    past = time.time() - 10_000
                    os.utime(hb, (past, past))
        for f in self._matching(('sigterm',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                self.record(f, step=step_no, signum=int(signal.SIGTERM))
                os.kill(os.getpid(), signal.SIGTERM)
        for f in self._matching(('sigkill',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                self.record(f, step=step_no, signum=int(signal.SIGKILL))
                # record must be durable first: SIGKILL gives no
                # chance to flush anything afterwards
                try:
                    from .. import telemetry
                    d = telemetry.flight_dir()
                    if d:
                        telemetry.dump_flight(os.path.join(
                            d, f'flightrec-chaos-kill-{step_no}.json'))
                except Exception:
                    pass
                os.kill(os.getpid(), signal.SIGKILL)

    def poison(self, step_no, *arrays):
        """NaN-inject the step-N batch (grads seam): returns the
        arrays, with element [0, ...] of each set to NaN when a
        ``nan_grads`` fault fires for this step.  Works on numpy
        arrays; float arrays only (ids pass through untouched)."""
        import numpy as np
        fired = False
        for f in self._matching(('nan_grads',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                self.record(f, step=step_no)
                fired = True
        if not fired:
            return arrays if len(arrays) != 1 else arrays[0]
        out = []
        for a in arrays:
            a = np.array(a, copy=True)
            if np.issubdtype(a.dtype, np.floating):
                a.reshape(-1)[0] = np.nan
            out.append(a)
        return tuple(out) if len(out) != 1 else out[0]


class ChaosCallback:
    """hapi-style callback adapter: drives ``engine.step`` from
    ``Model.fit``'s batch boundary so a FaultPlan's process-level
    faults apply to hapi training loops too (duck-typed — hapi only
    calls the hooks a callback defines)."""

    def __init__(self, engine):
        self.engine = engine
        self._step = 0

    def set_model(self, model):
        self.model = model

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self.engine.step(self._step)


# -- invariant checking --------------------------------------------------------

def check_invariants(ckpt_dir, prefix='step', events=None,
                     max_restarts=None, restarts=None,
                     preempt_codes=(), expect_committed=True):
    """Verify the resilience invariant set after a chaos run.

    Returns a list of violation strings (empty == all invariants held):

      I1  every COMMITTED step dir verifies (presence+size+digest) —
          restore() can therefore only ever yield a committed step;
      I2  committed steps seen over time are monotonic
          (``checkpoint_commit`` telemetry events, when provided);
      I3  every restore landed on a step that was committed at the
          time (``checkpoint_restore`` step ∈ committed set);
      I4  preemptions exited PREEMPTED_EXIT_CODE (`preempt_codes`:
          exit codes the supervisor attributed to preemption);
      I5  restarts stayed within budget (when both given).
    """
    from . import manifest as M
    from .shutdown import PREEMPTED_EXIT_CODE
    violations = []
    committed = []
    if os.path.isdir(ckpt_dir):
        for f in sorted(os.listdir(ckpt_dir)):
            tag = f[len(prefix) + 1:]
            if not (f.startswith(prefix + '_') and tag.isdigit()):
                continue
            p = os.path.join(ckpt_dir, f)
            if not M.is_committed(p):
                continue
            committed.append(int(tag))
            ok, errs = M.verify_manifest(p)
            if not ok:
                violations.append(
                    f'I1: committed step {tag} fails verification: '
                    f'{errs[:3]}')
    elif expect_committed:
        violations.append(f'I1: checkpoint dir {ckpt_dir} missing')
    if expect_committed and not committed:
        violations.append('I1: no committed step survived the run')
    if events:
        commits = [e.get('step') for e in events
                   if e.get('kind') == 'checkpoint_commit'
                   and e.get('step') is not None]
        # per-incarnation streams may interleave after a rollback
        # restore — monotonic within each rank's stream order is the
        # invariant (a later commit may legitimately re-commit an
        # EARLIER step only after a restore to it).  Restores are
        # emitted as spans (kind='span', name='checkpoint_restore').
        restores = [e.get('step') for e in events
                    if (e.get('kind') == 'checkpoint_restore'
                        or (e.get('kind') == 'span'
                            and e.get('name') == 'checkpoint_restore'))
                    and e.get('step') is not None]
        lo = None
        restored = set(restores)
        for s in commits:
            if lo is not None and s < lo and s not in restored \
                    and (s + 1) not in restored:
                violations.append(
                    f'I2: commit steps not monotonic: {s} after {lo} '
                    'with no intervening restore')
            lo = s if lo is None else max(lo, s)
        commit_set = set(commits) | set(committed)
        for s in restores:
            if s not in commit_set:
                violations.append(
                    f'I3: restore yielded step {s}, which was never '
                    'committed')
    for code in preempt_codes:
        if code != PREEMPTED_EXIT_CODE:
            violations.append(
                f'I4: preemption exited {code}, expected '
                f'{PREEMPTED_EXIT_CODE}')
    if max_restarts is not None and restarts is not None \
            and restarts > max_restarts:
        violations.append(
            f'I5: {restarts} failure restarts exceed the '
            f'max_restarts={max_restarts} budget')
    return violations
