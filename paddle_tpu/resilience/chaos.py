"""paddle_tpu.resilience.chaos — deterministic, seeded fault injection.

The resilience runtime (verified commits, two-phase cross-host
finalize, preemption handling, NaN rollback) makes promises it could
not previously PROVE: nothing in the repo injected the faults those
paths exist for.  This module is that proof harness.

A :class:`FaultPlan` is a declarative, *seeded* list of faults:

    plan = FaultPlan(seed=7, faults=[
        Fault('io_error', path='_PADDLE_COMMIT', prob=0.5,
              errno_name='EIO'),
        Fault('torn_write', at_step=3),
        Fault('sigkill', at_step=5),
        Fault('nan_grads', at_step=4),
    ])

and a :class:`ChaosEngine` applies it through *scoped monkeypatch
seams* on the boundaries real failures hit:

  file seam        ``resilience.manifest.atomic_write`` — EIO/ENOSPC
                   raised mid-commit, slow (sleep-injected) writes,
                   torn writes (the tmp file lands, truncated, WITHOUT
                   the atomic rename — what a dying NFS client leaves)
  ckpt seam        ``distributed.checkpoint._SaveHandle.wait`` — shard
                   truncation / byte corruption / dropped or
                   half-finished commits applied the instant a save
                   barrier completes (exactly when a host dies)
  process seam     ``engine.step(n)`` called from the training loop —
                   SIGTERM (graceful-preemption path) or SIGKILL
                   (crash path) delivered at step N, heartbeat files
                   deleted or back-dated
  grads seam       ``engine.poison(n, *arrays)`` — NaN written into
                   the step-N batch so the compiled step's finiteness
                   reduction (hapi / ParallelTrainer / 1F1B pipeline)
                   sees a genuinely non-finite gradient

Determinism is the load-bearing property: every probabilistic decision
comes from ``random.Random(plan.seed)``, consulted in a fixed seam
order, so the SAME plan replays the SAME injected-fault sequence —
``engine.sequence()`` — twice.  Every injection also lands in
telemetry as a ``fault_injected`` event, which tools/run_report.py
merges into the resilience timeline next to the commit-barrier spans
and rollbacks it provoked.

:func:`check_invariants` is the assertion side: given a checkpoint
directory (and optionally the run's telemetry events) it verifies the
resilience invariant set — restore() can only ever yield a committed,
verifiable step; committed steps are monotonic; preemptions exited
PREEMPTED_EXIT_CODE; restarts stayed within budget.  tools/chaos_run.py
drives a training script under a plan and gates on it; bench.py's
``--chaos-smoke`` preflight runs one short plan before spending chip
time.
"""
import contextlib
import errno as _errno
import json
import os
import random
import signal
import time

__all__ = ['FAULT_KINDS', 'COLLECTIVE_FAULT_KINDS',
           'SERVING_FAULT_KINDS', 'Fault', 'FaultPlan', 'ChaosEngine',
           'ChaosCallback', 'ChaosCluster', 'ServingFaultInjector',
           'check_invariants', 'plan_from_env', 'load_run_events',
           'PLAN_ENV']

PLAN_ENV = 'PADDLE_TPU_CHAOS_PLAN'

# faults that land on the host-collective wire (distributed.collective
# HostCollectives) — the seam class added for the multi-process chaos
# topology, and the one that must exist BEFORE quantized (EQuARX)
# collectives change what travels on it
COLLECTIVE_FAULT_KINDS = (
    'collective_delay',    # sleep delay_s before posting the payload
    'collective_hang',     # go silent: never post; peers time out and
                           # the abort flag (or delay_s) releases us
    'collective_drop',     # participant drops out: raise mid-collective
    'collective_corrupt',  # flip a payload byte AFTER the crc header
                           # is computed — receivers must detect it
)

# faults that land on the serving fleet (serving/router.py front
# door): injected by the drill driver through ServingFaultInjector's
# two seams, NOT by ChaosEngine's file/step/collective hooks — a
# serving drill has no training step to key on, so these fire on
# stream progress (`after_tokens`) instead of `at_step`.  Opt-in via
# plangen.OPTIN_KINDS, same draw-stream-stability reasoning as
# collective_skip.
SERVING_FAULT_KINDS = (
    'replica_kill',       # SIGKILL a fleet replica once a targeted
                          # stream has emitted after_tokens tokens —
                          # the router must land every in-flight rid
                          # in a terminal state: retried bit-exact on
                          # a survivor, or failed TYPED (never lost)
    'replica_hang',       # SIGSTOP a replica: its streams stall past
                          # the router's read timeout; looks like a
                          # dead peer that still holds the port, so
                          # detection cannot rely on process exit
    'client_disconnect',  # drop the CLIENT connection mid-stream
                          # after after_tokens tokens — the frontend
                          # must evict the rid and roll its tokens
                          # back (PR-12 preemption accounting)
    'slow_client',        # client stops reading between events for
                          # delay_s — backpressure must not wedge the
                          # engine thread or starve other streams
)

FAULT_KINDS = (
    'io_error',          # raise OSError(errno) from matching file writes
    'slow_io',           # sleep delay_s inside matching file writes
    'torn_write',        # leave a truncated tmp file, skip the rename
    'drop_commit',       # save barrier passes, manifest never written
    'corrupt_shard',     # flip bytes in the largest committed payload
    'truncate_shard',    # truncate the largest committed payload
    'sigterm',           # graceful preemption at step N
    'sigkill',           # hard crash at step N
    'delete_heartbeat',  # remove the heartbeat file at step N
    'stale_heartbeat',   # back-date the heartbeat mtime at step N
    'nan_grads',         # poison the step-N batch with NaN
    'slow_rank',         # throttle this rank's step N by delay_s (the
                         # straggler the watchdog must attribute)
    'drift',             # emit a synthetic drift_detected at step N
                         # (op + us_ratio): the sustained sensor edge
                         # the plan supervisor must actuate on exactly
                         # once — chaos-grade drift without waiting
                         # for a real profiled collective to degrade
    'collective_skip',   # rank silently SKIPS a matching collective
                         # (no post, no ledger entry) and proceeds —
                         # the SPMD-contract violation the collective
                         # flight recorder must attribute to its call
                         # site.  Deliberately NOT in
                         # COLLECTIVE_FAULT_KINDS: growing that tuple
                         # would shift plangen's seeded draw stream
                         # and break golden-pinned plans (opt-in via
                         # plangen.OPTIN_KINDS, the 'drift' precedent)
) + COLLECTIVE_FAULT_KINDS + SERVING_FAULT_KINDS


class Fault:
    """One declarative fault.

    kind        one of FAULT_KINDS.
    at_step     fire exactly at this training step (process/grads/
                collective seams), or at the save of this step (ckpt
                seam).
    prob        fire probabilistically per opportunity (file seam);
                drawn from the plan's seeded RNG.
    count       max number of injections (default 1 for at_step
                faults, unbounded for prob faults).
    path        substring filter on the file path (file/ckpt seams).
    errno_name  'EIO' | 'ENOSPC' | ... for io_error.
    delay_s     sleep for slow_io / collective_delay / slow_rank, and
                the hang duration cap for collective_hang.
    rank        only fire on this cluster rank (None = any rank) —
                multi-process plans slice per rank; see
                FaultPlan.slice_for_rank.
    op          substring filter on the collective op/tag (collective
                seams; e.g. 'allreduce' or 'step7'), and the drifted
                collective kind for ``drift`` faults (default
                'all-reduce').
    us_ratio    observed/predicted ratio a ``drift`` fault reports
                (default 8.0 — far outside the monitor's 4x band).
    after_tokens  serving seams (SERVING_FAULT_KINDS): fire once the
                targeted stream has emitted this many tokens — the
                serving analogue of at_step (a drill has no training
                step; stream progress is its clock).  `rank` selects
                the replica index for replica_* kinds; `path`
                substring-filters the rid.
    """

    def __init__(self, kind, at_step=None, prob=None, count=None,
                 path=None, errno_name='EIO', delay_s=0.05,
                 rank=None, op=None, us_ratio=None,
                 after_tokens=None):
        if kind not in FAULT_KINDS:
            raise ValueError(f'unknown fault kind {kind!r}; '
                             f'one of {FAULT_KINDS}')
        self.kind = kind
        self.at_step = at_step
        self.prob = prob
        self.count = count if count is not None else \
            (1 if at_step is not None else None)
        self.path = path
        self.errno_name = errno_name
        self.delay_s = delay_s
        self.rank = rank
        self.op = op
        self.us_ratio = us_ratio
        self.after_tokens = None if after_tokens is None \
            else int(after_tokens)
        self.fired = 0

    _FIELDS = ('kind', 'at_step', 'prob', 'count', 'path',
               'errno_name', 'delay_s', 'rank', 'op', 'us_ratio',
               'after_tokens')

    def to_dict(self):
        d = {k: getattr(self, k) for k in self._FIELDS}
        # us_ratio / after_tokens joined the schema after plans were
        # golden-pinned: omit them when unset so every pre-existing
        # plan's canonical JSON (and fingerprint) stays byte-identical
        for late in ('us_ratio', 'after_tokens'):
            if d[late] is None:
                del d[late]
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(**{k: v for k, v in d.items() if k in cls._FIELDS})

    def _exhausted(self):
        return self.count is not None and self.fired >= self.count

    def __repr__(self):
        bits = [self.kind]
        if self.at_step is not None:
            bits.append(f'at_step={self.at_step}')
        if self.prob is not None:
            bits.append(f'prob={self.prob}')
        if self.rank is not None:
            bits.append(f'rank={self.rank}')
        if self.op is not None:
            bits.append(f'op={self.op!r}')
        return f'Fault({", ".join(bits)})'


class FaultPlan:
    """A seeded, declarative set of faults — JSON-serializable so the
    chaos_run driver can ship it to a worker subprocess through one
    env var and a replayed run sees the identical plan."""

    def __init__(self, seed=0, faults=(), name=None):
        self.seed = int(seed)
        self.faults = [f if isinstance(f, Fault) else Fault.from_dict(f)
                       for f in faults]
        self.name = name

    def to_json(self):
        return json.dumps({'seed': self.seed, 'name': self.name,
                           'faults': [f.to_dict() for f in self.faults]},
                          sort_keys=True)

    @classmethod
    def from_json(cls, text):
        d = json.loads(text)
        return cls(seed=d.get('seed', 0), faults=d.get('faults', ()),
                   name=d.get('name'))

    def slice_for_rank(self, rank):
        """This rank's share of a cluster plan: faults addressed to
        `rank` plus the unaddressed ones.  The SEED is unchanged —
        same cluster seed => every rank replays its identical injected
        sequence, and the union over ranks is the plan's sequence."""
        rank = int(rank)
        faults = [Fault.from_dict(f.to_dict()) for f in self.faults
                  if f.rank is None or int(f.rank) == rank]
        return FaultPlan(seed=self.seed, faults=faults,
                         name=f'{self.name or "plan"}@r{rank}')

    def mark_fired(self, events, rank=None):
        """Replay the fault ledger into this plan: count the
        ``fault_injected`` records a PREVIOUS incarnation already
        injected (telemetry JSONL + flight dumps survive the process)
        and advance each bounded fault's ``fired`` counter, so a
        restarted worker re-reading the same plan does not re-kill /
        re-hang itself at the same step forever — while faults it has
        NOT yet reached still fire.  Returns the number of ledger
        entries applied."""
        applied = 0
        for f in self.faults:
            if f.count is None:
                continue        # unbounded prob faults may refire
            n = 0
            for e in events:
                if e.get('kind') != 'fault_injected':
                    continue
                if e.get('fault') != f.kind:
                    continue
                if rank is not None and e.get('rank', 0) != rank:
                    continue
                if f.at_step is not None \
                        and e.get('step') != f.at_step:
                    continue
                if f.op is not None and f.op not in str(
                        e.get('op') or e.get('tag') or ''):
                    continue
                n += 1
            if n:
                f.fired = min(f.count, f.fired + n)
                applied += n
        return applied


def plan_from_env(env=PLAN_ENV):
    """The FaultPlan shipped via the environment, or None.  Workers
    call this at startup so ANY training script becomes chaos-runnable
    without code changes beyond engine.step()/poison() hooks."""
    text = os.environ.get(env)
    return FaultPlan.from_json(text) if text else None


class ServingFaultInjector:
    """Interprets a plan's SERVING_FAULT_KINDS at the fleet drill's
    two seams — the serving counterpart of ChaosEngine (which patches
    file/step/collective seams a serving drill never crosses).

    The drill driver (``bench.py --frontdoor-smoke``, the frontdoor
    tests) calls:

    * :meth:`fleet_faults` from its on_token tap: replica-side kinds
      (replica_kill / replica_hang) due at this stream offset — the
      driver applies them with ``ReplicaHandle.kill(SIGKILL|SIGSTOP)``;
    * :meth:`client_faults` from the client read loop:
      client_disconnect (close the socket now) and slow_client (sleep
      ``delay_s`` before the next read).

    Faults stay declarative and seeded exactly like every other kind:
    same plan JSON => same injected sequence, and each firing is
    recorded so :func:`check_invariants`-style audits can line the
    ledger up against what was actually injected.
    """

    def __init__(self, plan, telemetry=None):
        self.plan = plan
        self.faults = [f for f in plan.faults
                       if f.kind in SERVING_FAULT_KINDS]
        self.telemetry = telemetry
        self.injected = []      # [{'fault', 'rid', 'emitted'}, ...]

    def _due(self, kinds, rid, emitted, replica_index=None):
        out = []
        for f in self.faults:
            if f.kind not in kinds or f._exhausted():
                continue
            if f.path is not None and f.path not in str(rid):
                continue
            if f.after_tokens is not None and emitted < f.after_tokens:
                continue
            if f.rank is not None and replica_index is not None \
                    and int(f.rank) != int(replica_index):
                continue
            f.fired += 1
            rec = {'fault': f.kind, 'rid': rid, 'emitted': emitted}
            self.injected.append(rec)
            if self.telemetry is not None:
                self.telemetry.event('fault_injected', fault=f.kind,
                                     rid=str(rid), emitted=emitted)
            out.append(f)
        return out

    def fleet_faults(self, rid, emitted, replica_index=None):
        """replica_kill / replica_hang due now for stream `rid` at
        global offset `emitted` (replica_index = position of the
        serving replica in the fleet's active list, matched against
        the fault's `rank`)."""
        return self._due(('replica_kill', 'replica_hang'), rid,
                         emitted, replica_index)

    def client_faults(self, rid, emitted):
        """client_disconnect / slow_client due now on `rid`'s client
        connection."""
        return self._due(('client_disconnect', 'slow_client'), rid,
                         emitted)


class ChaosEngine:
    """Applies one FaultPlan through scoped monkeypatch seams.

    Use as a context manager (``with ChaosEngine(plan) as eng:``) or
    via activate()/deactivate().  All patches are process-local and
    fully undone on exit — the `chaos` pytest fixture guarantees
    deactivation even on test failure.
    """

    def __init__(self, plan, heartbeat_file=None, rank=None):
        self.plan = plan if isinstance(plan, FaultPlan) else \
            FaultPlan(**plan) if isinstance(plan, dict) else plan
        self.rng = random.Random(self.plan.seed)
        self.heartbeat_file = heartbeat_file
        self.rank = (int(rank) if rank is not None else
                     int(os.environ.get('PADDLE_TRAINER_ID', 0) or 0))
        self.injected = []          # deterministic injection log
        self._saved = []            # (obj, attr, original) undo stack
        self._active = False
        self._current_step = None   # set by step(); collective faults
                                    # with at_step match against it

    # -- bookkeeping ---------------------------------------------------------

    def record(self, fault, **info):
        """One injection: appended to the deterministic sequence and
        emitted as a ``fault_injected`` telemetry event.  Every entry
        carries a rank (seam-provided, else the engine's own) so
        in-memory consumers and flight-ring copies stay attributable
        without relying on the JSONL writer's per-process tag."""
        fault.fired += 1
        entry = dict(fault=fault.kind, seq=len(self.injected), **info)
        entry.setdefault('rank', self.rank)
        self.injected.append(entry)
        try:
            from .. import telemetry
            telemetry.event('fault_injected', seed=self.plan.seed,
                            plan=self.plan.name, **entry)
            telemetry.add('chaos.injected')
        except Exception:       # pragma: no cover - defensive
            pass
        return entry

    def sequence(self):
        """The injected-fault sequence so far — the replayability
        contract: same plan (same seed), same scenario ⇒ identical
        sequence."""
        return list(self.injected)

    def _matching(self, kinds, path=None, step=None, op=None,
                  rank=None):
        """Armed faults of `kinds` matching the path/step/op/rank
        filters, in plan order (deterministic).  `rank` defaults to
        the engine's own rank; the collective seam passes the POSTING
        transport's rank instead (class-level patches see every
        transport in the process — in-process multi-rank tests would
        otherwise misattribute rank-addressed wire faults)."""
        rank = self.rank if rank is None else int(rank)
        out = []
        for f in self.plan.faults:
            if f.kind not in kinds or f._exhausted():
                continue
            if f.rank is not None and int(f.rank) != rank:
                continue
            if path is not None and f.path is not None \
                    and f.path not in str(path):
                continue
            if step is not None and f.at_step is not None \
                    and f.at_step != step:
                continue
            if path is None and f.path is not None:
                continue
            # a drift fault's `op` is PAYLOAD (which collective the
            # synthetic sensor edge reports), not an op-seam address —
            # the step loop that fires it has no op context
            if f.op is not None and f.kind != 'drift' \
                    and (op is None or f.op not in str(op)):
                continue
            out.append(f)
        return out

    def _roll(self, fault):
        """Seeded probability gate.  at_step faults fire
        deterministically; prob faults consult the plan RNG — one draw
        per opportunity, so the decision stream is a pure function of
        the seed and the seam-call order."""
        if fault.prob is None:
            return True
        return self.rng.random() < fault.prob

    # -- seams ---------------------------------------------------------------

    def _patch(self, obj, attr, repl):
        self._saved.append((obj, attr, getattr(obj, attr)))
        setattr(obj, attr, repl)

    def activate(self):
        if self._active:
            return self
        from . import manifest as _manifest
        from ..distributed import checkpoint as _ckpt

        orig_write = _manifest.atomic_write

        def chaotic_atomic_write(path, write_fn, mode='w',
                                 prefix='.tmp'):
            for f in self._matching(('io_error',), path=path):
                if self._roll(f):
                    self.record(f, path=str(path),
                                errno=f.errno_name)
                    code = getattr(_errno, f.errno_name, _errno.EIO)
                    raise OSError(code, os.strerror(code), str(path))
            for f in self._matching(('slow_io',), path=path):
                if self._roll(f):
                    self.record(f, path=str(path), delay_s=f.delay_s)
                    time.sleep(f.delay_s)
            for f in self._matching(('torn_write',), path=path):
                if self._roll(f):
                    # what a dying writer leaves on a non-atomic fs:
                    # half the bytes under the REAL name, no fsync, no
                    # rename discipline — the strongest tear the
                    # verify/quarantine path must catch
                    import io
                    buf = io.BytesIO() if 'b' in mode else io.StringIO()
                    write_fn(buf)
                    data = buf.getvalue()
                    half = data[:max(1, len(data) // 2)]
                    with open(path, 'wb' if 'b' in mode else 'w') as fh:
                        fh.write(half)
                    self.record(f, path=str(path),
                                bytes_kept=len(half))
                    return
            return orig_write(path, write_fn, mode=mode, prefix=prefix)

        self._patch(_manifest, 'atomic_write', chaotic_atomic_write)

        orig_wait = _ckpt._SaveHandle.wait
        eng = self

        def chaotic_wait(handle):
            step = getattr(handle, '_step', None)
            for f in eng._matching(('drop_commit',), step=step):
                if eng._roll(f):
                    # the save barrier drains but the process "dies"
                    # before its commit: exactly the SIGKILL-between-
                    # save-and-commit window, minus the actual kill
                    if hasattr(handle._ckptr, 'wait_until_finished'):
                        handle._ckptr.wait_until_finished()
                    handle._ckptr.close()
                    handle._drained = True
                    handle._done = True
                    eng.record(f, step=step)
                    return
            orig_wait(handle)
            for f in eng._matching(('corrupt_shard', 'truncate_shard'),
                                   step=step):
                if eng._roll(f):
                    # handle has no path; the fault carries it
                    target = f.path
                    if target and os.path.isdir(target):
                        victim = eng._damage_dir(target,
                                                 flip=f.kind ==
                                                 'corrupt_shard')
                        eng.record(f, step=step, path=victim)

        self._patch(_ckpt._SaveHandle, 'wait', chaotic_wait)
        self._install_collective_seams()
        self._active = True
        return self

    def _install_collective_seams(self):
        """Patch the host-collective transport's post() (class-level:
        every HostCollectives instance in this process).  The four wire
        faults live here because this is where a real cluster fails:
        a slow NIC (delay), a wedged peer (hang), a crashed peer
        (drop), and bit rot on the wire (corrupt) — all BEFORE the
        payload leaves this rank, so the injected byte damage must be
        caught by the receivers' frame checks, whatever the dtype."""
        from ..distributed import collective as _coll

        eng = self
        orig_post = _coll.HostCollectives.post

        def chaotic_post(transport, tag, op, payload):
            label = f'{op}:{tag}'
            step = eng._current_step

            def armed(f):
                # mirror the process seam's explicit recheck: an
                # at_step fault must not fire on collectives that run
                # BEFORE the loop's first engine.step() (startup
                # barriers/broadcasts), when _current_step is None and
                # _matching's step filter is vacuous
                if f.at_step is not None and f.at_step != step:
                    return False
                return eng._roll(f)
            for f in eng._matching(('collective_drop',), step=step,
                                   op=label,
                                   rank=transport.rank):
                if armed(f):
                    eng.record(f, op=op, tag=tag, rank=transport.rank,
                               step=step)
                    raise RuntimeError(
                        f'chaos: injected participant drop in '
                        f'{op}[{tag}] on rank {eng.rank}')
            for f in eng._matching(('collective_hang',), step=step,
                                   op=label,
                                   rank=transport.rank):
                if armed(f):
                    eng.record(f, op=op, tag=tag, rank=transport.rank,
                               step=step, delay_s=f.delay_s)
                    # go silent: peers see a missing participant and
                    # time out; we wake early only for the cluster
                    # abort flag (the coordinated-abort release) or
                    # the hang cap (a straggler that finally arrives)
                    deadline = time.monotonic() + f.delay_s
                    while time.monotonic() < deadline:
                        doc = transport.abort_requested()
                        if doc is not None:
                            from ..distributed.collective import \
                                CoordinatedAbort
                            raise CoordinatedAbort(
                                f'chaos hang in {op}[{tag}] released '
                                f'by abort from rank '
                                f'{doc.get("rank")}')
                        time.sleep(min(0.02, f.delay_s))
            for f in eng._matching(('collective_delay',), step=step,
                                   op=label,
                                   rank=transport.rank):
                if armed(f):
                    eng.record(f, op=op, tag=tag, rank=transport.rank,
                               step=step, delay_s=f.delay_s)
                    time.sleep(f.delay_s)
            for f in eng._matching(('collective_corrupt',), step=step,
                                   op=label,
                                   rank=transport.rank):
                if armed(f):
                    eng.record(f, op=op, tag=tag, rank=transport.rank,
                               step=step)
                    # flip one payload byte AFTER the crc header was
                    # computed: receivers MUST reject the frame
                    b = bytearray(payload)
                    b[-1] ^= 0xFF
                    payload = bytes(b)
            return orig_post(transport, tag, op, payload)

        self._patch(_coll.HostCollectives, 'post', chaotic_post)

        orig_exchange = _coll.HostCollectives._exchange

        def chaotic_exchange(transport, tag, op, arr, timeout_s=None,
                             quant=None):
            # collective_skip intercepts the WHOLE exchange (not just
            # the post): the rank records nothing in its ledger, posts
            # nothing, waits for nobody, and proceeds with its own
            # contribution — the rank-gated skipped collective whose
            # divergence the flight recorder must attribute
            label = f'{op}:{tag}'
            step = eng._current_step
            for f in eng._matching(('collective_skip',), step=step,
                                   op=label, rank=transport.rank):
                if f.at_step is not None and f.at_step != step:
                    continue
                if not eng._roll(f):
                    continue
                eng.record(f, op=op, tag=tag, rank=transport.rank,
                           step=step)
                import numpy as _np
                return {transport.rank: _np.asarray(arr)}
            return orig_exchange(transport, tag, op, arr,
                                 timeout_s=timeout_s, quant=quant)

        self._patch(_coll.HostCollectives, '_exchange',
                    chaotic_exchange)

    def deactivate(self):
        while self._saved:
            obj, attr, orig = self._saved.pop()
            setattr(obj, attr, orig)
        self._active = False

    def __enter__(self):
        return self.activate()

    def __exit__(self, *exc):
        self.deactivate()
        return False

    @staticmethod
    def _damage_dir(directory, flip=True):
        """Largest payload file in `directory`: byte-flip (bit-level
        corruption under an intact size) or truncate (torn write)."""
        from .manifest import MANIFEST_NAME, TWO_PHASE_DIR
        victim, size = None, -1
        for root, dirs, files in os.walk(directory):
            if TWO_PHASE_DIR in dirs:
                dirs.remove(TWO_PHASE_DIR)
            for f in files:
                if f == MANIFEST_NAME:
                    continue
                p = os.path.join(root, f)
                if os.path.getsize(p) > size:
                    victim, size = p, os.path.getsize(p)
        if victim is None:
            return None
        with open(victim, 'r+b') as fh:
            if flip:
                fh.seek(max(0, size // 2))
                b = fh.read(1)
                fh.seek(max(0, size // 2))
                fh.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
            else:
                fh.truncate(max(0, size // 2))
        return victim

    # -- process / heartbeat seam -------------------------------------------

    def step(self, step_no):
        """Call once per training step (the chaos_run worker and the
        ChaosCallback do).  Fires process-level faults scheduled for
        this step: SIGTERM (latched by GracefulShutdown → graceful
        preemption), SIGKILL (hard crash), heartbeat tampering,
        slow-rank throttling.  Also advances the step the collective
        seams match ``at_step`` against."""
        self._current_step = step_no
        for f in self._matching(('slow_rank',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                # the deliberate straggler: this rank's step runs, just
                # late — the watchdog's soft threshold must attribute
                # it without killing anything
                self.record(f, step=step_no, rank=self.rank,
                            delay_s=f.delay_s)
                time.sleep(f.delay_s)
        for f in self._matching(('drift',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                # synthetic sensor edge: the SAME drift_detected event
                # telemetry.monitors latches off a real profiled
                # collective, minus the hours of waiting — the plan
                # supervisor must classify, re-plan and actuate on it
                # exactly once
                op = f.op or 'all-reduce'
                ratio = float(f.us_ratio or 8.0)
                self.record(f, step=step_no, op=op, us_ratio=ratio)
                try:
                    from .. import telemetry
                    telemetry.event(
                        'drift_detected', cause='us_ratio', op=op,
                        instr='chaos-injected', us_ratio=ratio,
                        band=4.0, windows=8)
                except Exception:
                    pass
        for f in self._matching(('delete_heartbeat',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                hb = self.heartbeat_file
                self.record(f, step=step_no, path=hb)
                if hb:
                    try:
                        os.remove(hb)
                    except OSError:
                        pass
        for f in self._matching(('stale_heartbeat',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                hb = self.heartbeat_file
                self.record(f, step=step_no, path=hb)
                if hb and os.path.exists(hb):
                    past = time.time() - 10_000
                    os.utime(hb, (past, past))
        for f in self._matching(('sigterm',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                self.record(f, step=step_no, signum=int(signal.SIGTERM))
                os.kill(os.getpid(), signal.SIGTERM)
        for f in self._matching(('sigkill',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                self.record(f, step=step_no, signum=int(signal.SIGKILL))
                # record must be durable first: SIGKILL gives no
                # chance to flush anything afterwards
                try:
                    from .. import telemetry
                    d = telemetry.flight_dir()
                    if d:
                        telemetry.dump_flight(os.path.join(
                            d, f'flightrec-chaos-kill-{step_no}.json'))
                except Exception:
                    pass
                os.kill(os.getpid(), signal.SIGKILL)

    def poison(self, step_no, *arrays):
        """NaN-inject the step-N batch (grads seam): returns the
        arrays, with element [0, ...] of each set to NaN when a
        ``nan_grads`` fault fires for this step.  Works on numpy
        arrays; float arrays only (ids pass through untouched)."""
        import numpy as np
        fired = False
        for f in self._matching(('nan_grads',), step=step_no):
            if f.at_step == step_no and self._roll(f):
                self.record(f, step=step_no)
                fired = True
        if not fired:
            return arrays if len(arrays) != 1 else arrays[0]
        out = []
        for a in arrays:
            a = np.array(a, copy=True)
            if np.issubdtype(a.dtype, np.floating):
                a.reshape(-1)[0] = np.nan
            out.append(a)
        return tuple(out) if len(out) != 1 else out[0]


class ChaosCallback:
    """hapi-style callback adapter: drives ``engine.step`` from
    ``Model.fit``'s batch boundary so a FaultPlan's process-level
    faults apply to hapi training loops too (duck-typed — hapi only
    calls the hooks a callback defines)."""

    def __init__(self, engine):
        self.engine = engine
        self._step = 0

    def set_model(self, model):
        self.model = model

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self.engine.step(self._step)


# -- invariant checking --------------------------------------------------------

def check_invariants(ckpt_dir, prefix='step', events=None,
                     max_restarts=None, restarts=None,
                     preempt_codes=(), expect_committed=True,
                     final_rc=None, duration_s=None, deadline_s=None):
    """Verify the resilience invariant set after a chaos run.

    Returns a list of violation strings (empty == all invariants held):

      I1  every COMMITTED step dir verifies (presence+size+digest) —
          restore() can therefore only ever yield a committed step;
      I2  committed steps seen over time are monotonic
          (``checkpoint_commit`` telemetry events, when provided);
      I3  every restore landed on a step that was committed at the
          time (``checkpoint_restore`` step ∈ committed set);
      I4  preemptions exited PREEMPTED_EXIT_CODE (`preempt_codes`:
          exit codes the supervisor attributed to preemption);
      I5  restarts stayed within budget (when both given);
      I6  no step is published (committed) twice after a restart
          unless an intervening restore rolled back BELOW it — a
          restarted worker that re-commits work it never un-did is
          double-publishing state;
      I7  the cluster either completed (rc 0) or exited preempted,
          within the deadline budget — a deadlocked or wedged cluster
          (any other rc, or `duration_s` > `deadline_s`) is itself an
          invariant violation, whatever its checkpoints look like.
    """
    from . import manifest as M
    from .shutdown import PREEMPTED_EXIT_CODE
    violations = []
    committed = []
    if os.path.isdir(ckpt_dir):
        for f in sorted(os.listdir(ckpt_dir)):
            tag = f[len(prefix) + 1:]
            if not (f.startswith(prefix + '_') and tag.isdigit()):
                continue
            p = os.path.join(ckpt_dir, f)
            if not M.is_committed(p):
                continue
            committed.append(int(tag))
            ok, errs = M.verify_manifest(p)
            if not ok:
                violations.append(
                    f'I1: committed step {tag} fails verification: '
                    f'{errs[:3]}')
    elif expect_committed:
        violations.append(f'I1: checkpoint dir {ckpt_dir} missing')
    if expect_committed and not committed:
        violations.append('I1: no committed step survived the run')
    if events:
        commits = [e.get('step') for e in events
                   if e.get('kind') == 'checkpoint_commit'
                   and e.get('step') is not None]
        # per-incarnation streams may interleave after a rollback
        # restore — monotonic within each rank's stream order is the
        # invariant (a later commit may legitimately re-commit an
        # EARLIER step only after a restore to it).  Restores are
        # emitted as spans (kind='span', name='checkpoint_restore').
        restores = [e.get('step') for e in events
                    if (e.get('kind') == 'checkpoint_restore'
                        or (e.get('kind') == 'span'
                            and e.get('name') == 'checkpoint_restore'))
                    and e.get('step') is not None]
        lo = None
        restored = set(restores)
        for s in commits:
            if lo is not None and s < lo and s not in restored \
                    and (s + 1) not in restored:
                violations.append(
                    f'I2: commit steps not monotonic: {s} after {lo} '
                    'with no intervening restore')
            lo = s if lo is None else max(lo, s)
        commit_set = set(commits) | set(committed)
        for s in restores:
            if s not in commit_set:
                violations.append(
                    f'I3: restore yielded step {s}, which was never '
                    'committed')
        # I6: a step may be committed AGAIN only after a restore that
        # rolled back below it (the replay then legitimately re-earns
        # it).  Walk the merged stream in order, tracking whether a
        # sufficiently-deep restore separates the two commits.
        commit_or_restore = [
            e for e in events
            if (e.get('kind') == 'checkpoint_commit'
                and e.get('step') is not None)
            or ((e.get('kind') == 'checkpoint_restore'
                 or (e.get('kind') == 'span'
                     and e.get('name') == 'checkpoint_restore'))
                and e.get('step') is not None)]
        seen_commit = {}        # step -> index of its last commit
        for i, e in enumerate(commit_or_restore):
            s = e.get('step')
            if e.get('kind') == 'checkpoint_commit':
                if s in seen_commit:
                    prev = seen_commit[s]
                    rolled_back = any(
                        r.get('kind') != 'checkpoint_commit'
                        and r.get('step') < s
                        for r in commit_or_restore[prev + 1:i])
                    if not rolled_back:
                        violations.append(
                            f'I6: step {s} published twice with no '
                            'intervening restore below it')
                seen_commit[s] = i
    for code in preempt_codes:
        if code != PREEMPTED_EXIT_CODE:
            violations.append(
                f'I4: preemption exited {code}, expected '
                f'{PREEMPTED_EXIT_CODE}')
    if max_restarts is not None and restarts is not None \
            and restarts > max_restarts:
        violations.append(
            f'I5: {restarts} failure restarts exceed the '
            f'max_restarts={max_restarts} budget')
    if final_rc is not None and final_rc not in (
            0, PREEMPTED_EXIT_CODE):
        violations.append(
            f'I7: cluster neither completed nor exited preempted '
            f'(rc={final_rc})')
    if deadline_s is not None and duration_s is not None \
            and duration_s > deadline_s:
        violations.append(
            f'I7: run took {duration_s:.1f}s, past the '
            f'{deadline_s:.1f}s deadline budget')
    return violations


class ChaosCluster:
    """A true multi-process chaos topology: N worker processes under
    elastic supervision, one shared filesystem KV transport, one
    seeded FaultPlan sliced per rank.

    Each worker is a separate interpreter (tools/soak_run.py
    ``--worker`` by default) that: joins the cluster's
    :class:`~paddle_tpu.distributed.collective.FileKVStore` transport
    (restart-proof — the jax coordination service cannot re-admit a
    SIGKILLed task, files can; workers still ``jax.distributed``-
    initialize when `jax_distributed` is set and the plan kills
    nobody), activates its per-rank plan slice (same cluster seed =>
    identical injected sequence every run), trains the deterministic
    workload with a host all-reduce every step, two-phase-commits
    per-rank checkpoint shards, and runs a
    :class:`~paddle_tpu.resilience.watchdog.Watchdog` so a hung
    collective escalates timeout -> flight dump -> coordinated abort
    -> WATCHDOG_EXIT_CODE instead of deadlocking the cluster.

    ``run()`` supervises to completion (bounded by `deadline_s`),
    merges every incarnation's telemetry, and checks invariants I1-I7
    plus cross-rank final-state agreement.  Teardown is guaranteed:
    worker processes are terminated and any coordinator-side seams
    deactivated even when a worker dies mid-plan (the killed-worker
    case the PR-5 reverse-order teardown fix is mirrored for)."""

    def __init__(self, procs=2, plan=None, steps=20, workdir=None,
                 max_restarts=4, save_every=2, collective_timeout_s=30.0,
                 barrier_timeout_s=20.0, watchdog='step=90,grace=2',
                 worker_argv=None, deadline_s=240.0,
                 jax_distributed=False, engine=None, extra_env=None,
                 cluster_stats=False, cluster_stats_interval=0.25,
                 restart_backoff=0.2, restart_backoff_max=2.0,
                 supervisor=None):
        import tempfile
        self.procs = int(procs)
        # crash-restart backoff (seconds, exponential up to the max).
        # The cluster-obs smoke widens it so a SIGKILLed rank stays
        # down long enough for the live view's stale-marking to be
        # observable by a 200ms scraper — with the default snappy
        # respawn the degraded window can close before one scrape.
        self.restart_backoff = float(restart_backoff)
        self.restart_backoff_max = float(restart_backoff_max)
        # cluster_stats: arm the live training observability plane
        # (telemetry.cluster) inside the workers — every rank
        # publishes stats frames over the cluster's own KV transport,
        # rank 0 aggregates and serves /cluster/status.json on an
        # ephemeral 127.0.0.1 port written to
        # <workdir>/cluster_port.json so the supervisor (or a test /
        # the --cluster-obs-smoke gate) can scrape a LIVE view of the
        # chaos run.  The plane must survive every fault the plan
        # injects: a killed rank degrades the view (stale-marked),
        # never crashes it.
        self.cluster_stats = bool(cluster_stats)
        self.cluster_stats_interval = float(cluster_stats_interval)
        # supervisor: arm the self-healing plan supervisor inside the
        # workers (resilience.supervisor posture string/'1') AND the
        # coordinated-reshape watch on this supervision loop — a
        # rank-0 worker's swap request restarts the whole cluster
        # once, free of the max_restarts budget.
        self.supervisor = supervisor
        self.plan = (plan if isinstance(plan, FaultPlan)
                     else FaultPlan(**plan) if isinstance(plan, dict)
                     else plan or FaultPlan(seed=0))
        self.steps = int(steps)
        self.workdir = workdir or tempfile.mkdtemp(prefix='chaos_cluster_')
        self.max_restarts = max_restarts
        self.save_every = save_every
        self.collective_timeout_s = collective_timeout_s
        self.barrier_timeout_s = barrier_timeout_s
        self.watchdog = watchdog
        self.worker_argv = worker_argv
        self.deadline_s = deadline_s
        self.jax_distributed = jax_distributed
        # an optional coordinator-side engine (callers injecting
        # supervisor-level faults); run() owns its teardown
        self.engine = engine
        self.extra_env = dict(extra_env or {})

    def _default_worker(self):
        import sys
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        return [sys.executable,
                os.path.join(repo, 'tools', 'soak_run.py'), '--worker']

    def _worker_env(self):
        import sys
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env.pop('PALLAS_AXON_POOL_IPS', None)
        env.update({
            'JAX_PLATFORMS': 'cpu',
            'PYTHONPATH': repo + os.pathsep + env.get('PYTHONPATH', ''),
            'PADDLE_TPU_KV': 'file:' + os.path.join(self.workdir, 'kv'),
            'PADDLE_TRAINERS_NUM': str(self.procs),
            'PADDLE_TPU_CHAOS_PLAN': self.plan.to_json(),
            'PADDLE_TPU_CHAOS_STEPS': str(self.steps),
            'PADDLE_TPU_CHAOS_DIR': self.workdir,
            'PADDLE_TPU_SOAK_SAVE_EVERY': str(self.save_every),
            'PADDLE_TPU_SOAK_COLLECTIVE_TIMEOUT':
                str(self.collective_timeout_s),
            'PADDLE_TPU_SOAK_BARRIER_TIMEOUT':
                str(self.barrier_timeout_s),
            'PADDLE_TPU_SOAK_JAXDIST':
                '1' if self.jax_distributed else '0',
            'PADDLE_TPU_WATCHDOG': self.watchdog or '0',
            'PADDLE_TPU_MIN_PREEMPT_UPTIME': '0',
        })
        if self.cluster_stats:
            env['PADDLE_TPU_CLUSTER_STATS'] = str(
                self.cluster_stats_interval)
        if self.supervisor:
            env['PADDLE_TPU_SUPERVISOR'] = (
                '1' if self.supervisor is True else str(self.supervisor))
        if self.jax_distributed:
            import socket
            s = socket.socket()
            s.bind(('127.0.0.1', 0))
            port = s.getsockname()[1]
            s.close()
            env['PADDLE_TPU_SOAK_COORD'] = f'127.0.0.1:{port}'
        env.update({k: str(v) for k, v in self.extra_env.items()})
        return env

    def run(self):
        """Supervise one full chaos soak; returns the report dict
        (ok, violations, injected sequence, incarnations, finals)."""
        from ..distributed import elastic
        os.makedirs(os.path.join(self.workdir, 'kv'), exist_ok=True)
        cmd = list(self.worker_argv or self._default_worker())
        t0 = time.time()
        supervisor_events = []
        exit_codes = {'preempt': [], 'exit': [], 'watchdog': []}

        def on_event(kind, t):
            supervisor_events.append((kind, t.rank))
            rc = t.proc.returncode if t.proc else None
            if kind in exit_codes and rc is not None:
                exit_codes[kind].append(rc)

        procs = elastic.start_local_trainers(
            [cmd] * self.procs, envs=self._worker_env(),
            log_dir=os.path.join(self.workdir, 'logs'))
        try:
            rc = elastic.watch_local_trainers(
                procs, max_restarts=self.max_restarts, poll=0.05,
                min_preempt_uptime=0.0, on_event=on_event,
                restart_backoff=self.restart_backoff,
                restart_backoff_max=self.restart_backoff_max,
                deadline=self.deadline_s,
                reshape_dir=self.workdir if self.supervisor else None)
        finally:
            elastic.terminate_local_procs(procs, grace=2.0)
            if self.engine is not None:
                # mirror of the PR-5 reverse-order teardown fix for the
                # collective seam class: a worker SIGKILLed mid-plan
                # must not leave the coordinator's transport patched
                self.engine.deactivate()
        duration = time.time() - t0

        events = load_run_events(self.workdir)
        injected = [e for e in events
                    if e.get('kind') == 'fault_injected']
        restarts = max((p.restarts for p in procs), default=0)
        violations = check_invariants(
            os.path.join(self.workdir, 'ckpt'), events=events,
            max_restarts=self.max_restarts, restarts=restarts,
            preempt_codes=exit_codes['preempt'], final_rc=rc,
            duration_s=duration, deadline_s=self.deadline_s)
        finals = self._load_finals()
        if rc == 0:
            if len(finals) != self.procs:
                violations.append(
                    f'only {sorted(finals)} of {self.procs} ranks '
                    'wrote a final state')
            elif len({json.dumps(v['final_w']) for v in
                      finals.values()}) > 1:
                violations.append(
                    'ranks disagree on the final state — a collective '
                    'fault leaked into the arithmetic')
        return {
            'ok': not violations,
            'violations': violations,
            'plan': json.loads(self.plan.to_json()),
            'procs': self.procs,
            'steps': self.steps,
            'rc': rc,
            'injected': [{k: e.get(k) for k in
                          ('fault', 'step', 'path', 'seq', 'errno',
                           'op', 'tag', 'rank')
                          if e.get(k) is not None} for e in injected],
            'incarnations': {p.rank: 1 + p.restarts + p.preemptions
                             + p.reshapes for p in procs},
            'failure_restarts': {p.rank: p.restarts for p in procs},
            'preemptions': {p.rank: p.preemptions for p in procs},
            'reshapes': {p.rank: p.reshapes for p in procs},
            'preempt_exit_codes': exit_codes['preempt'],
            'watchdog_exit_codes': exit_codes['watchdog'],
            'supervisor_events': supervisor_events,
            'duration_s': round(duration, 2),
            'finals': finals,
            'workdir': self.workdir,
            'events': len(events),
            'cluster_port_file': (self.cluster_port_file
                                  if self.cluster_stats else None),
        }

    @property
    def cluster_port_file(self):
        """Where rank 0's aggregator publishes its bound HTTP port
        (written by the worker once the MetricsServer is up)."""
        return os.path.join(self.workdir, 'cluster_port.json')

    def _load_finals(self):
        out = {}
        for r in range(self.procs):
            p = os.path.join(self.workdir, f'out_r{r}.json')
            try:
                with open(p) as f:
                    out[r] = json.load(f)
            except (OSError, ValueError):
                continue
        return out


def load_run_events(workdir):
    """Every telemetry event of a supervised run under `workdir`:
    streamed JSONL plus the event rings of any flight-recorder dumps
    (a SIGKILLed or watchdog-killed incarnation's last moments only
    survive in its pre-kill dump).  Deduped and wall-clock ordered —
    the input to check_invariants(events=...)."""
    import glob
    events = []
    for f in sorted(glob.glob(os.path.join(
            workdir, '**', 'telemetry-*.jsonl'), recursive=True)):
        with open(f) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue        # torn final line of a dead worker
                if isinstance(rec, dict) and 'kind' in rec:
                    events.append(rec)
    for f in sorted(glob.glob(os.path.join(
            workdir, '**', 'flightrec-*.json'), recursive=True)):
        try:
            with open(f) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        rank = doc.get('rank', 0)
        for rec in doc.get('events', []):
            if isinstance(rec, dict) and 'kind' in rec:
                rec = dict(rec)
                rec.setdefault('rank', rank)
                events.append(rec)
    # an event both streamed and ring-dumped collapses to one, and the
    # merged stream is replayed in wall-clock order
    seen, out = set(), []
    for e in events:
        k = (e.get('ts'), e.get('t'), e.get('kind'), e.get('rank', 0))
        if k in seen:
            continue
        seen.add(k)
        out.append(e)
    out.sort(key=lambda e: e.get('ts') or 0)
    return out
