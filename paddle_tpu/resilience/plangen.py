"""Property-based chaos plan generation + failing-plan shrinking.

Hand-written FaultPlans prove the failure modes someone thought of.
Long soaks need the other kind: arbitrary LEGAL compositions of faults
(seeded, so any failure replays exactly), run against the invariant
set I1-I7 until something breaks — and when it does, a plan of a dozen
interleaved faults is useless as a bug report.  This module does both
halves:

* :func:`generate_plan` — a seeded generator that composes faults
  respecting each seam's PRECONDITIONS (a shard-corruption fault needs
  a checkpoint to exist, collective faults need >1 process, process
  faults must land inside the step range, a hang must outlast the
  collective timeout so it is a hang and not a delay).  A `require`
  set guarantees coverage classes (the soak acceptance gate wants at
  least one hung collective, one killed worker, one torn checkpoint
  in every default soak).  Same (seed, steps, procs) => the identical
  plan, fault for fault.
* :func:`shrink` — delta-debugging over the fault list: greedily drop
  halves, then single faults, while the failure predicate keeps
  failing; the fixed point is a minimal reproducer.
* :func:`emit_regression` — write the shrunk plan as a ready-to-commit
  pytest case (slow-marked) so the reproducer survives the incident.

tools/soak_run.py drives all three.
"""
import hashlib
import json
import random

from .chaos import (Fault, FaultPlan, COLLECTIVE_FAULT_KINDS,
                    SERVING_FAULT_KINDS)

__all__ = ['GENERATABLE_KINDS', 'OPTIN_KINDS', 'generate_plan',
           'legal', 'shrink', 'plan_fingerprint', 'emit_regression']

# kinds the generator composes.  nan_grads is excluded (the soak
# workload has no gradient path), delete/stale_heartbeat are excluded
# (the multi-process topology heartbeats through the KV store, not the
# legacy file).
GENERATABLE_KINDS = (
    'sigkill', 'sigterm', 'torn_write', 'drop_commit', 'io_error',
    'slow_io', 'slow_rank',
) + COLLECTIVE_FAULT_KINDS

# opt-in coverage-class kinds: legal() admits them but the DEFAULT
# pool never draws them — growing GENERATABLE_KINDS would shift every
# seeded draw stream and silently break golden-pinned plans.  'drift'
# is the supervisor-migration class (generate_plan(supervisor=True));
# 'collective_skip' is the SPMD-contract-violation class the
# collective flight recorder attributes (pass kinds= explicitly);
# the SERVING_FAULT_KINDS are the fleet-drill class (bench.py
# --frontdoor-smoke / ServingFaultInjector) — their drills have no
# training step, so their clock is stream progress (after_tokens).
OPTIN_KINDS = ('drift', 'collective_skip') + SERVING_FAULT_KINDS


def legal(fault, steps, procs, save_every=2, hang_min_s=None):
    """True iff `fault` respects its seam's preconditions for a soak
    of `steps` steps over `procs` processes.  The generator only emits
    legal faults; the shrinker preserves legality by construction
    (removing faults cannot violate a precondition)."""
    f = fault if isinstance(fault, Fault) else Fault.from_dict(fault)
    if f.kind not in GENERATABLE_KINDS + OPTIN_KINDS:
        return False
    if f.rank is not None and not (0 <= int(f.rank) < procs):
        return False
    in_range = f.at_step is None or (2 <= f.at_step <= steps)
    if f.kind in SERVING_FAULT_KINDS:
        # serving faults are clocked by stream progress, not steps:
        # need an after_tokens mark and a bounded count (an unbounded
        # replica_kill would murder every promoted spare in turn);
        # `procs` is the replica count for replica-addressed kinds
        if f.after_tokens is None or f.after_tokens < 0 \
                or f.count is None:
            return False
        if f.kind in ('replica_kill', 'replica_hang'):
            return f.rank is None or 0 <= int(f.rank) < procs
        return True
    if f.kind == 'drift':
        # the synthetic sensor edge must land on rank 0 — the plan
        # supervisor actuator subscribes to rank 0's recorder; drift
        # injected anywhere else never reaches it
        return in_range and f.at_step is not None and f.rank == 0
    if f.kind in ('sigkill', 'sigterm'):
        # process faults fire from the step loop: need a live step, an
        # addressed rank (an unaddressed kill would fire on EVERY rank
        # — that is cluster murder, not a fault), and a step AFTER the
        # first save so the restart exercises restore, not a cold
        # start
        return (in_range and f.at_step is not None
                and f.rank is not None and f.at_step > save_every)
    if f.kind == 'slow_rank':
        return in_range and f.at_step is not None and f.rank is not None
    if f.kind == 'collective_skip':
        # same wire preconditions as the COLLECTIVE_FAULT_KINDS seams
        # plus a bounded count: an unbounded skip would re-fire on
        # every post-restart replay and the run would never converge
        return (procs >= 2 and f.rank is not None and in_range
                and f.at_step is not None and f.count is not None)
    if f.kind in COLLECTIVE_FAULT_KINDS:
        # collective faults need a wire: >1 process, an addressed rank
        # (the sequence must be attributable), a step inside the range;
        # a hang must outlast the collective timeout or it is a delay
        if procs < 2 or f.rank is None or not in_range \
                or f.at_step is None:
            return False
        if f.kind == 'collective_hang' and hang_min_s is not None \
                and f.delay_s < hang_min_s:
            return False
        return True
    if f.kind in ('torn_write', 'drop_commit'):
        # checkpoint-seam faults need a save to exist: the step they
        # target must be a save step
        if f.kind == 'drop_commit':
            return f.at_step is not None and in_range \
                and f.at_step % save_every == 0
        return f.path is not None and f.path.startswith('step_')
    if f.kind in ('io_error', 'slow_io'):
        return f.prob is not None and 0 < f.prob <= 1
    return in_range


def _make(kind, rng, steps, procs, save_every, hang_s):
    """One legal fault of `kind`, drawn from the plan RNG."""
    step = rng.randrange(2, max(3, steps + 1))
    rank = rng.randrange(procs)
    if kind in ('sigkill', 'sigterm'):
        lo = min(save_every + 1, steps)
        return Fault(kind, at_step=rng.randrange(lo, steps + 1),
                     rank=rank)
    if kind == 'drift':
        lo = min(save_every + 1, steps)
        return Fault(kind, at_step=rng.randrange(lo, steps + 1),
                     rank=0, op='all-reduce',
                     us_ratio=round(rng.uniform(6.0, 12.0), 2))
    if kind == 'slow_rank':
        return Fault(kind, at_step=step, rank=rank,
                     delay_s=round(rng.uniform(0.2, 0.8), 3))
    if kind == 'collective_hang':
        return Fault(kind, at_step=step, rank=rank, delay_s=hang_s)
    if kind == 'collective_delay':
        return Fault(kind, at_step=step, rank=rank,
                     delay_s=round(rng.uniform(0.05, 0.3), 3))
    if kind in ('collective_drop', 'collective_corrupt'):
        return Fault(kind, at_step=step, rank=rank)
    if kind == 'collective_skip':
        return Fault(kind, at_step=step, rank=rank, count=1)
    if kind == 'torn_write':
        save_step = save_every * rng.randrange(
            1, max(2, steps // save_every + 1))
        # bounded: tear one save attempt (shard + intent) and let the
        # replayed save commit — an unbounded tear would also make the
        # injected sequence depend on the incarnation count
        return Fault(kind, path=f'step_{save_step}', count=2)
    if kind == 'drop_commit':
        save_step = save_every * rng.randrange(
            1, max(2, steps // save_every + 1))
        return Fault(kind, at_step=save_step)
    if kind == 'io_error':
        return Fault(kind, prob=round(rng.uniform(0.05, 0.2), 3),
                     count=2, path='_PADDLE_2PC',
                     errno_name=rng.choice(('EIO', 'ENOSPC')))
    if kind == 'slow_io':
        return Fault(kind, prob=round(rng.uniform(0.1, 0.3), 3),
                     count=3, delay_s=0.05)
    raise ValueError(kind)


def generate_plan(seed, steps, procs, n_faults=6,
                  require=('collective_hang', 'sigkill', 'torn_write'),
                  save_every=2, hang_s=60.0, kinds=None,
                  name=None, quant_wire=False, supervisor=False):
    """A seeded, legal FaultPlan for one soak.

    `require` kinds are always present (coverage classes the soak
    gate demands); the rest are drawn from `kinds` (default
    GENERATABLE_KINDS, minus requirements already satisfied).  Pure in
    (seed, steps, procs, knobs): the same call composes the identical
    plan, which is what makes a soak failure replayable before it is
    even shrunk.

    ``quant_wire`` is the quantized-wire COVERAGE CLASS: the plan is
    tagged ``+qwire`` and tools/soak_run.py runs the workers' host
    all-reduces on the block-scaled int8 wire
    (``HostCollectives.allreduce(quant='int8')``), so every injected
    fault — corrupt-after-crc, SIGKILL mid-allreduce, hangs — drives
    the QUANTIZED payload path.  It changes no fault draw: the same
    seed composes the identical fault sequence either way (so a
    quantized soak failure bisects cleanly against its full-width
    twin).

    ``supervisor`` is the supervisor-MIGRATION coverage class (plan
    tagged ``+sup``): an injected ``drift`` fault on rank 0 — the
    synthetic sensor edge the plan supervisor actuates on — plus a
    SIGKILL landing ONE STEP after it, i.e. inside the window where
    the reshape request is written but the coordinated restart has
    not completed.  The gate it feeds: the request survives the
    crash, the cluster reshapes exactly once, no max_restarts burn,
    finals stay bit-exact.  The extra draws happen AFTER the require
    loop and only when armed, so ``supervisor=False`` plans (and
    their golden fingerprints) are byte-identical to before."""
    # int-folded so the draw stream is pure in (seed, steps, procs)
    # (random.Random rejects tuples)
    rng = random.Random(int(seed) * 1_000_003
                        + int(steps) * 1_009 + int(procs))
    pool = tuple(kinds or GENERATABLE_KINDS)
    faults = []
    seen = set()

    def admit(f):
        key = (f.kind, f.at_step, f.rank, f.path, f.op)
        if key in seen:
            return False
        if not legal(f, steps, procs, save_every=save_every):
            return False
        seen.add(key)
        faults.append(f)
        return True

    for kind in require:
        for _ in range(64):
            if admit(_make(kind, rng, steps, procs, save_every,
                           hang_s)):
                break
        else:
            raise RuntimeError(
                f'could not compose a legal {kind!r} fault for '
                f'steps={steps} procs={procs}')
    if supervisor:
        drift = None
        for _ in range(64):
            f = _make('drift', rng, steps, procs, save_every, hang_s)
            if admit(f):
                drift = f
                break
        if drift is None:
            raise RuntimeError(
                f'could not compose a legal drift fault for '
                f'steps={steps} procs={procs}')
        # the mid-migration crash: one step after the sensor edge
        admit(Fault('sigkill', rank=rng.randrange(procs),
                    at_step=min(steps, drift.at_step + 1)))
    while len(faults) < n_faults:
        kind = pool[rng.randrange(len(pool))]
        for _ in range(64):
            if admit(_make(kind, rng, steps, procs, save_every,
                           hang_s)):
                break
        else:
            break       # pool exhausted at this size; plan stays legal
    base = name or f'soak-{seed}'
    if quant_wire:
        base += '+qwire'
    if supervisor:
        base += '+sup'
    return FaultPlan(seed=seed, faults=faults, name=base)


def plan_fingerprint(plan):
    """Stable sha256 of a plan's canonical JSON — what the golden
    fixture pins so neither the generator nor the shrinker can drift
    silently."""
    return hashlib.sha256(plan.to_json().encode('utf-8')).hexdigest()


def shrink(plan, failing, max_runs=64, log=None):
    """Minimize a failing plan: returns (shrunk_plan, runs_used).

    `failing(FaultPlan) -> bool` is the oracle (True = still fails —
    for a soak, "some invariant still violated").  Delta debugging:
    drop contiguous halves first (cheap big cuts), then single faults,
    to a fixed point.  The oracle's own determinism comes from the
    plan seed — the same candidate plan replays the same run.  Caller
    note: each oracle call may be a full cluster run; `max_runs`
    bounds the bill."""
    faults = list(plan.faults)
    runs = 0

    def plan_with(fs):
        return FaultPlan(
            seed=plan.seed,
            faults=[Fault.from_dict(f.to_dict()) for f in fs],
            name=f'{plan.name or "plan"}-shrunk')

    def still_fails(fs):
        nonlocal runs
        runs += 1
        ok = failing(plan_with(fs))
        if log:
            log(f'shrink probe {runs}: {len(fs)} fault(s) -> '
                f'{"still fails" if ok else "passes"}')
        return ok

    if not still_fails(faults):
        raise ValueError('shrink() needs a failing plan: the oracle '
                         'passed on the full plan')
    # big cuts first (halves, quarters, ...), then single faults to a
    # fixed point
    chunk = max(1, len(faults) // 2)
    while runs < max_runs:
        i, progressed = 0, False
        while i < len(faults) and runs < max_runs:
            cand = faults[:i] + faults[i + chunk:]
            if cand and still_fails(cand):
                faults = cand
                progressed = True
            else:
                i += chunk
        if chunk > 1:
            chunk //= 2
        elif not progressed:
            break
    return plan_with(faults), runs


REGRESSION_TEMPLATE = '''\
"""Auto-generated chaos regression (tools/soak_run.py --emit-regression).

A property-based soak found an invariant violation; this is the
SHRUNK minimal reproducer.  Same seed => same injected sequence.
Violated: {violations}
"""
import json

import pytest

from paddle_tpu.resilience.chaos import ChaosCluster, FaultPlan

PLAN_JSON = r"""{plan_json}"""


@pytest.mark.slow
@pytest.mark.faultinject
def test_shrunk_chaos_plan_regression(tmp_path):
    plan = FaultPlan.from_json(PLAN_JSON)
    report = ChaosCluster(procs={procs}, plan=plan, steps={steps},
                          workdir=str(tmp_path / 'soak'),
                          collective_timeout_s={collective_timeout_s},
                          deadline_s={deadline_s}).run()
    assert report['ok'], json.dumps(report['violations'], indent=1)
'''


def emit_regression(plan, path, procs, steps, violations=(),
                    collective_timeout_s=15.0, deadline_s=240.0):
    """Write the shrunk plan as a ready-to-commit pytest case (slow-
    marked: it spins a real multi-process cluster).  The test asserts
    the invariants HOLD — committing it pins the fix."""
    text = REGRESSION_TEMPLATE.format(
        plan_json=plan.to_json(),
        procs=int(procs), steps=int(steps),
        collective_timeout_s=float(collective_timeout_s),
        deadline_s=float(deadline_s),
        violations='; '.join(str(v) for v in violations)[:400]
        or '(see soak report)')
    with open(path, 'w') as f:
        f.write(text)
    return path
