"""Shared retry with exponential backoff + jitter.

One decorator instead of per-module ad-hoc loops, so every transient
host-side failure (shared-fs read, checkpoint commit, cache resolve)
gets the same policy: bounded attempts, exponential backoff, decorrelated
jitter (full-jitter — concurrent hosts retrying a shared filesystem
must not stampede in lockstep).
"""
import functools
import random
import time

__all__ = ['retry']


def retry(fn=None, *, retries=3, backoff=0.1, max_backoff=30.0,
          jitter=True, retry_on=(OSError,), on_retry=None,
          sleep=time.sleep):
    """Retry `fn` up to `retries` extra times on `retry_on` exceptions.

    Usable three ways::

        @retry
        def f(...): ...

        @retry(retries=5, retry_on=(OSError, TimeoutError))
        def g(...): ...

        retry(lambda: flaky(), retries=2)()   # ad-hoc call site

    Attempt k (0-based) sleeps `backoff * 2**k`, capped at
    `max_backoff`; with `jitter` the sleep is uniform in (0, that] so
    a fleet of restarted hosts decorrelates.  The final failure
    re-raises the last exception unchanged.  `on_retry(exc, attempt)`
    observes each failed attempt (loggers, tests).
    """
    if fn is None:
        return functools.partial(
            retry, retries=retries, backoff=backoff,
            max_backoff=max_backoff, jitter=jitter, retry_on=retry_on,
            on_retry=on_retry, sleep=sleep)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        for attempt in range(retries + 1):
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                if attempt >= retries:
                    raise
                if on_retry is not None:
                    on_retry(e, attempt)
                delay = min(backoff * (2 ** attempt), max_backoff)
                if jitter:
                    delay = random.uniform(0, delay) or delay * 0.5
                sleep(delay)

    return wrapper
