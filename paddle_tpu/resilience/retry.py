"""Shared retry with exponential backoff + jitter.

One decorator instead of per-module ad-hoc loops, so every transient
host-side failure (shared-fs read, checkpoint commit, cache resolve)
gets the same policy: bounded attempts, exponential backoff, decorrelated
jitter (full-jitter — concurrent hosts retrying a shared filesystem
must not stampede in lockstep), and an optional total-wall-clock
`deadline` so a barrier wait can never retry forever.

Every retried attempt lands in the run's telemetry stream (a ``retry``
event + ``retry.count`` counter) unless the caller supplies its own
`on_retry` observer — silent retries hide exactly the flaky-fs
episodes a post-mortem needs to see.
"""
import functools
import random
import time

__all__ = ['retry']


def _default_on_retry(fn, exc, attempt, delay, clamped_from=None,
                      deadline=None):
    """The default observer: a telemetry ``retry`` event + counter.
    Never raises — retrying is the priority, not recording it."""
    try:
        from .. import telemetry
        extra = {}
        if clamped_from is not None:
            # the watchdog's collective budget shortened this loop's
            # deadline — recorded so a post-mortem can tell "retry gave
            # up early" from "retry exhausted its own deadline"
            extra['deadline_s'] = round(deadline, 6)
            extra['clamped_from_s'] = (
                None if clamped_from == float('inf')
                else round(clamped_from, 6))
        telemetry.event('retry', fn=getattr(fn, '__name__', repr(fn)),
                        attempt=attempt, delay_s=round(delay, 6),
                        error=repr(exc)[:200], **extra)
        telemetry.add('retry.count')
    except Exception:       # pragma: no cover - defensive
        pass


def retry(fn=None, *, retries=3, backoff=0.1, max_backoff=30.0,
          jitter=True, retry_on=(OSError,), on_retry=None,
          sleep=time.sleep, deadline=None):
    """Retry `fn` up to `retries` extra times on `retry_on` exceptions.

    Usable three ways::

        @retry
        def f(...): ...

        @retry(retries=5, retry_on=(OSError, TimeoutError))
        def g(...): ...

        retry(lambda: flaky(), retries=2)()   # ad-hoc call site

    Attempt k (0-based) sleeps `backoff * 2**k`, capped at
    `max_backoff`; with `jitter` the sleep is uniform in (0, that] so
    a fleet of restarted hosts decorrelates.  The final failure
    re-raises the last exception unchanged.  `on_retry(exc, attempt)`
    observes each failed attempt (loggers, tests); when omitted, each
    retry emits a telemetry ``retry`` event instead.

    `deadline` caps TOTAL wall clock: when the elapsed time plus the
    next sleep would cross it, the last exception re-raises instead of
    sleeping — the cross-host commit barrier leans on this (a dead
    host must become a timeout, not an infinite wait).

    When the call runs inside a watchdog collective budget
    (resilience.watchdog.collective_budget), the effective deadline is
    CLAMPED to the remaining budget — a retry loop nested inside a
    collective deadline must not outlive it (a generous
    `deadline=120` on a shared-fs read would otherwise keep a rank
    alive-but-silent long past the point its peers timed out and
    aborted).  The clamp is recorded on the emitted ``retry`` events
    (`deadline_s` + `clamped_from_s`).
    """
    if fn is None:
        return functools.partial(
            retry, retries=retries, backoff=backoff,
            max_backoff=max_backoff, jitter=jitter, retry_on=retry_on,
            on_retry=on_retry, sleep=sleep, deadline=deadline)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        start = time.monotonic()
        eff_deadline, clamped_from = deadline, None
        try:
            from .watchdog import remaining_budget
            rem = remaining_budget()
        except Exception:       # pragma: no cover - defensive
            rem = None
        if rem is not None and (eff_deadline is None
                                or rem < eff_deadline):
            clamped_from = (float('inf') if eff_deadline is None
                            else eff_deadline)
            eff_deadline = rem
        for attempt in range(retries + 1):
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                if attempt >= retries:
                    raise
                # exponent clamped: deadline-capped barrier waits run
                # thousands of attempts, and 2**attempt as a bare int
                # overflows float conversion past ~2**1024
                delay = min(backoff * (2 ** min(attempt, 60)),
                            max_backoff)
                if jitter:
                    delay = random.uniform(0, delay) or delay * 0.5
                if eff_deadline is not None and \
                        time.monotonic() - start + delay > eff_deadline:
                    raise
                if on_retry is not None:
                    on_retry(e, attempt)
                else:
                    _default_on_retry(fn, e, attempt, delay,
                                      clamped_from=clamped_from,
                                      deadline=eff_deadline)
                sleep(delay)

    return wrapper
