"""L1/L2 weight decay (reference: python/paddle/regularizer.py)."""

__all__ = ['L1Decay', 'L2Decay']


class L1Decay:
    _mode = 'l1'

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


class L2Decay:
    _mode = 'l2'

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


# legacy 1.x spellings (reference fluid/regularizer.py)
L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
