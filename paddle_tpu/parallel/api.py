"""Sharding annotation helpers.

The contract: layers declare per-parameter axis specs in
`_param_shardings` as tuples of mesh-axis names (None = replicated dim,
'...' = leading dims replicated); the engine resolves them to
jax.sharding.NamedSharding over the installed mesh.  Axes absent from
the mesh degrade to replication, so the same model runs 1-chip or
many-chip unchanged — the TPU counterpart of the reference running the
same Program with or without fleet meta_optimizers.
"""
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..distributed import env as _env

__all__ = ['maybe_shard', 'collect_param_shardings', 'named_sharding',
           'make_spec']


def make_spec(spec, ndim, mesh=None):
    """spec tuple → PartitionSpec, dropping axes the mesh lacks or that
    would not divide evenly is left to XLA (it pads)."""
    mesh = mesh or _env.get_mesh()
    if spec is None:
        return P()
    axis_names = set(mesh.axis_names) if mesh is not None else set()
    parts = []
    if spec and spec[0] == '...':
        tail = list(spec[1:])
        parts = [None] * (ndim - len(tail)) + tail
    else:
        parts = list(spec) + [None] * (ndim - len(spec))
    parts = [p if (p in axis_names and _axis_size(mesh, p) > 1) or p is None
             else None for p in parts]
    return P(*parts)


def _axis_size(mesh, name):
    try:
        return mesh.shape[name]
    except Exception:
        return 1


def named_sharding(spec, ndim, mesh=None):
    mesh = mesh or _env.get_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, make_spec(spec, ndim, mesh))


def maybe_shard(x, spec):
    """with_sharding_constraint when tracing under an installed mesh;
    identity otherwise (eager single-chip)."""
    mesh = _env.get_mesh()
    val = x.value if isinstance(x, Tensor) else x
    if mesh is None or not isinstance(val, jax.core.Tracer):
        return x
    s = named_sharding(spec, val.ndim, mesh)
    out = jax.lax.with_sharding_constraint(val, s)
    if isinstance(x, Tensor):
        return Tensor._from_value(out, stop_gradient=x.stop_gradient)
    return out


def collect_param_shardings(layer):
    """Walk the Layer tree; return {qualified_param_name: spec tuple}
    using each sublayer's `_param_shardings` (missing → replicated)."""
    out = {}

    def visit(l, prefix):
        shardings = getattr(l, '_param_shardings', {}) or {}
        for name, _p in l._parameters.items():
            q = prefix + name if prefix else name
            out[q] = shardings.get(name)
        for cname, child in l._sub_layers.items():
            visit(child, f"{prefix}{cname}.")

    visit(layer, '')
    return out
