"""Quantized wire — EQuARX-style block-scaled int8 collectives.

Every distributed byte the parallel engines move today is full width:
the DP grad all-reduce, localsgd's model average and the host
transport all ship f32/bf16.  EQuARX (arxiv 2506.17615) shows a
block-scaled int8 all-reduce delivers 2-4x wire reduction with
negligible quality loss.  XLA gives no hook into the ring hops of its
own all-reduce, so the software decomposition is explicit and
dtype-aware:

    all-reduce(g)  =  quantize -> all-to-all(int8 + block scales)
                      -> dequant + local sum          [reduce-scatter]
                      -> quantize -> all-gather(int8) -> dequant

Both halves move ~(n-1)/n · S int8 bytes (plus f32 scales, one per
``block`` elements), so the wire cost is the classic 2·S·(n-1)/n with
1-byte elements: 4x below f32, 2x below bf16.  The *sum* itself runs
in f32 on each owner shard — only representation on the wire is
quantized.  ``master_accum=True`` is the escape hatch for
numerically-delicate runs: the reduce half stays a full-width
``psum_scatter`` (the SUM is exact) and only the gather half
quantizes, ~1.6x total reduction.

Rounding is stochastic by default (floor(x/s + u), u ~ U[0,1)) so
quantization error stays zero-mean across steps — the key derives
IN-MODULE from the traced step counter (``step_key``), never from the
host rng stream: the quantized step adds no host randomness and no
host sync (transfer-guard proven by test).

The pure core (``quantize_blocks`` / ``dequantize_blocks``) round
trips bit-stably: values already on a block's grid re-quantize to the
identical int8 payload under the same scales, and the same key
replays the same stochastic draw — which is what makes quantized
elastic restarts replayable.

Consumers: ``ParallelTrainer(quant_collectives='int8')`` (DP grad
sync), ``LocalSGDTrainer(quant_collectives=...)`` (model averaging),
``HostCollectives.allreduce(..., quant='int8')`` (host wire — numpy
twin of the same block format, scales riding the crc frame), and the
``PADDLE_TPU_QUANT_COLLECTIVES`` env (default OFF; explicit False
beats env, same posture as profile/watchdog/fused).
"""
import os

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['QuantCollectiveConfig', 'resolve_quant_collectives',
           'quantize_blocks', 'dequantize_blocks', 'step_key',
           'quantized_allreduce', 'quantized_allreduce_tree',
           'wire_factor', 'QUANT_ENV', 'DEFAULT_BLOCK',
           'DEFAULT_MIN_BYTES']

QUANT_ENV = 'PADDLE_TPU_QUANT_COLLECTIVES'

DEFAULT_BLOCK = 256
# below this many payload bytes the per-block scale overhead and the
# extra dispatch latency beat the byte savings — small messages ship
# full width (see MIGRATION: "when NOT to quantize")
DEFAULT_MIN_BYTES = 64 << 10
_QMAX = 127.0
# the in-module stochastic-rounding seed: folded with the traced step
# counter so the draw is pure in (config, step) — no host randomness
_DEFAULT_SEED = 0x0EA82C


class QuantCollectiveConfig:
    """Declared wire-quantization posture for one engine.

    dtype        wire dtype; 'int8' is the implemented device wire
                 (packed int4 exists on the PTQ weight path, not the
                 collective wire — 4-bit grads diverge).
    block        elements per abs-max scale block.
    stochastic   stochastic rounding (keyed off the step counter);
                 False = round-to-nearest (deterministic runs).
    master_accum full-width reduce half (exact sum), quantized gather
                 half only.
    min_bytes    full-width fallback threshold for the fused message.
    seed         base of the in-module rounding key stream.
    """

    def __init__(self, dtype='int8', block=DEFAULT_BLOCK,
                 stochastic=True, master_accum=False,
                 min_bytes=DEFAULT_MIN_BYTES, seed=_DEFAULT_SEED):
        if dtype != 'int8':
            raise ValueError(
                f'quant_collectives wire dtype {dtype!r}: only '
                "'int8' is supported on the collective wire")
        self.dtype = dtype
        self.block = max(1, int(block))
        self.stochastic = bool(stochastic)
        self.master_accum = bool(master_accum)
        self.min_bytes = max(0, int(min_bytes))
        self.seed = int(seed)

    def __repr__(self):
        return (f'QuantCollectiveConfig(dtype={self.dtype!r}, '
                f'block={self.block}, stochastic={self.stochastic}, '
                f'master_accum={self.master_accum}, '
                f'min_bytes={self.min_bytes})')

    def __eq__(self, other):
        return isinstance(other, QuantCollectiveConfig) and \
            vars(self) == vars(other)


_TRUE = ('1', 'true', 'yes', 'on')
_FALSE = ('', '0', 'false', 'no', 'off', 'none')


def _parse_env(spec):
    """'int8' / '1' / 'int8,block=128,master_accum=1,stochastic=0'."""
    spec = spec.strip()
    if spec.lower() in _FALSE:
        return None
    kw = {}
    for part in (p.strip() for p in spec.split(',')):
        if not part:
            continue
        if '=' not in part:
            if part.lower() not in _TRUE:
                kw['dtype'] = part
            continue
        k, v = part.split('=', 1)
        k = k.strip()
        if k in ('block', 'min_bytes', 'seed'):
            kw[k] = int(v)
        elif k in ('stochastic', 'master_accum'):
            kw[k] = v.strip().lower() in _TRUE
        elif k == 'dtype':
            kw[k] = v.strip()
        else:
            raise ValueError(
                f'{QUANT_ENV}: unknown knob {k!r} in {spec!r}')
    return QuantCollectiveConfig(**kw)


def resolve_quant_collectives(arg, env=None):
    """The quant_collectives= posture shared by every consumer:
    ``None`` -> the ``PADDLE_TPU_QUANT_COLLECTIVES`` env decides
    (unset = OFF); explicit ``False`` beats env; ``True``/'int8' ->
    defaults; a dict -> ``QuantCollectiveConfig(**dict)``; a config
    passes through.  Returns a config or None (off)."""
    if arg is False:
        return None
    if arg is None:
        spec = (env if env is not None
                else os.environ.get(QUANT_ENV, ''))
        if not spec:
            return None
        return _parse_env(spec)
    if arg is True:
        return QuantCollectiveConfig()
    if isinstance(arg, str):
        return _parse_env(arg)
    if isinstance(arg, dict):
        return QuantCollectiveConfig(**arg)
    if isinstance(arg, QuantCollectiveConfig):
        return arg
    raise TypeError(f'quant_collectives={arg!r}: expected None/bool/'
                    "str/'int8'/dict/QuantCollectiveConfig")


def wire_factor(cfg, elem_bytes=4):
    """Predicted payload-byte multiplier of this config's wire — ONE
    formula, owned by the cost model (costmodel.quant_wire_factor),
    so the planner's predictions and this helper can never drift."""
    from ..analysis.costmodel import quant_wire_factor
    return quant_wire_factor(elem_bytes, cfg.dtype, cfg.block)


def step_key(cfg, step_no):
    """The in-module stochastic-rounding key for one step: pure in
    (config seed, traced step counter).  Derived inside the compiled
    module — no host randomness, no draw from the model's rng stream
    (quantized and full-width runs see identical dropout)."""
    return jax.random.fold_in(
        jax.random.PRNGKey(cfg.seed),
        jnp.asarray(step_no, jnp.uint32))


# -- pure quantize/dequant core ----------------------------------------------

def _round(y, key):
    """Stochastic floor(y + u) when keyed, round-to-nearest otherwise.
    Grid values (y integral) are FIXED POINTS of both modes: u < 1
    never carries an exact integer across — the bit-stable-round-trip
    contract."""
    if key is None:
        return jnp.round(y)
    u = jax.random.uniform(key, y.shape, dtype=y.dtype)
    return jnp.floor(y + u)


def quantize_blocks(x, block=DEFAULT_BLOCK, key=None, scales=None):
    """Flat float vector -> (int8 [nb, block], f32 scales [nb]).

    ``x.size`` must divide by ``block`` (callers pad).  Scales are
    per-block abs-max / 127; pass ``scales=`` to re-quantize onto an
    existing grid (the round-trip identity: values of the form
    q·scale re-quantize to exactly q under the same scales).
    ``key`` arms stochastic rounding — same key, same draw."""
    xb = x.astype(jnp.float32).reshape(-1, block)
    if scales is None:
        scales = jnp.maximum(jnp.max(jnp.abs(xb), axis=1) / _QMAX,
                             jnp.float32(1e-30))
    q = _round(xb / scales[:, None], key)
    return (jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8),
            scales.astype(jnp.float32))


def dequantize_blocks(q, scales):
    """(int8 [nb, block], f32 [nb]) -> flat f32 [nb*block]."""
    return (q.astype(jnp.float32) * scales[:, None]).reshape(-1)


# -- the quantized all-reduce (shard_map interior) ---------------------------

def _keys(cfg, key, axis):
    """Per-phase, per-rank rounding keys (None when deterministic)."""
    if key is None or not cfg.stochastic:
        return None, None
    mine = jax.random.fold_in(key, lax.axis_index(axis))
    return jax.random.fold_in(mine, 0), jax.random.fold_in(mine, 1)


def quantized_allreduce(x, axis, *, n, cfg, key=None, op='sum'):
    """All-reduce one flat f32 vector across mesh axis ``axis`` with
    an int8 wire.  MUST run inside a shard_map region over ``axis``
    (``x`` is this device's local value; every rank returns the
    identical reduced vector).

    Decomposition: chunk rows per rank -> quantize -> all-to-all
    (int8 + scales) -> dequant + f32 sum of the owned chunk ->
    quantize -> all-gather (int8 + scales) -> dequant.  With
    ``cfg.master_accum`` the first half is a full-width psum_scatter
    instead (exact sum, quantized broadcast only)."""
    if op not in ('sum', 'mean'):
        raise ValueError(f'quantized allreduce op {op!r}')
    g = x.shape[0]
    block = cfg.block
    chunk = -(-g // (n * block)) * block        # block-aligned chunk
    xs = jnp.pad(x.astype(jnp.float32),
                 (0, n * chunk - g)).reshape(n, chunk)
    k1, k2 = _keys(cfg, key, axis)
    # cfg is a replicated QuantConfig (every rank constructs the same
    # one), so the branch predicate cannot disagree across ranks
    if cfg.master_accum:  # tpu-lint: disable=collective-order
        # exact f32 sum of the owned chunk; only the gather quantizes
        mine = lax.psum_scatter(xs, axis, scatter_dimension=0,
                                tiled=True).reshape(-1)
    else:
        q, s = quantize_blocks(xs.reshape(-1), block, key=k1)
        nb = chunk // block
        q_t = lax.all_to_all(q.reshape(n, chunk), axis,
                             split_axis=0, concat_axis=0, tiled=True)
        s_t = lax.all_to_all(s.reshape(n, nb), axis,
                             split_axis=0, concat_axis=0, tiled=True)
        # rows are now the n peers' versions of MY chunk: dequantize
        # each and sum in f32 — the master accumulation
        parts = (q_t.reshape(n, nb, block).astype(jnp.float32)
                 * s_t[:, :, None])
        mine = parts.sum(axis=0).reshape(-1)
    if op == 'mean':
        # scale BEFORE the second quantize so its grid matches the
        # final magnitudes
        mine = mine / n
    q_m, s_m = quantize_blocks(mine, block, key=k2)
    q_all = lax.all_gather(q_m.reshape(-1), axis, axis=0, tiled=False)
    s_all = lax.all_gather(s_m, axis, axis=0, tiled=False)
    full = (q_all.reshape(n, -1, block).astype(jnp.float32)
            * s_all[:, :, None]).reshape(-1)
    return full[:g]


def quantized_allreduce_tree(tree, axis, *, n, cfg, key=None,
                             op='sum'):
    """Tree-level quantized all-reduce: every leaf concatenates into
    ONE fused flat message (real DP fusion-bucket behavior — one
    collective pair, block efficiency on small leaves), reduced by
    :func:`quantized_allreduce`, then split back to leaf shapes and
    dtypes.  Messages under ``cfg.min_bytes`` ship full width
    (``lax.psum``/``pmean`` — scale overhead would win)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    total = sum(v.size for v in leaves)
    if total * 4 < cfg.min_bytes:
        red = lax.pmean if op == 'mean' else lax.psum
        return jax.tree_util.tree_unflatten(
            treedef, [red(v, axis) for v in leaves])
    flat = jnp.concatenate(
        [v.reshape(-1).astype(jnp.float32) for v in leaves])
    out = quantized_allreduce(flat, axis, n=n, cfg=cfg, key=key, op=op)
    got, off = [], 0
    for v in leaves:
        got.append(out[off:off + v.size].reshape(v.shape)
                   .astype(v.dtype))
        off += v.size
    return jax.tree_util.tree_unflatten(treedef, got)
