"""1F1B pipeline-parallel training engine over the `pp` mesh axis.

Reference analogue:
/root/reference/python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:43
(``PipelineParallel._forward_backward_pipeline``: the 1F1B schedule with
NCCL p2p send/recv between stage ranks) together with
meta_optimizers/pipeline_optimizer.py (program section cut).  TPU-native
redesign: instead of per-rank Python processes exchanging tensors, the
ENTIRE schedule — warmup forwards, steady-state 1F1B interleave,
cooldown backwards — is ONE ``lax.scan`` inside ONE ``shard_map`` over
the (pp, dp, tp) mesh; stage hand-offs are ``lax.ppermute`` ring hops
over ICI and the backward is hand-rolled with ``jax.vjp`` per tick.

Schedule (S stages, M microbatches, T = 2M + 2S - 2 ticks):

    forward  of microbatch m on stage s at tick  2m + s
    backward of microbatch m on stage s at tick  2m + 2S - 1 - s

F-ticks and B-ticks have opposite parity on every device, so each
device does at most one unit of work per tick and alternates F/B in
steady state — the 1F1B order.  A stage holds at most S - s in-flight
microbatch *inputs* (O(S) live activations, not GPipe's O(M)); the
backward tick recomputes the stage forward from the stashed input
(activation recompute, the reference's recompute+pipeline composition).

Non-homogeneous stages: ``first_fn`` (e.g. token+position embedding)
runs only on stage 0, ``last_fn`` (e.g. final LN + LM head + loss) only
on stage S-1, both gated by ``lax.cond`` on the pp coordinate; their
parameters travel in the ``shared`` pytree, replicated over pp, and
their gradients are psum'd over pp (so weights tied between first and
last stage — GPT's embedding/LM head — accumulate both contributions
for free).

Tensor-parallel composition: stage parameters may carry 'tp' in their
PartitionSpec; the stage function is then responsible for its own
``lax.psum(..., 'tp')`` after row-parallel matmuls (see
models/gpt_pipe.py).  Gradients of tp-*replicated* leaves are psum'd
over tp here, driven by whether each leaf's spec mentions the tp axis.
"""
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['pipeline_value_and_grad']


def _spec_mentions(spec, axis):
    for part in spec:
        if part == axis:
            return True
        if isinstance(part, (tuple, list)) and axis in part:
            return True
    return False


def pipeline_value_and_grad(shared, stages, ids_mb, labels_mb, *, mesh,
                            first_fn, stage_fn, last_fn, stage_specs,
                            pp_axis='pp', dp_axis='dp', tp_axis='tp',
                            ep_axis='ep', with_finite=False):
    """Compute (mean loss, (d_shared, d_stages)) with 1F1B pipelining.

    `with_finite=True` additionally returns a replicated boolean `ok`:
    every microbatch loss was finite (checked PER MICROBATCH inside the
    schedule, on the last stage, at the tick that produced it) AND the
    reduced gradients are finite.  The reduction is folded into the
    same XLA module — nan_guard under pipeline parallelism costs no
    extra dispatch, and only the one boolean crosses to the host.

    shared      : pytree of pp-replicated params (embedding, final LN…).
    stages      : pytree whose leaves are stage-major [S, ...].
    ids_mb      : [M, B_mb, ...] inputs (microbatch-major).
    labels_mb   : [M, B_mb, ...] labels, same layout.
    first_fn(shared, ids_1mb)            -> x0 [mb, ...] float
    stage_fn(shared, stage_p, x, rank)   -> y  (same shape/dtype as x0;
                  rank is the traced pp coordinate — heterogeneous
                  engines lax.switch on it, homogeneous ones ignore it)
    last_fn(shared, y, labels_1mb)       -> scalar per-microbatch loss
    stage_specs : pytree matching `stages` of GLOBAL PartitionSpecs
                  (leading 'pp' + optional 'tp' dims).
    """
    shape = dict(mesh.shape)
    S = shape.get(pp_axis, 1)
    dp = shape.get(dp_axis, 1)
    tp = shape.get(tp_axis, 1)
    ep = shape.get(ep_axis, 1)
    M = ids_mb.shape[0]
    ticks = 2 * M + 2 * S - 2
    perm_dn = [(i, (i + 1) % S) for i in range(S)]   # acts: s -> s+1
    perm_up = [(i, (i - 1) % S) for i in range(S)]   # grads: s -> s-1

    def worker(shared, stages_l, ids, labels):
        # stages_l leaves arrive as [1, ...] local slices — strip pp dim
        stage_p = jax.tree_util.tree_map(lambda a: a[0], stages_l)
        rank = jax.lax.axis_index(pp_axis)
        is_first = rank == 0
        is_last = rank == S - 1

        def full_stage(shared, stage_p, act_in, m):
            """One stage's complete forward for microbatch m: gated
            first_fn on stage 0, blocks, gated last_fn on stage S-1.
            Returns (activation to ship, per-mb loss)."""
            ids_1 = jax.lax.dynamic_index_in_dim(ids, m, 0, keepdims=False)
            lbl_1 = jax.lax.dynamic_index_in_dim(labels, m, 0,
                                                 keepdims=False)
            x = jax.lax.cond(
                is_first,
                lambda: first_fn(shared, ids_1).astype(act_in.dtype),
                lambda: act_in)
            y = stage_fn(shared, stage_p, x, rank)
            loss = jax.lax.cond(
                is_last,
                lambda: last_fn(shared, y, lbl_1).astype(jnp.float32),
                lambda: jnp.float32(0.0))
            return y, loss

        # activation template (shape of what flows between stages)
        x0_shape = jax.eval_shape(
            lambda sh, i: first_fn(sh, i[0]), shared, ids)
        act_zero = jnp.zeros(x0_shape.shape, x0_shape.dtype)
        d_sh0 = jax.tree_util.tree_map(jnp.zeros_like, shared)
        d_st0 = jax.tree_util.tree_map(jnp.zeros_like, stage_p)
        nstash = min(S, M)
        stash0 = jnp.zeros((nstash,) + act_zero.shape, act_zero.dtype)

        def tick(carry, t):
            (act_in, grad_in, stash, d_sh, d_st, loss_acc,
             nbad) = carry
            tf = t - rank
            do_f = (tf >= 0) & (tf < 2 * M) & (tf % 2 == 0)
            m_f = jnp.clip(tf // 2, 0, M - 1)
            tb = t - (2 * S - 1 - rank)
            do_b = (tb >= 0) & (tb < 2 * M) & (tb % 2 == 0)
            m_b = jnp.clip(tb // 2, 0, M - 1)

            def fwd(op):
                act_in, stash, loss_acc, nbad = op
                y, l = full_stage(shared, stage_p, act_in, m_f)
                stash = jax.lax.dynamic_update_index_in_dim(
                    stash, act_in, m_f % nstash, 0)
                # per-microbatch health, folded into the schedule: l
                # is this microbatch's loss on the last stage (0.0 —
                # finite — elsewhere), so nbad counts exactly the
                # non-finite microbatches
                nbad = nbad + (~jnp.isfinite(l)).astype(jnp.int32)
                return y, stash, loss_acc + l, nbad

            act_out, stash, loss_acc, nbad = jax.lax.cond(
                do_f, fwd, lambda op: op,
                (act_in, stash, loss_acc, nbad))

            def bwd(op):
                grad_in, d_sh, d_st = op
                x_saved = jax.lax.dynamic_index_in_dim(
                    stash, m_b % nstash, 0, keepdims=False)
                _, vjp_fn = jax.vjp(
                    lambda sh, sp, a: full_stage(sh, sp, a, m_b),
                    shared, stage_p, x_saved)
                # last stage's shipped activation is unused downstream;
                # its cotangent is zero and the loss seed is 1.0
                dy = jnp.where(is_last, 0.0, 1.0) * grad_in
                dl = jnp.where(is_last, 1.0, 0.0).astype(jnp.float32)
                g_sh, g_st, dx = vjp_fn((dy, dl))
                d_sh = jax.tree_util.tree_map(jnp.add, d_sh, g_sh)
                d_st = jax.tree_util.tree_map(jnp.add, d_st, g_st)
                return dx, d_sh, d_st

            dx_out, d_sh, d_st = jax.lax.cond(
                do_b, bwd, lambda op: op, (grad_in, d_sh, d_st))

            # ring hops: activations ride down, gradients ride up; junk
            # travels on idle edges and is masked by the schedule
            act_nxt = jax.lax.ppermute(act_out, pp_axis, perm_dn)
            grad_nxt = jax.lax.ppermute(dx_out, pp_axis, perm_up)
            return (act_nxt, grad_nxt, stash, d_sh, d_st, loss_acc,
                    nbad), None

        init = (act_zero, act_zero, stash0, d_sh0, d_st0,
                jnp.float32(0.0), jnp.int32(0))
        (_, _, _, d_sh, d_st, loss_acc, nbad), _ = jax.lax.scan(
            tick, init, jnp.arange(ticks))

        # loss lives on stage S-1 only; total over pp, mean over M, dp
        loss = jax.lax.psum(loss_acc, pp_axis) / M
        if dp > 1:
            loss = jax.lax.pmean(loss, dp_axis)
        scale = 1.0 / (M * dp)
        d_sh = jax.tree_util.tree_map(lambda g: g * scale, d_sh)
        d_st = jax.tree_util.tree_map(lambda g: g * scale, d_st)
        # shared params: stage 0 (embedding) and stage S-1 (head)
        # contribute from different pp ranks — total over pp (this is
        # also what ties wte's embedding+head gradients together)
        d_sh = jax.lax.psum(d_sh, pp_axis)
        if dp > 1:
            d_sh = jax.lax.psum(d_sh, dp_axis)
            d_st = jax.lax.psum(d_st, dp_axis)
        # Model-parallel axes (tp: Megatron row/col split; ep: expert
        # shards).  Inside shard_map, the hand-rolled jax.vjp transposes
        # the stage_fn's `lax.psum(..., axis)` back into a psum, so
        # every cotangent strictly upstream of such a psum arrives
        # multiplied by the axis size, and cotangents on residual paths
        # are per-rank partials whose rank-sum is size x the true
        # cotangent (verified empirically vs jax.grad; see
        # tests/test_pipeline.py gradient-parity tests).  Hence, per
        # axis:
        #   - leaves SHARDED on the axis (spec mentions it) sit
        #     upstream of their block's psum: local shard gradient is
        #     exact x size -> divide by size;
        #   - leaves REPLICATED on the axis carry per-rank values whose
        #     sum over the axis is size x the true gradient -> pmean.
        for axis, size in ((tp_axis, tp), (ep_axis, ep)):
            if size <= 1:
                continue
            inv = 1.0 / size
            d_sh = jax.lax.pmean(d_sh, axis)
            d_st = jax.tree_util.tree_map(
                lambda g, spec, a=axis, iv=inv: g * iv
                if _spec_mentions(spec, a)
                else jax.lax.pmean(g, a),
                d_st, stage_specs)
        ok = None
        if with_finite:
            # grad health AFTER all reductions: a NaN/inf anywhere in
            # any rank's shard poisons its local sum of squares; psum
            # over every mesh axis makes the verdict identical on all
            # ranks (so `ok` can be returned replicated)
            leaves = (jax.tree_util.tree_leaves(d_sh)
                      + jax.tree_util.tree_leaves(d_st))
            g2 = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in leaves) if leaves else jnp.zeros(())
            bad = (nbad
                   + (~jnp.isfinite(g2)).astype(jnp.int32)
                   + (~jnp.isfinite(loss)).astype(jnp.int32))
            bad = jax.lax.psum(bad, pp_axis)
            for axis, size in ((dp_axis, dp), (tp_axis, tp),
                               (ep_axis, ep)):
                if size > 1:
                    bad = jax.lax.psum(bad, axis)
            ok = bad == 0
        # re-attach the local pp dim for the out_spec gather
        d_st = jax.tree_util.tree_map(lambda g: g[None], d_st)
        if with_finite:
            return loss, d_sh, d_st, ok
        return loss, d_sh, d_st

    repl = P()
    shared_specs = jax.tree_util.tree_map(lambda _: repl, shared)
    mb_spec = P(None, dp_axis)
    out_stage_specs = stage_specs
    out_specs = (repl, shared_specs, out_stage_specs)
    if with_finite:
        out_specs = out_specs + (repl,)
    from ..core.jaxcompat import shard_map
    out = shard_map(
        worker, mesh=mesh,
        in_specs=(shared_specs, stage_specs, mb_spec, mb_spec),
        out_specs=out_specs,
        check_vma=False)(shared, stages, ids_mb, labels_mb)
    if with_finite:
        loss, d_sh, d_st, ok = out
        return loss, (d_sh, d_st), ok
    loss, d_sh, d_st = out
    return loss, (d_sh, d_st)
