"""ParallelTrainer — ONE jitted SPMD train step over the mesh.

Replaces (TPU-native) the reference's executor pipeline:
ParallelExecutor + fleet meta_optimizer Program rewrites
(/root/reference/paddle/fluid/framework/parallel_executor.cc,
python/paddle/distributed/fleet/meta_optimizers/*).  Where the
reference *rewrites a graph* to insert allreduce/recompute/AMP-cast ops,
here the strategy simply parameterizes how ONE pure function is built
and sharded, and XLA's SPMD partitioner materializes the collectives:

  batch P('dp')          → grads arrive per-shard; psum by partitioner
  params per-layer specs → tp matmul sharding (psum on row outputs)
  opt state on 'dp'      → ZeRO-1: reduce-scatter + sharded update
  strategy.recompute     → jax.checkpoint around the forward
  strategy.gradient_merge→ lax.scan over microbatches inside the step
  strategy.amp           → bf16 auto_cast applied during trace

donate_argnums on (params, opt_state) lets XLA update HBM in place —
peak memory ≈ params + state + activations, like the reference's
in-place optimizer kernels.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..core import rng as rng_mod
from ..distributed import env as _env
from .api import collect_param_shardings, make_spec

__all__ = ['ParallelTrainer']


def _zero_spec(spec, shape, mesh, dp_axis='dp'):
    """ZeRO-1: additionally shard a (replicated-on-dp) state/param leaf
    along dim 0 over dp when divisible."""
    parts = list(make_spec(spec, len(shape), mesh))
    if not shape or dp_axis not in mesh.shape or mesh.shape[dp_axis] <= 1:
        return P(*parts)
    if parts and parts[0] is not None:
        return P(*parts)
    if shape[0] % mesh.shape[dp_axis] == 0:
        parts = [dp_axis] + parts[1:]
    return P(*parts)


class ParallelTrainer:
    """Compile model+optimizer+loss into a sharded train step.

    loss_fn(outputs, *labels) -> scalar Tensor; model outputs are
    Tensors.  Used by hapi.Model.prepare(...) and directly by power
    users (GPT/ERNIE training scripts).
    """

    def __init__(self, model, optimizer, loss_fn, mesh=None, strategy=None,
                 donate=True, n_inputs=1):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.n_inputs = n_inputs  # batch[:n_inputs] feed forward, rest loss
        self.mesh = mesh or _env.get_mesh()
        self.strategy = strategy or getattr(optimizer, '_fleet_strategy',
                                            None)
        self.donate = donate
        self._step_no = 0
        self._compiled = None
        self._eval_compiled = None

        params, buffers = model.functional_state()
        self.param_specs = collect_param_shardings(model)
        self.params = params
        self.buffers = buffers
        self.opt_state = optimizer.init(params)
        if self.mesh is not None:
            self._place_state()
        elif self.donate:
            # device_put would alias the live Parameters' arrays; the
            # donated step would delete them out from under the Layer
            self.params = {n: jnp.array(v, copy=True)
                           for n, v in self.params.items()}
            self.buffers = {n: jnp.array(v, copy=True)
                            for n, v in self.buffers.items()}

    # -- sharding placement --------------------------------------------------
    def _sharding_for(self, name, v, zero=False):
        spec = self.param_specs.get(name)
        if zero:
            return NamedSharding(self.mesh, _zero_spec(spec, v.shape,
                                                       self.mesh))
        return NamedSharding(self.mesh, make_spec(spec, v.ndim, self.mesh))

    def _place_state(self):
        zero = bool(self.strategy and self.strategy.sharding)
        self.params = {n: jax.device_put(v, self._sharding_for(n, v))
                       for n, v in self.params.items()}
        self.opt_state = {
            n: {k: (jax.device_put(s, self._sharding_for(n, s, zero=zero))
                    if hasattr(s, 'shape') and s.shape == self.params[n].shape
                    else s)
                for k, s in st.items()}
            for n, st in self.opt_state.items()}
        self.buffers = {n: jax.device_put(v, NamedSharding(self.mesh, P()))
                        for n, v in self.buffers.items()}

    # -- step builders -------------------------------------------------------
    def _forward_loss(self, params, buffers, key, batch):
        from ..jit import functional_call
        xs, ys = batch[:self.n_inputs], batch[self.n_inputs:]
        amp_on = bool(self.strategy and self.strategy.amp)

        def run(params, xs):
            import contextlib
            from .. import amp as amp_mod
            cm = amp_mod.auto_cast(level='O2' if (
                self.strategy and self.strategy.amp_configs.get(
                    'use_pure_fp16')) else 'O1') if amp_on else \
                contextlib.nullcontext()
            with cm:
                out, new_buffers = functional_call(
                    self.model, params, buffers, xs, key=key,
                    training=True)
            return out, new_buffers

        if self.strategy and self.strategy.recompute:
            run = jax.checkpoint(run)
        out, new_buffers = run(params, xs)
        out_t = jax.tree_util.tree_map(
            lambda v: Tensor._from_value(v), out)
        ys_t = [Tensor._from_value(y) for y in ys]
        from ..core.autograd import no_grad
        with no_grad():
            loss = self.loss_fn(out_t, *ys_t)
        loss_v = loss.value if isinstance(loss, Tensor) else loss
        return loss_v.astype(jnp.float32).mean(), new_buffers

    def _build_step(self):
        opt = self.optimizer
        merge_k = (self.strategy.gradient_merge_configs.get('k_steps', 1)
                   if self.strategy and self.strategy.gradient_merge else 1)

        def train_step(params, buffers, opt_state, step_no, key, *batch):
            if merge_k > 1:
                # microbatch accumulation: batch dim 0 must divide by k
                def body(carry, mb):
                    g_acc, buf = carry
                    (loss, new_buf), g = jax.value_and_grad(
                        self._forward_loss, has_aux=True)(
                            params, buf, key, mb)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    return (g_acc, new_buf), loss
                stacked = tuple(
                    v.reshape((merge_k, v.shape[0] // merge_k) + v.shape[1:])
                    for v in batch)
                zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
                (grads, new_buffers), losses = jax.lax.scan(
                    body, (zeros, buffers), stacked)
                grads = jax.tree_util.tree_map(
                    lambda g: g / merge_k, grads)
                loss = losses.mean()
            else:
                (loss, new_buffers), grads = jax.value_and_grad(
                    self._forward_loss, has_aux=True)(
                        params, buffers, key, batch)
            new_params, new_state = opt.apply_gradients(
                params, grads, opt_state, step_no)
            return new_params, new_buffers, new_state, loss

        kwargs = {}
        if self.mesh is not None:
            repl = NamedSharding(self.mesh, P())
            dp = NamedSharding(
                self.mesh,
                P(('dp',) if 'dp' in self.mesh.shape
                  and self.mesh.shape['dp'] > 1 else None))
            zero = bool(self.strategy and self.strategy.sharding)
            p_sh = {n: self._sharding_for(n, v)
                    for n, v in self.params.items()}
            s_sh = {n: {k: (self._sharding_for(n, s, zero=zero)
                            if hasattr(s, 'shape')
                            and s.shape == self.params[n].shape else repl)
                        for k, s in st.items()}
                    for n, st in self.opt_state.items()}
            b_sh = {n: repl for n in self.buffers}
            kwargs['in_shardings'] = (
                p_sh, b_sh, s_sh, repl, repl) + tuple(
                    dp for _ in range(self._n_batch))
            kwargs['out_shardings'] = (p_sh, b_sh, s_sh, repl)
        if self.donate:
            kwargs['donate_argnums'] = (0, 2)
        return jax.jit(train_step, **kwargs)

    # -- public API ----------------------------------------------------------
    def step(self, *batch):
        """batch: numpy/jax arrays (x, y, ...). Returns python float loss."""
        vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        if self._compiled is None:
            self._n_batch = len(vals)
            self._compiled = self._build_step()
        key = rng_mod.next_key()
        self.params, self.buffers, self.opt_state, loss = self._compiled(
            self.params, self.buffers, self.opt_state,
            jnp.asarray(self._step_no + 1), key, *vals)
        self._step_no += 1
        # LR-scheduler advancement is the caller's job (hapi epoch loop)
        return loss

    def eval_step(self, *batch):
        vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        if self._eval_compiled is None:
            def estep(params, buffers, key, *batch):
                from ..jit import functional_call
                out, _ = functional_call(self.model, params, buffers,
                                         batch[:self.n_inputs], key=key,
                                         training=False)
                out_t = jax.tree_util.tree_map(
                    lambda v: Tensor._from_value(v), out)
                ys_t = [Tensor._from_value(y) for y in batch[self.n_inputs:]]
                from ..core.autograd import no_grad
                with no_grad():
                    loss = self.loss_fn(out_t, *ys_t)
                loss_v = loss.value if isinstance(loss, Tensor) else loss
                return out, loss_v.astype(jnp.float32).mean()
            self._eval_compiled = jax.jit(estep)
        key = rng_mod.next_key()
        return self._eval_compiled(self.params, self.buffers, key, *vals)

    def sync_to_model(self):
        """Write compiled-state params/buffers back into the live Layer
        (for state_dict/save after training).  Copies when donating:
        the next step() would otherwise delete the Layer's arrays."""
        params, buffers = self.params, self.buffers
        if self.donate:
            params = {n: jnp.array(v, copy=True) for n, v in params.items()}
            buffers = {n: jnp.array(v, copy=True)
                       for n, v in buffers.items()}
        self.model.load_functional_state(params, buffers)

    def loss_float(self, loss):
        return float(np.asarray(loss))
